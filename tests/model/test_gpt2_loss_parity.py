"""Model-scale loss-parity suite: GPT-2 training must be numerically
IDENTICAL (within tolerance) across parallelism layouts.

The analog of the reference's Megatron-GPT2 functional suite, which runs
baseline-vs-deepspeed training pairs across mp x zero grids and compares
`LM loss` within relative tolerance (reference:
tests/model/Megatron_GPT2/run_func_test.py:19-120). Here the baseline is a
single-device stage-0 run and every parallel layout — ZeRO-1, ZeRO-2,
ZeRO-2 + tensor parallel, ZeRO-2 + sequence parallel — must reproduce its
loss trajectory on the 8-device virtual mesh: the test that proves the
parallelism stack trains *identically*, not just runs.
"""

import dataclasses

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel, partition_specs
from deepspeed_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

STEPS = 20
BATCH = 8
SEQ = 64
RTOL = 1e-2  # reference uses 0.01 on LM loss (run_func_test.py)


def _cfg(mesh=None, pp=1):
    return GPT2Config(
        vocab_size=512,
        n_positions=SEQ,
        n_embd=128,
        n_layer=2,
        n_head=4,
        dropout=0.0,  # parity runs compare exact trajectories
        mesh=mesh,
        pipeline_stages=pp,
        pipeline_microbatches=2 * pp if pp > 1 else 0,
    )


def _data():
    # two fixed batches cycled so the loss actually decreases (random
    # tokens are memorizable; fresh random data would sit at ln(512))
    rng = np.random.default_rng(1234)
    fixed = [
        rng.integers(0, 512, (BATCH, SEQ)).astype(np.int32) for _ in range(2)
    ]
    return [fixed[i % 2] for i in range(STEPS)]


def _train(mesh, zero_stage, use_mp=False, pp=1):
    cfg = _cfg(mesh=mesh, pp=pp)
    model = GPT2LMHeadModel(cfg)
    ids0 = jax.numpy.asarray(_data()[0])
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids0, ids0,
    )["params"]
    specs = None
    if use_mp or pp > 1:
        # mp sharding of layer weights stays active inside the pipeline's
        # shard_map (model is an auto axis there)
        specs = partition_specs(params, pipeline=pp > 1)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        mesh=mesh,
        param_specs=specs,
        config_params={
            "train_batch_size": BATCH,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": zero_stage},
            "steps_per_print": 10_000,
        },
        rng_seed=0,
    )
    losses = []
    for ids in _data():
        loss = engine(ids, ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert engine.global_steps == STEPS
    return np.asarray(losses)


@pytest.fixture(scope="module")
def baseline_losses():
    mesh = build_mesh(devices=jax.devices()[:1], data_parallel_size=1)
    losses = _train(mesh, zero_stage=0)
    # sanity: the baseline itself must be training
    assert losses[-1] < 0.9 * losses[0], losses
    return losses


PARALLEL_LAYOUTS = {
    "zero1_dp8": dict(dp=8, mp=1, sp=1, pp=1, stage=1),
    "zero2_dp8": dict(dp=8, mp=1, sp=1, pp=1, stage=2),
    "zero2_dp4_mp2": dict(dp=4, mp=2, sp=1, pp=1, stage=2),
    "zero2_dp4_sp2": dict(dp=4, mp=1, sp=2, pp=1, stage=2),
    "zero2_dp4_pp2": dict(dp=4, mp=1, sp=1, pp=2, stage=2),
    "zero2_dp2_mp2_pp2": dict(dp=2, mp=2, sp=1, pp=2, stage=2),
}


@pytest.mark.parametrize("name", sorted(PARALLEL_LAYOUTS))
def test_parallel_layout_matches_baseline(name, baseline_losses):
    lay = PARALLEL_LAYOUTS[name]
    mesh = build_mesh(
        data_parallel_size=lay["dp"],
        model_parallel_size=lay["mp"],
        sequence_parallel_size=lay["sp"],
        pipeline_parallel_size=lay["pp"],
    )
    losses = _train(
        mesh, zero_stage=lay["stage"], use_mp=lay["mp"] > 1, pp=lay["pp"]
    )
    np.testing.assert_allclose(
        losses, baseline_losses, rtol=RTOL,
        err_msg=f"{name} diverged from the single-device baseline",
    )
