"""Opt-in real-data SQuAD v1.1 gate (reference:
tests/model/BingBertSquad/test_e2e_squad.py:53-58 asserts EM 83.98 /
F1 90.71 after fine-tuning from a pretrained checkpoint, ~5 GPU-hours).

Runs only when $SQUAD_DATA_DIR holds train-v1.1.json / dev-v1.1.json /
vocab.txt (no network egress in CI, so this cannot be always-on); the
synthetic distractor gate in test_bert_squad_gate.py is the fallback.
Pretrained weights load from $BERT_CKPT_MSGPACK (this repo's layout) or
$BERT_CKPT_TORCH (a public torch/HF pytorch_model.bin, converted
in-process via tools/import_bert_checkpoint.py) — the full EM/F1
thresholds apply only then (a from-scratch BERT cannot reach them;
without a checkpoint the test asserts the pipeline itself: loss decreases
and the extraction produces non-degenerate spans).
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

DATA_DIR = os.environ.get("SQUAD_DATA_DIR")
needs_data = pytest.mark.skipif(
    not (
        DATA_DIR
        and os.path.exists(os.path.join(DATA_DIR, "train-v1.1.json"))
        and os.path.exists(os.path.join(DATA_DIR, "dev-v1.1.json"))
        and os.path.exists(os.path.join(DATA_DIR, "vocab.txt"))
    ),
    reason="SQUAD_DATA_DIR with train/dev/vocab not provided",
)


@needs_data
def test_squad_v11_real_data_gate():
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import BertConfig, BertForQuestionAnswering
    from tests.model import squad_harness as H

    tok = H.load_tokenizer(DATA_DIR)
    train_ex, _ = H.read_squad(
        os.path.join(DATA_DIR, "train-v1.1.json"), training=True
    )
    dev_ex, dev_raw = H.read_squad(
        os.path.join(DATA_DIR, "dev-v1.1.json"), training=False
    )
    max_train = int(os.environ.get("SQUAD_MAX_TRAIN", "0")) or len(train_ex)
    max_dev = int(os.environ.get("SQUAD_MAX_DEV", "0")) or len(dev_ex)
    train_feats = H.convert_examples(train_ex[:max_train], tok, training=True)
    dev_feats = H.convert_examples(dev_ex[:max_dev], tok, training=False)

    cfg = BertConfig(
        vocab_size=tok.vocab_size, hidden_size=1024, num_hidden_layers=24,
        num_attention_heads=16, intermediate_size=4096,
        max_position_embeddings=512,
    )
    model = BertForQuestionAnswering(cfg)
    f0 = train_feats[0]
    ids0 = jnp.asarray([f0["input_ids"]], jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids0, None, None, jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32),
    )["params"]

    ckpt = os.environ.get("BERT_CKPT_MSGPACK")
    torch_ckpt = os.environ.get("BERT_CKPT_TORCH")
    pretrained = bool(ckpt and os.path.exists(ckpt))
    if pretrained:
        from flax import serialization

        with open(ckpt, "rb") as f:
            params = serialization.from_bytes(params, f.read())
    elif torch_ckpt and os.path.exists(torch_ckpt):
        # public-artifact path: a raw torch/HF BERT checkpoint converts
        # in-process (tools/import_bert_checkpoint.py), so the gate needs
        # nothing beyond the published pytorch_model.bin
        from tools.import_bert_checkpoint import (
            convert_state_dict, load_torch_state_dict,
        )

        imported, _ = convert_state_dict(
            load_torch_state_dict(torch_ckpt), head="qa"
        )
        if "qa_outputs" not in imported:
            imported["qa_outputs"] = params["qa_outputs"]
        params = imported
        pretrained = True

    micro = int(os.environ.get("SQUAD_MICRO", "8"))
    epochs = float(os.environ.get("SQUAD_EPOCHS", "2"))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        config_params={
            "train_batch_size": micro,
            "optimizer": {"type": "Adam", "params": {"lr": 3e-5}},
            "bf16": {"enabled": True},
            "steps_per_print": 200,
        },
    )

    rng = np.random.default_rng(0)
    steps = int(epochs * len(train_feats) / micro)
    first_loss = last_loss = None
    for step in range(steps):
        idx = rng.integers(0, len(train_feats), micro)
        batch = [train_feats[i] for i in idx]
        ids = np.array([f["input_ids"] for f in batch], np.int32)
        tt = np.array([f["token_type_ids"] for f in batch], np.int32)
        am = np.array([f["attention_mask"] for f in batch], np.int32)
        st = np.array([f["start_position"] for f in batch], np.int32)
        en = np.array([f["end_position"] for f in batch], np.int32)
        # BertForQuestionAnswering signature: (input_ids, attention_mask,
        # token_type_ids, start, end) — models/bert.py:219-222
        loss = engine(ids, am, tt, st, en)
        engine.backward(loss)
        engine.step()
        if step == 0:
            first_loss = float(loss)
    last_loss = float(loss)
    assert last_loss < first_loss, (first_loss, last_loss)

    # dev evaluation
    all_s, all_e = [], []
    for i in range(0, len(dev_feats), micro):
        batch = dev_feats[i : i + micro]
        ids = np.array([f["input_ids"] for f in batch], np.int32)
        am = np.array([f["attention_mask"] for f in batch], np.int32)
        tt = np.array([f["token_type_ids"] for f in batch], np.int32)
        s_log, e_log = model.apply(
            {"params": engine.params}, jnp.asarray(ids), jnp.asarray(am),
            jnp.asarray(tt), train=False,
        )
        all_s.extend(np.asarray(s_log, np.float32))
        all_e.extend(np.asarray(e_log, np.float32))
    preds = H.extract_predictions(dev_ex[:max_dev], dev_feats, all_s, all_e)
    scores = H.evaluate_squad(
        [
            {
                "paragraphs": [
                    {"qas": [qa for qa in p["qas"]
                             if qa["id"] in preds]}
                    for p in a["paragraphs"]
                ]
            }
            for a in dev_raw
        ],
        preds,
    )
    print("SQuAD v1.1:", scores)
    if pretrained and not os.environ.get("SQUAD_MAX_TRAIN"):
        # the reference's full gate (test_e2e_squad.py:53-58)
        assert scores["exact_match"] >= 83.98, scores
        assert scores["f1"] >= 90.71, scores
    else:
        # pipeline sanity: extraction must produce real spans
        assert any(p.strip() for p in preds.values())


def test_squad_metric_functions_exact_values():
    """The official-normalization metric math is always tested (no data
    needed): known strings produce known EM/F1."""
    from tests.model import squad_harness as H

    assert H.exact_match_score("The  Cat!", "cat") == 1.0
    assert H.exact_match_score("a dog", "cat") == 0.0
    assert H.f1_score("the big cat", "big cat") == 1.0
    f1 = H.f1_score("big red cat", "big cat")
    assert abs(f1 - 0.8) < 1e-9  # 2*(2/3)*(2/2)/((2/3)+1)
    dataset = [{"paragraphs": [{"qas": [
        {"id": "q1", "answers": [{"text": "big cat"}]},
        {"id": "q2", "answers": [{"text": "dog"}, {"text": "the dog"}]},
    ]}]}]
    scores = H.evaluate_squad(dataset, {"q1": "big cat", "q2": "a dog"})
    assert scores["exact_match"] == 100.0
    assert scores["f1"] == 100.0
