"""Real-data SQuAD v1.1 fine-tune harness (opt-in).

The reference's true quality gate fine-tunes BERT on SQuAD v1.1 and asserts
EM 83.98 / F1 90.71 (reference: tests/model/BingBertSquad/test_e2e_squad.py:
53-58, evaluate-v1.1 metric semantics).  This module reproduces that
pipeline — wordpiece feature conversion with doc-stride windows, engine
fine-tune, span extraction, official normalization for EM/F1 — against
local data, since the environment has no network egress.

Expected layout under ``$SQUAD_DATA_DIR``:
    train-v1.1.json   dev-v1.1.json   vocab.txt
and optionally pretrained weights the caller loads into the engine before
fine-tuning (a from-scratch BERT cannot reach the gate).
"""

import collections
import json
import os
import re
import string


# ----------------------------------------------------------- official metric
def normalize_answer(s):
    """Official SQuAD v1.1 normalization: lower, strip punct/articles/ws."""

    def remove_articles(text):
        return re.sub(r"\b(a|an|the)\b", " ", text)

    def white_space_fix(text):
        return " ".join(text.split())

    def remove_punc(text):
        exclude = set(string.punctuation)
        return "".join(ch for ch in text if ch not in exclude)

    return white_space_fix(remove_articles(remove_punc(s.lower())))


def f1_score(prediction, ground_truth):
    pred_tokens = normalize_answer(prediction).split()
    gt_tokens = normalize_answer(ground_truth).split()
    common = collections.Counter(pred_tokens) & collections.Counter(gt_tokens)
    num_same = sum(common.values())
    if num_same == 0:
        return 0.0
    precision = num_same / len(pred_tokens)
    recall = num_same / len(gt_tokens)
    return 2 * precision * recall / (precision + recall)


def exact_match_score(prediction, ground_truth):
    return float(normalize_answer(prediction) == normalize_answer(ground_truth))


def metric_max_over_ground_truths(metric_fn, prediction, ground_truths):
    return max(metric_fn(prediction, gt) for gt in ground_truths)


def evaluate_squad(dataset, predictions):
    """dataset: parsed dev-v1.1.json["data"]; predictions: {qid: text}.
    Returns {"exact_match": pct, "f1": pct} (evaluate-v1.1.py semantics)."""
    f1 = em = total = 0
    for article in dataset:
        for paragraph in article["paragraphs"]:
            for qa in paragraph["qas"]:
                total += 1
                if qa["id"] not in predictions:
                    continue
                gts = [a["text"] for a in qa["answers"]]
                pred = predictions[qa["id"]]
                em += metric_max_over_ground_truths(exact_match_score, pred, gts)
                f1 += metric_max_over_ground_truths(f1_score, pred, gts)
    return {"exact_match": 100.0 * em / total, "f1": 100.0 * f1 / total}


# -------------------------------------------------------- feature conversion
def load_tokenizer(data_dir):
    from transformers import BertTokenizerFast

    return BertTokenizerFast(
        vocab_file=os.path.join(data_dir, "vocab.txt"), do_lower_case=True
    )


def read_squad(path, training):
    with open(path) as f:
        data = json.load(f)["data"]
    examples = []
    for article in data:
        for paragraph in article["paragraphs"]:
            context = paragraph["context"]
            for qa in paragraph["qas"]:
                ex = {
                    "qid": qa["id"],
                    "question": qa["question"],
                    "context": context,
                }
                if training:
                    a = qa["answers"][0]
                    ex["answer_start"] = a["answer_start"]
                    ex["answer_text"] = a["text"]
                examples.append(ex)
    return examples, data


def convert_examples(examples, tokenizer, max_seq=384, doc_stride=128,
                     max_query=64, training=True):
    """Tokenize question+context into windows (the reference harness's
    convert_examples_to_features contract): returns a list of feature
    dicts with input_ids/token_type_ids/start/end positions and, for eval,
    offset mappings back into the context string."""
    feats = []
    for ex_idx, ex in enumerate(examples):
        enc = tokenizer(
            ex["question"][:512],
            ex["context"],
            truncation="only_second",
            max_length=max_seq,
            stride=doc_stride,
            return_overflowing_tokens=True,
            return_offsets_mapping=True,
            padding="max_length",
        )
        for i in range(len(enc["input_ids"])):
            offsets = enc["offset_mapping"][i]
            type_ids = enc["token_type_ids"][i]
            feat = {
                "ex_idx": ex_idx,
                "qid": ex["qid"],
                "input_ids": enc["input_ids"][i],
                "token_type_ids": type_ids,
                "attention_mask": enc["attention_mask"][i],
                "offsets": offsets,
            }
            if training:
                a0 = ex["answer_start"]
                a1 = a0 + len(ex["answer_text"])
                start = end = 0  # [CLS] = "no answer in this window"
                for t, (o0, o1) in enumerate(offsets):
                    if type_ids[t] != 1:
                        continue
                    if o0 <= a0 < o1:
                        start = t
                    if o0 < a1 <= o1:
                        end = t
                if start == 0 or end == 0 or end < start:
                    start = end = 0
                feat["start_position"] = start
                feat["end_position"] = end
            feats.append(feat)
    return feats


def extract_predictions(examples, feats, all_start_logits, all_end_logits,
                        n_best=20, max_answer_len=30):
    """Argmax-span extraction with the reference's n-best window search."""
    import numpy as np

    by_qid = collections.defaultdict(list)
    for fi, feat in enumerate(feats):
        by_qid[feat["qid"]].append(fi)
    predictions = {}
    for ex in examples:
        best_text, best_score = "", -1e9
        for fi in by_qid[ex["qid"]]:
            feat = feats[fi]
            s_log, e_log = all_start_logits[fi], all_end_logits[fi]
            s_idx = np.argsort(s_log)[-n_best:][::-1]
            e_idx = np.argsort(e_log)[-n_best:][::-1]
            for s in s_idx:
                for e in e_idx:
                    if e < s or e - s + 1 > max_answer_len:
                        continue
                    if feat["token_type_ids"][s] != 1 or feat["token_type_ids"][e] != 1:
                        continue
                    score = s_log[s] + e_log[e]
                    if score > best_score:
                        o0 = feat["offsets"][s][0]
                        o1 = feat["offsets"][e][1]
                        best_score = score
                        best_text = ex["context"][o0:o1]
        predictions[ex["qid"]] = best_text
    return predictions
