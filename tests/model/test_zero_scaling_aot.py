"""ZeRO scaling proof, ahead-of-time: the GPT-2 1.5B training step — which
cannot fit one 16 GB chip (fp32 params+grads+Adam state = 24.8 GB) — must
compile under ZeRO sharding on an 8-device mesh with a per-device footprint
that fits.

This is the scaling claim of the reference's perf harness
(tests/model/Megatron_GPT2/run_perf_test.py: 1.5B across 16 GPUs with
ZeRO-2) validated without hardware: AOT-lower the jitted step against
sharded abstract inputs and read XLA's memory analysis. No 1.5B buffers are
ever materialized — everything runs on ShapeDtypeStructs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime import zero as zero_lib
from deepspeed_tpu.ops.optimizers import Adam

HBM_BYTES = 16e9
N_DEV = 8


@pytest.mark.parametrize("preset,min_params_b", [("xl_1_5b", 1.5)])
def test_zero2_step_shards_within_one_chip(preset, min_params_b):
    mesh = build_mesh(data_parallel_size=N_DEV)
    cfg = getattr(GPT2Config, preset)(
        remat=True, remat_policy="dots_with_no_batch_dims_saveable",
        use_flash=False,  # CPU lowering; kernel choice doesn't move state
        dropout=0.0,
    )
    model = GPT2LMHeadModel(cfg)
    MICRO, SEQ = 8, 1024
    ids_shape = jax.ShapeDtypeStruct((MICRO, SEQ), jnp.int32)

    params_shape = jax.eval_shape(
        lambda rng: model.init(
            {"params": rng}, jnp.zeros((1, SEQ), jnp.int32),
            jnp.zeros((1, SEQ), jnp.int32), train=False,
        )["params"],
        jax.random.PRNGKey(0),
    )
    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params_shape)
    )
    assert n_params >= min_params_b * 1e9

    opt = Adam()
    opt_shape = jax.eval_shape(opt.init, params_shape)

    stage = 2
    param_specs = zero_lib.zero_param_specs(params_shape, N_DEV, stage)
    grad_specs = zero_lib.zero_grad_specs(params_shape, N_DEV, stage)
    optstate_param_specs = zero_lib.zero_optstate_specs(
        params_shape, N_DEV, stage
    )
    param_sh = zero_lib.specs_to_shardings(param_specs, mesh)
    grad_sh = zero_lib.specs_to_shardings(grad_specs, mesh)
    opt_sh = zero_lib.specs_to_shardings(
        zero_lib.optstate_specs_like(opt_shape, optstate_param_specs, params_shape),
        mesh,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    data_sh = NamedSharding(mesh, P("data", None))

    def train_step(params, opt_state, ids):
        def loss_fn(p):
            pc = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), p)
            return model.apply({"params": pc}, ids, ids, train=False)

        grads = jax.grad(loss_fn)(params)
        grads = jax.tree_util.tree_map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g.astype(jnp.float32), s
            ),
            grads, grad_sh,
        )
        new_params, new_opt, _ = opt.apply(params, grads, opt_state, 1e-4)
        new_params = jax.tree_util.tree_map(
            lambda p, s: jax.lax.with_sharding_constraint(p, s),
            new_params, param_sh,
        )
        return new_params, new_opt

    def shaped(tree, shardings):
        return jax.tree_util.tree_map(
            lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
            tree, shardings,
        )

    lowered = jax.jit(
        train_step,
        in_shardings=(param_sh, opt_sh, data_sh),
        out_shardings=(param_sh, opt_sh),
    ).lower(
        shaped(params_shape, param_sh),
        shaped(opt_shape, opt_sh),
        jax.ShapeDtypeStruct(ids_shape.shape, ids_shape.dtype, sharding=data_sh),
    )
    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    if mem is None:
        pytest.skip("backend provides no memory analysis")
    per_device = (
        mem.argument_size_in_bytes / N_DEV
        + mem.temp_size_in_bytes / N_DEV
        + mem.output_size_in_bytes / N_DEV
    )
    # unsharded fp32 state alone is ~25 GB; sharded step must fit one chip
    assert per_device < HBM_BYTES, (
        f"per-device footprint {per_device / 1e9:.1f} GB exceeds HBM"
    )
    # and ZeRO must actually be doing something: the all-device total
    # divided by N must be far below the unsharded state
    unsharded_state = 16 * n_params
    assert per_device < 0.8 * unsharded_state, (per_device, unsharded_state)
