"""ZeRO scaling proofs, ahead-of-time: models that cannot fit one 16 GB
chip must compile under ZeRO sharding with a per-device footprint that
fits — validated from XLA's memory analysis without materializing a byte.

Covers the reference's scaling claims (tests/model/Megatron_GPT2/
run_perf_test.py: GPT-2 1.5B across 16 GPUs with ZeRO-2; the Turing-NLG
17B announcement trained with ZeRO + Megatron MP) on virtual CPU meshes.
``memory_analysis()`` reports PER-DEVICE bytes; arguments + temps bound the
live footprint (outputs alias donated arguments in the real engine step).
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

HBM_BYTES = 16e9
REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


_FOOTPRINT_CACHE = {}


def _aot_footprint(cfg_kwargs, dp, mp, stage, micro, seq=1024):
    """Lower+compile the sharded train step; return (n_params, args+temp
    per-device bytes). Runs in-process on the current (8-device) mesh.

    The step is compiled WITHOUT donation and outputs are excluded from the
    footprint: the real engine's update donates params+opt state
    (runtime/engine.py, donate_argnums), so at runtime outputs alias the
    argument buffers one-for-one (identical tree structure and shardings).
    Compiling WITH donation here would be wrong the other way — this
    backend's memory_analysis folds donated outputs into temps, double
    counting them. Results are memoized per config."""
    key = (tuple(sorted(cfg_kwargs.items())), dp, mp, stage, micro, seq)
    if key in _FOOTPRINT_CACHE:
        return _FOOTPRINT_CACHE[key]
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel, partition_specs
    from deepspeed_tpu.ops.optimizers import Adam
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime import zero as zero_lib
    from jax.sharding import NamedSharding, PartitionSpec as P

    kw = dict(cfg_kwargs)
    policy = kw.pop("remat_policy", "dots_with_no_batch_dims_saveable")
    cfg = GPT2Config(
        dropout=0.0, remat=True,
        remat_policy=policy,
        use_flash=False,  # CPU lowering; kernel choice doesn't move state
        **kw,
    )
    model = GPT2LMHeadModel(cfg)
    mesh = build_mesh(data_parallel_size=dp, model_parallel_size=mp)

    params_shape = jax.eval_shape(
        lambda rng: model.init(
            {"params": rng}, jnp.zeros((1, seq), jnp.int32),
            jnp.zeros((1, seq), jnp.int32), train=False,
        )["params"],
        jax.random.PRNGKey(0),
    )
    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params_shape)
    )
    opt = Adam()
    inner_shape = jax.eval_shape(opt.init, params_shape)
    mp_specs = partition_specs(params_shape) if mp > 1 else None
    param_sh = zero_lib.specs_to_shardings(
        zero_lib.zero_param_specs(params_shape, dp, stage, model_specs=mp_specs),
        mesh,
    )
    grad_sh = zero_lib.specs_to_shardings(
        zero_lib.zero_grad_specs(params_shape, dp, stage, model_specs=mp_specs),
        mesh,
    )
    optstate_param_specs = zero_lib.zero_optstate_specs(
        params_shape, dp, stage, model_specs=mp_specs
    )
    inner_sh = zero_lib.specs_to_shardings(
        zero_lib.optstate_specs_like(
            inner_shape, optstate_param_specs, params_shape
        ),
        mesh,
    )
    # the engine's master-weights layout (runtime/engine.py): params stored
    # bf16 (replicated over dp like the reference's fp16 params), fp32
    # master inside the stage>=1-sharded optimizer state
    bf16_params_shape = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, jnp.bfloat16), params_shape
    )
    opt_shape = {"master": params_shape, "inner": inner_shape}
    opt_sh = {
        "master": zero_lib.specs_to_shardings(optstate_param_specs, mesh),
        "inner": inner_sh,
    }
    data_sh = NamedSharding(mesh, P("data", None))

    def train_step(params, opt_state, ids):
        def loss_fn(p):
            return model.apply({"params": p}, ids, ids, train=False)

        grads = jax.grad(loss_fn)(params)
        grads = jax.tree_util.tree_map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g.astype(jnp.float32), s
            ),
            grads, grad_sh,
        )
        new_master, new_inner, _ = opt.apply(
            opt_state["master"], grads, opt_state["inner"], 1e-4
        )
        new_params = jax.tree_util.tree_map(
            lambda m, s: jax.lax.with_sharding_constraint(
                m.astype(jnp.bfloat16), s
            ),
            new_master, param_sh,
        )
        return new_params, {"master": new_master, "inner": new_inner}

    def shaped(tree, sh):
        return jax.tree_util.tree_map(
            lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
            tree, sh,
        )

    compiled = jax.jit(
        train_step,
        in_shardings=(param_sh, opt_sh, data_sh),
        out_shardings=(param_sh, opt_sh),
    ).lower(
        shaped(bf16_params_shape, param_sh),
        shaped(opt_shape, opt_sh),
        jax.ShapeDtypeStruct((micro, seq), jnp.int32, sharding=data_sh),
    ).compile()
    mem = compiled.memory_analysis()
    if mem is None:
        pytest.skip("backend provides no memory analysis")
    result = (n_params, mem.argument_size_in_bytes + mem.temp_size_in_bytes)
    _FOOTPRINT_CACHE[key] = result
    return result


def test_gpt2_1_5b_zero2_fits_per_chip():
    """The reference's 1.5B perf config, ZeRO-2 over 8 chips: per-device
    footprint must fit although the unsharded fp32 state (~25 GB) cannot."""
    n, per_dev = _aot_footprint(
        dict(n_embd=1600, n_layer=48, n_head=25), dp=8, mp=1, stage=2, micro=8,
    )
    assert n >= 1.5e9
    assert 16 * n > HBM_BYTES  # the unsharded state really doesn't fit
    assert per_dev < HBM_BYTES, f"{per_dev / 1e9:.1f} GB"


def test_gpt2_1_5b_int8_state_shards_over_dp():
    """int8 moment storage composes with ZeRO (round-3 verdict #4): at
    1.5B over dp8 the quantized+compensated optimizer state must occupy
    ~1/8 of its total bytes per chip. Asserted from XLA's AOT memory
    analysis: argument bytes minus the replicated bf16 params leave the
    state, which unsharded would be ~4 bytes/param (int8 mu + bf16 nu +
    int8 comp) and sharded must come out near 4/8 = 0.5 bytes/param."""
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.ops.optimizers import Adam
    from deepspeed_tpu.ops.quant import is_quantized
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime import zero as zero_lib
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp, stage, micro, seq = 8, 2, 8, 1024
    cfg = GPT2Config(
        n_embd=1600, n_layer=48, n_head=25, dropout=0.0, remat=True,
        remat_policy="dots_with_no_batch_dims_saveable", use_flash=False,
    )
    model = GPT2LMHeadModel(cfg)
    mesh = build_mesh(data_parallel_size=dp)
    params_shape = jax.eval_shape(
        lambda rng: model.init(
            {"params": rng}, jnp.zeros((1, seq), jnp.int32),
            jnp.zeros((1, seq), jnp.int32), train=False,
        )["params"],
        jax.random.PRNGKey(0),
    )
    n = sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params_shape)
    )
    bf16_params_shape = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, jnp.bfloat16), params_shape
    )
    # mirror the engine's ZeRO settings (runtime/engine.py): dp-independent
    # pad multiple, chunking disabled (it is a single-chip measure; under
    # sharding the chunk scan would force GSPMD to gather the flat leaves)
    opt = Adam(
        state_dtype="int8", state_pad_blocks=max(256, dp),
        master_compensation=True, chunk_elements=1 << 62,
    )
    inner_shape = jax.eval_shape(opt.init, bf16_params_shape)
    optstate_param_specs = zero_lib.zero_optstate_specs(
        params_shape, dp, stage
    )
    inner_specs = zero_lib.optstate_specs_like(
        inner_shape, optstate_param_specs, params_shape, dp_size=dp
    )
    # every quantized leaf's q AND scale shard over the data axis
    flat = jax.tree_util.tree_leaves_with_path(
        inner_shape["mu"], is_leaf=is_quantized
    )
    specs_flat = jax.tree_util.tree_leaves_with_path(
        inner_specs["mu"], is_leaf=lambda x: isinstance(x, P)
    )
    spec_by_path = {tuple(str(k) for k in p): s for p, s in specs_flat}
    nq = 0
    for path, leaf in flat:
        if not is_quantized(leaf):
            continue
        pq = spec_by_path[tuple(str(k) for k in path) + ("['q']",)]
        ps = spec_by_path[tuple(str(k) for k in path) + ("['scale']",)]
        assert pq == P("data"), (path, pq)
        assert ps == P("data"), (path, ps)
        nq += 1
    assert nq > 0

    inner_sh = zero_lib.specs_to_shardings(inner_specs, mesh)
    param_sh = zero_lib.specs_to_shardings(
        zero_lib.zero_param_specs(params_shape, dp, stage), mesh
    )
    grad_sh = zero_lib.specs_to_shardings(
        zero_lib.zero_grad_specs(params_shape, dp, stage), mesh
    )
    data_sh = NamedSharding(mesh, P("data", None))

    def train_step(params, inner, ids):
        def loss_fn(p):
            return model.apply({"params": p}, ids, ids, train=False)

        grads = jax.grad(loss_fn)(params)
        grads = jax.tree_util.tree_map(
            lambda g, s: jax.lax.with_sharding_constraint(g, s),
            grads, grad_sh,
        )
        new_params, new_inner, _ = opt.apply(params, grads, inner, 1e-4)
        new_params = jax.tree_util.tree_map(
            lambda m, s: jax.lax.with_sharding_constraint(m, s),
            new_params, param_sh,
        )
        return new_params, new_inner

    def shaped(tree, sh):
        return jax.tree_util.tree_map(
            lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
            tree, sh,
        )

    compiled = jax.jit(
        train_step,
        in_shardings=(param_sh, inner_sh, data_sh),
        out_shardings=(param_sh, inner_sh),
    ).lower(
        shaped(bf16_params_shape, param_sh),
        shaped(inner_shape, inner_sh),
        jax.ShapeDtypeStruct((micro, seq), jnp.int32, sharding=data_sh),
    ).compile()
    mem = compiled.memory_analysis()
    if mem is None:
        pytest.skip("backend provides no memory analysis")
    # replicated bf16 params = 2 bytes/param per chip; everything else in
    # the arguments is optimizer state (+ the tiny ids). Unsharded state
    # is ~4 bytes/param (int8 q + scale + bf16 nu + int8 comp); sharded it
    # must land near 4/8 = 0.5 — well under the 0.8 bound, and nowhere
    # near the 4.0 replication would cost.
    state_bytes = mem.argument_size_in_bytes - 2 * n
    assert state_bytes < 0.8 * n, f"{state_bytes / n:.2f} bytes/param"
    assert mem.argument_size_in_bytes + mem.temp_size_in_bytes < HBM_BYTES


def test_gpt2_1_5b_zero3_shards_params_too():
    """Stage 3 (beyond the reference) additionally shards parameters: the
    per-device footprint must drop well below stage 2's."""
    n, s2 = _aot_footprint(
        dict(n_embd=1600, n_layer=48, n_head=25), dp=8, mp=1, stage=2, micro=8,
    )
    _, s3 = _aot_footprint(
        dict(n_embd=1600, n_layer=48, n_head=25), dp=8, mp=1, stage=3, micro=8,
    )
    assert s3 < 0.65 * s2, (s3 / 1e9, s2 / 1e9)


GPT4B_SNIPPET = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
sys.path.insert(0, {repo!r})
sys.path.insert(0, {repo!r} + "/tests")
import jax
jax.config.update("jax_platforms", "cpu")
from model.test_zero_scaling_aot import _aot_footprint, HBM_BYTES
n, per_dev = _aot_footprint(
    dict(n_embd=2304, n_layer=64, n_head=24), dp=4, mp=4, stage=2, micro=4,
)
assert n >= 4e9, n
assert per_dev < HBM_BYTES, per_dev
print(f"GPT4B_OK {{n}} {{per_dev}}")
"""


def test_gpt2_4b_zero2_mp4_fits_per_chip_on_16_devices():
    """The reference perf ladder's 4B config (64L/2304h,
    run_perf_test.py:36-46) over 16 devices, ZeRO-2 x mp4: measured
    8.8 GB/chip."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c", GPT4B_SNIPPET.format(repo=REPO)],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "GPT4B_OK" in proc.stdout


GPT8B_SNIPPET = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
sys.path.insert(0, {repo!r})
sys.path.insert(0, {repo!r} + "/tests")
import jax
jax.config.update("jax_platforms", "cpu")
from model.test_zero_scaling_aot import _aot_footprint, HBM_BYTES
n, per_dev = _aot_footprint(
    dict(n_embd=3072, n_layer=72, n_head=24, remat_policy="full"),
    dp=4, mp=4, stage=3, micro=4,
)
assert n >= 8e9, n
assert per_dev < HBM_BYTES, per_dev
print(f"GPT8B_OK {{n}} {{per_dev}}")
"""


def test_gpt2_8b_zero3_mp4_fits_per_chip_on_16_devices():
    """The reference perf ladder's LARGEST config (8B: 72L/3072h,
    run_perf_test.py:47-60) over 16 devices — the full perf-harness model
    family is now AOT-proved per chip. The reference ran it mp2/ZeRO-2 on
    32 GB V100s; 16 GB chips need ZeRO-3 (params sharded too — beyond the
    reference) x mp4 with full remat."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c", GPT8B_SNIPPET.format(repo=REPO)],
        env=env, capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "GPT8B_OK" in proc.stdout


TURING_SNIPPET = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=128"
sys.path.insert(0, {repo!r})
sys.path.insert(0, {repo!r} + "/tests")
import jax
jax.config.update("jax_platforms", "cpu")
from model.test_zero_scaling_aot import _aot_footprint, HBM_BYTES
n, per_dev = _aot_footprint(
    dict(n_embd=4256, n_layer=78, n_head=28), dp=16, mp=8, stage=2, micro=16,
)
assert n >= 17e9, n
assert per_dev < HBM_BYTES, per_dev
print(f"TURING17B_OK {{n}} {{per_dev}}")
"""


def test_turing_17b_zero2_mp8_fits_per_chip_on_128_devices():
    """Turing-NLG-scale 17B, ZeRO-2 x Megatron-MP8 over 128 devices (the
    BASELINE 'v5p-128' config): needs its own 128-device interpreter."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c", TURING_SNIPPET.format(repo=REPO)],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "TURING17B_OK" in proc.stdout
