"""Fine-tune quality gate: BERT extractive QA must reach an exact-match
threshold after fine-tuning through the engine.

The scaled-down analog of the reference's BingBertSquad e2e gate, which
fine-tunes on SQuAD v1.1 and asserts EM 83.98 / F1 90.71 after ~5 GPU-hours
(reference: tests/model/BingBertSquad/test_e2e_squad.py:53-58). Here the
task is synthetic extractive QA — the answer span is delimited by sentinel
tokens the model must locate — so the same train-to-quality contract runs
in seconds: engine fine-tune -> argmax span -> EM >= 0.9.
"""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import BertConfig, BertForQuestionAnswering

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

VOCAB, SEQ = 64, 64
START_TOK, END_TOK = 2, 3


def _make_batch(rng, n):
    ids = rng.integers(4, VOCAB, (n, SEQ)).astype(np.int32)
    starts = rng.integers(1, SEQ - 6, n).astype(np.int32)
    ends = (starts + 1 + rng.integers(1, 4, n)).astype(np.int32)
    for i in range(n):
        ids[i, starts[i]] = START_TOK
        ids[i, ends[i]] = END_TOK
    return ids, starts, ends


def test_qa_finetune_reaches_exact_match_gate():
    cfg = BertConfig(
        vocab_size=VOCAB, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=SEQ, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    model = BertForQuestionAnswering(cfg)
    rng = np.random.default_rng(0)
    ids0, s0, e0 = _make_batch(rng, 4)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.asarray(ids0), None, None, jnp.asarray(s0), jnp.asarray(e0),
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        config_params={
            "train_batch_size": 32,
            "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
            "steps_per_print": 10_000,
        },
    )
    for _ in range(80):
        ids, starts, ends = _make_batch(rng, 32)
        loss = engine(ids, None, None, starts, ends)
        engine.backward(loss)
        engine.step()

    # held-out evaluation: exact match of the argmax span
    ids, starts, ends = _make_batch(np.random.default_rng(999), 64)
    start_logits, end_logits = model.apply(
        {"params": engine.params}, jnp.asarray(ids), train=False
    )
    pred_s = np.asarray(jnp.argmax(start_logits, axis=-1))
    pred_e = np.asarray(jnp.argmax(end_logits, axis=-1))
    em = float(np.mean((pred_s == starts) & (pred_e == ends)))
    assert em >= 0.9, f"exact match {em:.2f} below the 0.9 gate"
