"""Fine-tune quality gate: BERT extractive QA must reach an exact-match
threshold after fine-tuning through the engine.

The scaled-down analog of the reference's BingBertSquad e2e gate, which
fine-tunes on SQuAD v1.1 and asserts EM 83.98 / F1 90.71 after ~5 GPU-hours
(reference: tests/model/BingBertSquad/test_e2e_squad.py:53-58).

Two tiers:
  * synthetic (always runs): key-query span selection with DISTRACTOR
    spans — the sequence holds several key-marked candidate spans and a
    question token selects which one is the answer, so locating the span
    requires relating the question to the right key through attention
    (a sentinel-detector or broken attention mask fails it).
  * real data (opt-in): when SQUAD_DATA_DIR points at SQuAD v1.1 files,
    tests/model/squad_harness.py runs the true fine-tune + EM/F1 gate.
"""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import BertConfig, BertForQuestionAnswering

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

VOCAB, SEQ = 64, 32
N_KEYS = 3          # candidate-span markers (tokens 4..6)
SPAN_LEN = 3        # value tokens after each key
KEY0, FILLER0 = 4, 4 + N_KEYS


def _make_batch(rng, n):
    """Each row: position 0 carries the QUESTION key; the context holds
    N_KEYS candidate spans, each introduced by a distinct key token and
    followed by SPAN_LEN value tokens.  The answer is the span whose key
    matches the question — every other span is a distractor, and no
    sentinel marks the answer itself."""
    ids = rng.integers(FILLER0, VOCAB, (n, SEQ)).astype(np.int32)
    starts = np.zeros(n, np.int32)
    ends = np.zeros(n, np.int32)
    slot_w = (SEQ - 2) // N_KEYS
    for i in range(n):
        keys = rng.permutation(N_KEYS)
        q = rng.integers(0, N_KEYS)
        ids[i, 0] = KEY0 + q
        for j, k in enumerate(keys):
            # one key+span per slot, jittered so position alone can't
            # memorize the answer
            pos = 1 + j * slot_w + rng.integers(0, slot_w - SPAN_LEN - 1)
            ids[i, pos] = KEY0 + k
            if k == q:
                starts[i] = pos + 1
                ends[i] = pos + SPAN_LEN
    return ids, starts, ends


def test_qa_finetune_reaches_exact_match_gate():
    cfg = BertConfig(
        vocab_size=VOCAB, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=SEQ, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    model = BertForQuestionAnswering(cfg)
    rng = np.random.default_rng(0)
    ids0, s0, e0 = _make_batch(rng, 4)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.asarray(ids0), None, None, jnp.asarray(s0), jnp.asarray(e0),
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        config_params={
            "train_batch_size": 64,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 10_000,
        },
    )
    # measured EM trajectory for this recipe: 0.16@300, 0.77@450,
    # 0.95@600, 0.98@900 — gate at 0.9 with margin
    for _ in range(900):
        ids, starts, ends = _make_batch(rng, 64)
        loss = engine(ids, None, None, starts, ends)
        engine.backward(loss)
        engine.step()

    # held-out evaluation: exact match of the argmax span
    ids, starts, ends = _make_batch(np.random.default_rng(999), 64)
    start_logits, end_logits = model.apply(
        {"params": engine.params}, jnp.asarray(ids), train=False
    )
    pred_s = np.asarray(jnp.argmax(start_logits, axis=-1))
    pred_e = np.asarray(jnp.argmax(end_logits, axis=-1))
    em = float(np.mean((pred_s == starts) & (pred_e == ends)))
    assert em >= 0.9, f"exact match {em:.2f} below the 0.9 gate"


def test_qa_finetune_from_imported_checkpoint_reaches_gate(tmp_path):
    """The real-data SQuAD gate's weight path, end to end on synthetic data
    (no-egress analog of the reference's pretrained-BERT fine-tune,
    tests/model/BingBertSquad/test_e2e_squad.py:40-58): a torch/HF
    checkpoint saved by ``torch.save`` -> tools/import_bert_checkpoint
    conversion -> msgpack artifact -> ``$BERT_CKPT_MSGPACK``-style reload
    into the flax template -> engine fine-tune -> exact-match gate. Any
    transposition, padding, or serialization bug upstream of training
    makes the gate unreachable."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from flax import serialization

    from tools.import_bert_checkpoint import (
        convert_state_dict,
        load_torch_state_dict,
    )

    hf_cfg = transformers.BertConfig(
        vocab_size=VOCAB, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=SEQ, type_vocab_size=2,
        hidden_act="gelu_new", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
        # HF's default std-0.02 init is tuned for pretraining at full
        # scale; at this toy scale attention stays uniform and training
        # plateaus question-blind at exactly ln(3) loss (measured: EM 0.09
        # after 900 steps). The importer path, not HF's init scale, is
        # under test — 0.1 matches the trainable scale of the random-init
        # gate above (measured: loss 3.66 -> 4e-4, EM 1.0).
        initializer_range=0.1,
    )
    torch.manual_seed(0)
    hf_model = transformers.BertForQuestionAnswering(hf_cfg)
    ckpt_bin = tmp_path / "pytorch_model.bin"
    torch.save(hf_model.state_dict(), ckpt_bin)

    imported, _ = convert_state_dict(
        load_torch_state_dict(str(ckpt_bin)), head="qa"
    )
    msgpack_path = tmp_path / "bert_tiny.msgpack"
    msgpack_path.write_bytes(serialization.to_bytes(imported))

    cfg = BertConfig(
        vocab_size=VOCAB, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=SEQ, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    model = BertForQuestionAnswering(cfg)
    rng = np.random.default_rng(0)
    ids0, s0, e0 = _make_batch(rng, 4)
    template = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.asarray(ids0), None, None, jnp.asarray(s0), jnp.asarray(e0),
    )["params"]
    # the $BERT_CKPT_MSGPACK load path of tests/model/test_squad_real_data
    params = serialization.from_bytes(template, msgpack_path.read_bytes())

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        config_params={
            "train_batch_size": 64,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 10_000,
        },
    )
    for _ in range(900):
        ids, starts, ends = _make_batch(rng, 64)
        loss = engine(ids, None, None, starts, ends)
        engine.backward(loss)
        engine.step()

    ids, starts, ends = _make_batch(np.random.default_rng(999), 64)
    start_logits, end_logits = model.apply(
        {"params": engine.params}, jnp.asarray(ids), train=False
    )
    pred_s = np.asarray(jnp.argmax(start_logits, axis=-1))
    pred_e = np.asarray(jnp.argmax(end_logits, axis=-1))
    em = float(np.mean((pred_s == starts) & (pred_e == ends)))
    assert em >= 0.9, f"exact match {em:.2f} below the 0.9 gate"


def test_qa_gate_fails_without_attention_to_question():
    """The distractor design must actually require the question token:
    a majority-class predictor (or one ignoring position 0) cannot reach
    the gate, because the answer key is uniform over N_KEYS slots."""
    rng = np.random.default_rng(1)
    ids, starts, ends = _make_batch(rng, 256)
    # best question-blind strategy: always predict the most common slot
    slot_w = (SEQ - 2) // N_KEYS
    slots = (starts - 1) // slot_w
    best_blind = max(np.mean(slots == j) for j in range(N_KEYS))
    assert best_blind < 0.5, "distractors leave a question-blind shortcut"
