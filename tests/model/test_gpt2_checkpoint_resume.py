"""Model-scale checkpoint-resume suite: save mid-training, resume in a
FRESH engine, and the loss trajectory must continue as if uninterrupted.

The analog of the reference's Megatron-GPT2 checkpoint suite
(reference: tests/model/Megatron_GPT2/run_checkpoint_test.py), which runs
a training job, saves, resumes, and compares `LM loss` after resume
against the unbroken run. Two scenarios:

1. same-layout resume (dp=8 ZeRO-2 -> dp=8 ZeRO-2): continuation must be
   numerically identical (the fresh engine starts from random params, so
   a match proves module + optimizer + scaler + counter restore).
2. elastic resume (dp=8 ZeRO-2 -> dp=4 x mp=2 ZeRO-2): the saved
   optimizer shards are merged and resharded for the new layout
   (reference: deepspeed_zero_optimizer.py:1483-1538); the trajectory
   must continue within the functional-suite tolerance.
"""

import dataclasses

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel, partition_specs
from deepspeed_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

STEPS_BEFORE = 10
STEPS_AFTER = 10
BATCH = 8
SEQ = 64
RTOL = 1e-2  # functional-suite tolerance (run_func_test.py uses 0.01)


def _cfg(mesh=None):
    return GPT2Config(
        vocab_size=512,
        n_positions=SEQ,
        n_embd=128,
        n_layer=2,
        n_head=4,
        dropout=0.0,  # resume comparisons need deterministic trajectories
        mesh=mesh,
    )


def _data(n_steps, offset=0):
    rng = np.random.default_rng(1234)
    fixed = [
        rng.integers(0, 512, (BATCH, SEQ)).astype(np.int32) for _ in range(2)
    ]
    return [fixed[(offset + i) % 2] for i in range(n_steps)]


def _make_engine(mesh, use_mp, init_seed=0):
    cfg = _cfg(mesh=mesh)
    model = GPT2LMHeadModel(cfg)
    ids0 = jax.numpy.asarray(_data(1)[0])
    params = model.init(
        {"params": jax.random.PRNGKey(init_seed),
         "dropout": jax.random.PRNGKey(init_seed + 1)},
        ids0, ids0,
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        mesh=mesh,
        param_specs=partition_specs(params) if use_mp else None,
        config_params={
            "train_batch_size": BATCH,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10_000,
        },
        rng_seed=0,
    )
    return engine


def _run(engine, batches):
    losses = []
    for ids in batches:
        loss = engine(ids, ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return np.asarray(losses)


@pytest.fixture(scope="module")
def unbroken_losses():
    mesh = build_mesh(data_parallel_size=8)
    engine = _make_engine(mesh, use_mp=False)
    losses = _run(engine, _data(STEPS_BEFORE + STEPS_AFTER))
    assert losses[-1] < 0.9 * losses[0], losses
    return losses


@pytest.fixture(scope="module")
def saved_checkpoint(tmp_path_factory, unbroken_losses):
    """Train the first half under dp=8 ZeRO-2 and save."""
    ckpt_dir = str(tmp_path_factory.mktemp("gpt2_ckpt"))
    mesh = build_mesh(data_parallel_size=8)
    engine = _make_engine(mesh, use_mp=False)
    losses = _run(engine, _data(STEPS_BEFORE))
    np.testing.assert_allclose(
        losses, unbroken_losses[:STEPS_BEFORE], rtol=1e-6,
        err_msg="pre-save trajectory deviates from the unbroken run",
    )
    engine.save_checkpoint(ckpt_dir, tag="mid", client_state={"note": "t10"})
    return ckpt_dir


def test_same_layout_resume_continues_trajectory(
    saved_checkpoint, unbroken_losses
):
    mesh = build_mesh(data_parallel_size=8)
    # fresh engine, DIFFERENT init seed: only a full restore can match
    engine = _make_engine(mesh, use_mp=False, init_seed=7)
    path, client_state = engine.load_checkpoint(saved_checkpoint, tag="mid")
    assert path is not None
    assert client_state == {"note": "t10"}
    assert engine.global_steps == STEPS_BEFORE
    losses = _run(engine, _data(STEPS_AFTER, offset=STEPS_BEFORE))
    np.testing.assert_allclose(
        losses, unbroken_losses[STEPS_BEFORE:], rtol=1e-5,
        err_msg="same-layout resume diverged from the unbroken run",
    )


def test_cross_stack_resume_scanned_to_pipelined(
    saved_checkpoint, unbroken_losses
):
    """The pipelined stack's param tree is identical to the scanned one, so
    a checkpoint saved under dp=8 (scanned) resumes under pipe=2 x dp=4
    (GPipe) and continues the trajectory — elastic across parallelism
    STRATEGIES, not just sizes."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.models import partition_specs as pspecs

    mesh = build_mesh(data_parallel_size=4, pipeline_parallel_size=2)
    cfg = dataclasses.replace(
        _cfg(mesh=mesh), pipeline_stages=2, pipeline_microbatches=4
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jax.numpy.asarray(_data(1)[0])
    params = model.init(
        {"params": jax.random.PRNGKey(9)}, ids0, ids0, train=False
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        mesh=mesh,
        param_specs=pspecs(params, pipeline=True),
        config_params={
            "train_batch_size": BATCH,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10_000,
        },
        rng_seed=0,
    )
    path, _ = engine.load_checkpoint(saved_checkpoint, tag="mid")
    assert path is not None
    assert engine.global_steps == STEPS_BEFORE
    losses = _run(engine, _data(STEPS_AFTER, offset=STEPS_BEFORE))
    np.testing.assert_allclose(
        losses, unbroken_losses[STEPS_BEFORE:], rtol=RTOL,
        err_msg="scanned->pipelined resume diverged from the unbroken run",
    )


def test_elastic_resume_dp8_to_dp4_mp2(saved_checkpoint, unbroken_losses):
    mesh = build_mesh(data_parallel_size=4, model_parallel_size=2)
    engine = _make_engine(mesh, use_mp=True, init_seed=7)
    path, _ = engine.load_checkpoint(saved_checkpoint, tag="mid")
    assert path is not None
    assert engine.global_steps == STEPS_BEFORE
    losses = _run(engine, _data(STEPS_AFTER, offset=STEPS_BEFORE))
    np.testing.assert_allclose(
        losses, unbroken_losses[STEPS_BEFORE:], rtol=RTOL,
        err_msg="elastic dp8->dp4xmp2 resume diverged from the unbroken run",
    )
