"""ZeRO-Offload analog: fp32 master + moments on the host cpu device
(`zero_optimization.offload_optimizer: {"device": "cpu"}`).

On tunneled TPU setups this trades step time for HBM (docs/memory.md
recommends compensated masters there); the SEMANTICS pinned here: state
placement on the cpu device, numerics identical to the on-accelerator
master path, exact checkpoint resume, overflow-skip intact.
"""

import flax.linen as nn
import pytest

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedConfigError
from deepspeed_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, y, train=True):
        h = nn.relu(nn.Dense(32)(x))
        logp = jax.nn.log_softmax(nn.Dense(4)(h))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))


def _data(seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int32) + 2 * (X[:, 1] > 0).astype(np.int32)
    return X, Y


def _engine(offload, seed=0, dp=8):
    X, Y = _data()
    model = MLP()
    params = model.init(
        {"params": jax.random.PRNGKey(seed)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]
    zero = {"stage": 2}
    if offload:
        zero["offload_optimizer"] = {"device": "cpu"}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        mesh=build_mesh(data_parallel_size=dp),
        config_params={
            "train_batch_size": 16,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": zero,
            "steps_per_print": 10_000,
        },
        rng_seed=0,
    )
    return engine


def _train(engine, steps=10):
    X, Y = _data()
    out = []
    for _ in range(steps):
        loss = engine(X, Y)
        engine.backward(loss)
        engine.step()
        out.append(float(loss))
    return np.asarray(out)


def test_offload_state_lives_on_host():
    engine = _engine(offload=True)
    assert engine.host_offload and engine.master_in_opt
    cpu = jax.devices("cpu")[0]
    for leaf in jax.tree_util.tree_leaves(engine.optimizer_state):
        assert leaf.devices() == {cpu}, leaf.devices()
    masters = jax.tree_util.tree_leaves(engine.optimizer_state["master"])
    assert all(m.dtype == jnp.float32 for m in masters)
    # accelerator-side params stay in the compute dtype
    for leaf in jax.tree_util.tree_leaves(engine.params):
        assert leaf.dtype == engine.compute_dtype


def test_offload_matches_on_device_master_numerics():
    """Moving the master to the host must not change a single step (same
    fp32 math, same bf16 publish) — the ZeRO master placement contract."""
    on_dev = _train(_engine(offload=False))
    off = _train(_engine(offload=True))
    np.testing.assert_array_equal(on_dev, off)
    assert off[-1] < 0.5 * off[0]


def test_offload_train_batch_path():
    engine = _engine(offload=True)
    X, Y = _data()
    accum = engine.gradient_accumulation_steps()
    losses = [
        float(engine.train_batch(iter([(X, Y)] * accum))) for _ in range(8)
    ]
    assert losses[-1] < losses[0]
    assert engine.global_steps == 8


def test_offload_checkpoint_resume_exact(tmp_path):
    engine = _engine(offload=True)
    _train(engine, steps=6)
    engine.save_checkpoint(str(tmp_path), tag="t")
    cont = _train(engine, steps=6)

    fresh = _engine(offload=True, seed=7)
    fresh.load_checkpoint(str(tmp_path), tag="t")
    # restored state must land back on the host
    cpu = jax.devices("cpu")[0]
    for leaf in jax.tree_util.tree_leaves(fresh.optimizer_state):
        assert leaf.devices() == {cpu}
    resumed = _train(fresh, steps=6)
    np.testing.assert_allclose(resumed, cont, rtol=1e-6)


def test_offload_rejects_compensated_combo():
    X, Y = _data()
    model = MLP()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]
    with pytest.raises(DeepSpeedConfigError, match="offload"):
        deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            mesh=build_mesh(data_parallel_size=8),
            config_params={
                "train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "bf16": {"enabled": True},
                "zero_optimization": {
                    "stage": 2, "offload_optimizer": {"device": "cpu"},
                },
                "data_types": {"master_dtype": "compensated"},
            },
        )


def test_offload_config_validation():
    from deepspeed_tpu.config.zero_config import DeepSpeedZeroConfig

    cfg = DeepSpeedZeroConfig(
        {"zero_optimization": {"stage": 2,
                               "offload_optimizer": {"device": "cpu"}}}
    )
    assert cfg.offload_optimizer_device == "cpu"
    assert DeepSpeedZeroConfig(
        {"zero_optimization": {"stage": 2}}
    ).offload_optimizer_device == "none"
    with pytest.raises(ValueError, match="offload_optimizer"):
        DeepSpeedZeroConfig(
            {"zero_optimization": {"offload_optimizer": {"device": "nvme"}}}
        )
    # a block WITHOUT an explicit device (e.g. a ported config carrying
    # only pin_memory) must not silently enable offload — upstream's
    # device default is 'none'
    assert DeepSpeedZeroConfig(
        {"zero_optimization": {"offload_optimizer": {"pin_memory": True}}}
    ).offload_optimizer_device == "none"
