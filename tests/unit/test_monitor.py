"""Monitoring: scalar event streams + engine tensorboard wiring."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils.monitor import JsonlSummaryWriter, Monitor


def test_jsonl_writer_roundtrip(tmp_path):
    w = JsonlSummaryWriter(str(tmp_path / "tb"))
    w.add_scalar("Train/loss", 1.5, global_step=3)
    w.add_scalar("Train/lr", 0.01, global_step=3)
    w.flush()
    w.close()
    lines = [
        json.loads(l)
        for l in open(tmp_path / "tb" / "events.jsonl").read().splitlines()
    ]
    assert lines[0]["tag"] == "Train/loss" and lines[0]["value"] == 1.5
    assert lines[1]["step"] == 3


def test_jsonl_writer_nonfinite_values_stay_rfc_json(tmp_path):
    """json.dumps would emit bare NaN/Infinity (valid Python, not RFC 8259
    JSON); non-finite scalars must serialize as null + finite:false so
    strict downstream parsers survive a loss spike."""
    w = JsonlSummaryWriter(str(tmp_path / "tb"))
    w.add_scalar("Train/loss", float("nan"), global_step=1)
    w.add_scalar("Train/grad_norm", float("inf"), global_step=1)
    w.add_scalar("Train/lr", 0.5, global_step=1)
    w.close()
    raw = open(tmp_path / "tb" / "events.jsonl").read()
    lines = [
        # parse_constant trips on any bare NaN/Infinity token
        json.loads(l, parse_constant=lambda s: pytest.fail(f"non-RFC: {s}"))
        for l in raw.splitlines()
    ]
    assert lines[0]["value"] is None and lines[0]["finite"] is False
    assert lines[1]["value"] is None and lines[1]["finite"] is False
    assert lines[2]["value"] == 0.5 and "finite" not in lines[2]


def test_monitor_disabled_is_noop():
    m = Monitor(enabled=False)
    m.write_scalars({"a": 1.0}, 1)  # must not raise
    m.close()


def test_engine_writes_events(tmp_path):
    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            pred = nn.Dense(1)(x)
            return jnp.mean((pred[:, 0] - y) ** 2)

    m = M()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    y = rng.standard_normal((8,)).astype(np.float32)
    params = m.init(jax.random.PRNGKey(0), x[:2], y[:2])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m, model_parameters=params,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "steps_per_print": 1000,
            "tensorboard": {
                "enabled": True,
                "output_path": str(tmp_path),
                "job_name": "job",
            },
        },
    )
    for _ in range(3):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.monitor.close()
    # either torch tensorboard event files or the jsonl fallback must exist
    job_dir = tmp_path / "job"
    assert job_dir.exists()
    contents = os.listdir(job_dir)
    assert contents, "no event files written"
    if "events.jsonl" in contents:
        lines = [
            json.loads(l)
            for l in open(job_dir / "events.jsonl").read().splitlines()
        ]
        tags = {l["tag"] for l in lines}
        assert {"Train/lr", "Train/loss", "Train/loss_scale"} <= tags


def test_engine_profiler_trace(tmp_path):
    """start_profile/stop_profile capture an XLA trace (the TPU analog of
    the reference's wall-clock breakdown timers, SURVEY §5)."""
    import glob

    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            logp = jax.nn.log_softmax(nn.Dense(4)(x))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], -1))

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 8)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.int32)
    m = M()
    params = m.init({"params": jax.random.PRNGKey(0)}, x, y)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m, model_parameters=params,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "steps_per_print": 10_000,
        },
    )
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()  # compile outside the trace window
    trace_dir = str(tmp_path / "prof")
    engine.start_profile(trace_dir)
    engine.start_profile(trace_dir)  # idempotent
    for _ in range(2):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.stop_profile()
    engine.stop_profile()  # idempotent
    artifacts = glob.glob(trace_dir + "/**/*.pb", recursive=True) + glob.glob(
        trace_dir + "/**/*.json.gz", recursive=True
    )
    assert artifacts, os.listdir(trace_dir)
