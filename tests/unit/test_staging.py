"""Overlapped window staging (runtime/staging.py) + persistent compile
cache (runtime/compile_cache.py).

Coverage per the PR's acceptance criteria: staged vs. unstaged
``train_batch`` bitwise equivalence (params, losses, RNG stream) over
multi-window runs at accum 1 and 4; the ragged-final-window RuntimeError
on both paths; epoch-boundary refill (a fresh iterator rebuilds the
stager and the stream continues deterministically); preemption-drain
shutdown; thread-leak checks; the data-pipeline telemetry streams; the
staged dataloader ``_place`` path; config validation; and compile-cache
hits on a second ``initialize()``.

Models are bare ``loss_fn(params, batch, rng)`` callables (no flax) so
the jit programs stay tiny — this file runs in tier-1, not under the
``slow`` marker.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime import compile_cache
from deepspeed_tpu.runtime.staging import WindowStager, ragged_window_error

INPUT_DIM = 8


def loss_fn(params, batch, rng):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    # the additive noise makes the loss DEPEND on the rng key, so the
    # equivalence tests prove the staged pre-split reproduces the
    # unstaged key stream bit-for-bit, not merely the data order
    noise = 0.01 * jax.random.normal(rng, pred[:, 0].shape)
    return jnp.mean((pred[:, 0] + noise - y) ** 2)


def make_params(seed=0):
    r = np.random.default_rng(seed)
    return {
        "w": r.standard_normal((INPUT_DIM, 1)).astype(np.float32),
        "b": np.zeros((1,), np.float32),
    }


def make_batches(n, rows, seed=1):
    r = np.random.default_rng(seed)
    return [
        (
            r.standard_normal((rows, INPUT_DIM)).astype(np.float32),
            r.standard_normal((rows,)).astype(np.float32),
        )
        for _ in range(n)
    ]


def build_engine(accum=1, staged=True, extra=None):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": accum,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "steps_per_print": 1000,
        "data_pipeline": {"enabled": staged},
    }
    cfg.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=make_params(), config_params=cfg
    )
    return engine


def global_rows(engine):
    return engine.train_micro_batch_size_per_gpu() * engine.dp_world_size


def stager_threads():
    return [
        t for t in threading.enumerate()
        if t.is_alive() and t.name.startswith("ds-window-stager")
    ]


def rng_state(engine):
    return np.asarray(jax.random.key_data(engine._rng))


# ---------------------------------------------------------------------------
# equivalence: staged == unstaged, bitwise
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("accum", [1, 4])
def test_staged_equals_unstaged_bitwise(accum):
    def run(staged):
        engine = build_engine(accum=accum, staged=staged)
        batches = make_batches(4 * accum, global_rows(engine))
        it = iter(batches)
        losses = [float(engine.train_batch(it)) for _ in range(4)]
        params = jax.tree_util.tree_map(np.asarray, engine.params)
        rng = rng_state(engine)
        used_stager = engine._stager is not None
        engine.close_data_pipeline()
        return losses, params, rng, used_stager

    losses_u, params_u, rng_u, stager_u = run(False)
    losses_s, params_s, rng_s, stager_s = run(True)
    assert not stager_u and stager_s
    assert losses_u == losses_s  # float-exact, not approx
    for a, b in zip(
        jax.tree_util.tree_leaves(params_u), jax.tree_util.tree_leaves(params_s)
    ):
        assert np.array_equal(a, b)
    # the staged pre-split left the engine's RNG chain exactly where the
    # unstaged dispatch chain lands
    assert np.array_equal(rng_u, rng_s)


def test_staged_run_converges():
    import itertools

    engine = build_engine(accum=2, staged=True)
    # one fixed window cycled: the regression target is learnable, so the
    # staged loop must actually descend
    it = itertools.cycle(make_batches(2, global_rows(engine)))
    losses = [float(engine.train_batch(it)) for _ in range(12)]
    assert losses[-1] < losses[0]
    assert engine.global_steps == 12
    engine.close_data_pipeline()


# ---------------------------------------------------------------------------
# ragged final window (satellite: the bare-StopIteration fix)
# ---------------------------------------------------------------------------
def test_ragged_window_raises_runtime_error_unstaged():
    engine = build_engine(accum=4, staged=False)
    batches = make_batches(2, global_rows(engine))  # 2 of 4 micro-batches
    with pytest.raises(RuntimeError, match=r"2 of gradient_accumulation_steps=4"):
        engine.train_batch(iter(batches))


def test_ragged_window_raises_runtime_error_staged():
    engine = build_engine(accum=4, staged=True)
    batches = make_batches(6, global_rows(engine))  # 1 full window + 2 ragged
    it = iter(batches)
    float(engine.train_batch(it))
    with pytest.raises(RuntimeError, match=r"2 of gradient_accumulation_steps=4"):
        engine.train_batch(it)
    # the failed stream tore its stager down
    assert engine._stager is None
    assert stager_threads() == []


def test_clean_exhaustion_raises_stop_iteration_both_paths():
    for staged in (False, True):
        engine = build_engine(accum=2, staged=staged)
        batches = make_batches(4, global_rows(engine))  # exactly 2 windows
        it = iter(batches)
        float(engine.train_batch(it))
        float(engine.train_batch(it))
        with pytest.raises(StopIteration):
            engine.train_batch(it)
        assert engine._stager is None


# ---------------------------------------------------------------------------
# epoch-boundary refill
# ---------------------------------------------------------------------------
def test_epoch_boundary_refill_matches_single_stream():
    """Two epochs fed as two fresh iterators (stager torn down and
    rebuilt at the boundary) produce the same params as one staged stream
    over the concatenated data — the RNG chain hands off through the
    rebuild."""
    def run(two_epochs):
        engine = build_engine(accum=2, staged=True)
        batches = make_batches(8, global_rows(engine))  # 4 windows
        if two_epochs:
            for epoch in (batches[:4], batches[4:]):
                it = iter(epoch)
                float(engine.train_batch(it))
                float(engine.train_batch(it))
        else:
            it = iter(batches)
            for _ in range(4):
                float(engine.train_batch(it))
        params = jax.tree_util.tree_map(np.asarray, engine.params)
        engine.close_data_pipeline()
        return params

    single = run(False)
    double = run(True)
    for a, b in zip(
        jax.tree_util.tree_leaves(single), jax.tree_util.tree_leaves(double)
    ):
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# shutdown: preemption drain + thread leaks
# ---------------------------------------------------------------------------
def _preemption_engine(tmp_path, exit_after_save):
    return build_engine(
        accum=2,
        staged=True,
        extra={
            "resilience": {
                "preemption": {
                    "enabled": True,
                    "save_dir": str(tmp_path),
                    "exit_after_save": exit_after_save,
                },
            },
        },
    )


def test_preemption_drain_exit_closes_stager(tmp_path, monkeypatch):
    """exit_after_save (the preemption default): the stager is closed
    before the final checkpoint commits — no worker mid-device_put at
    exit, no leaked threads blocking the drain."""
    kills = []
    monkeypatch.setattr(
        "deepspeed_tpu.resilience.preemption.os.kill",
        lambda pid, sig: kills.append((pid, sig)),
    )
    engine = _preemption_engine(tmp_path, exit_after_save=True)
    batches = make_batches(2 * 8, global_rows(engine))
    it = iter(batches)
    float(engine.train_batch(it))
    assert engine._stager is not None
    import signal

    engine.resilience.preemption.arm(signal.SIGTERM)
    # the next step boundary honors the drain: stager torn down, final
    # checkpoint committed, original signal re-delivered (stubbed)
    float(engine.train_batch(it))
    assert engine._stager is None
    assert stager_threads() == []
    tags = {p.name for p in tmp_path.iterdir()}
    assert any(t.startswith("preempt_global_step") for t in tags)
    assert kills  # the drain re-raised to exit


def test_preemption_drain_exit_closes_loader_stager(tmp_path, monkeypatch):
    """At accum=1 the staging worker is LOADER-owned (train_batch skips
    its own stager on the marked iterator) — the exit drain must reach it
    through close_data_pipeline(), not only the engine-owned stager."""
    import signal

    monkeypatch.setattr(
        "deepspeed_tpu.resilience.preemption.os.kill",
        lambda pid, sig: None,
    )
    engine = build_engine(
        accum=1,
        staged=True,
        extra={
            "resilience": {
                "preemption": {
                    "enabled": True,
                    "save_dir": str(tmp_path),
                    "exit_after_save": True,
                },
            },
        },
    )
    loader = _loader_for(engine, 8)
    it = iter(loader)
    float(engine.train_batch(it))
    assert engine._stager is None  # loader-owned staging served it
    assert stager_threads()  # the loader's worker is live mid-epoch
    engine.resilience.preemption.arm(signal.SIGTERM)
    float(engine.train_batch(it))
    assert stager_threads() == []  # drain reached the loader's worker
    tags = {p.name for p in tmp_path.iterdir()}
    assert any(t.startswith("preempt_global_step") for t in tags)


def test_preemption_drain_keep_training_loses_no_data(tmp_path):
    """exit_after_save=false (checkpoint-and-continue): the stager stays
    attached — closing it would silently drop the windows it already
    pulled from the live iterator. The whole run must stay bitwise equal
    to an undrained staged run."""
    def run(drain):
        engine = _preemption_engine(tmp_path / f"d{int(drain)}",
                                    exit_after_save=False)
        batches = make_batches(2 * 6, global_rows(engine))
        it = iter(batches)
        losses = [float(engine.train_batch(it))]
        if drain:
            engine.resilience.preemption.arm()
        for _ in range(5):
            losses.append(float(engine.train_batch(it)))
        params = jax.tree_util.tree_map(np.asarray, engine.params)
        stager_alive = engine._stager is not None
        engine.close_data_pipeline()
        return losses, params, stager_alive

    losses_plain, params_plain, _ = run(False)
    losses_drain, params_drain, alive = run(True)
    assert alive  # the continue-drain kept the stager attached
    assert losses_plain == losses_drain
    for a, b in zip(
        jax.tree_util.tree_leaves(params_plain),
        jax.tree_util.tree_leaves(params_drain),
    ):
        assert np.array_equal(a, b)
    tags = {p.name for p in (tmp_path / "d1").iterdir()}
    assert any(t.startswith("preempt_global_step") for t in tags)


def test_no_thread_leak_across_stager_lifecycles():
    before = len(stager_threads())
    for _ in range(3):
        engine = build_engine(accum=1, staged=True)
        batches = make_batches(3, global_rows(engine))
        it = iter(batches)
        float(engine.train_batch(it))
        # new source mid-stream: old stager must close, not leak
        it2 = iter(make_batches(3, global_rows(engine), seed=7))
        float(engine.train_batch(it2))
        engine.close_data_pipeline()
    for t in stager_threads():
        t.join(timeout=5.0)
    assert len(stager_threads()) == before


def test_abandoned_engine_does_not_leak_stager():
    """Dropping an engine mid-stream (sweep, notebook rebuild) must stop
    the staging worker via the weakref finalizer: the worker holds only a
    weak engine ref, so the engine is collectable and its collection
    closes the stager."""
    import gc
    import itertools
    import time

    engine = build_engine(accum=1, staged=True)
    it = itertools.cycle(make_batches(2, global_rows(engine)))
    float(engine.train_batch(it))
    assert stager_threads()
    del engine
    gc.collect()
    deadline = time.monotonic() + 5.0
    while stager_threads() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert stager_threads() == []


def test_fresh_iterator_per_call_falls_back_unstaged():
    """A NEW iterator object every call (iter(list) per window) passes
    the iterator check but gives the stager nothing to pull ahead — after
    two churned single-window stagers the engine stops paying a thread
    per window and runs unstaged."""
    engine = build_engine(accum=1, staged=True)
    rows = global_rows(engine)
    losses = []
    for seed in range(5):
        losses.append(
            float(engine.train_batch(iter(make_batches(1, rows, seed=seed))))
        )
    assert all(np.isfinite(losses))
    assert engine.global_steps == 5
    # churn guard engaged: no stager attached, no worker threads
    assert engine._stager is None
    assert engine._stager_churn >= 2
    assert stager_threads() == []
    # ...but NOT a permanent latch: switching to one persistent iterator
    # (fresh-iterator warmups then the real loop) re-engages staging on
    # the second call with the same source
    it = iter(make_batches(4, rows, seed=99))
    float(engine.train_batch(it))  # same-source probe window (unstaged)
    assert engine._stager is None
    float(engine.train_batch(it))
    assert engine._stager is not None
    engine.close_data_pipeline()


def _loader_for(engine, n_batches):
    rows = global_rows(engine)
    r = np.random.default_rng(0)
    data = (
        r.standard_normal((rows * n_batches, INPUT_DIM)).astype(np.float32),
        r.standard_normal((rows * n_batches,)).astype(np.float32),
    )
    return engine.deepspeed_io(data, batch_size=rows)


def test_staged_loader_accum1_skips_engine_stager():
    """At accum=1 the loader's accum=1 stager IS the window stager: its
    iterator is marked already_staged and train_batch must NOT layer a
    second stager on top (double staging would re-stack placed arrays
    device-side and re-transfer the window)."""
    engine = build_engine(accum=1, staged=True)
    loader = _loader_for(engine, 4)
    it = iter(loader)
    assert getattr(it, "already_staged", False)
    float(engine.train_batch(it))
    assert engine._stager is None  # loader staging served the window
    float(engine.train_batch(it))
    assert engine.global_steps == 2
    # abandoning the epoch mid-stream: closing the marked iterator drains
    # the loader's stager synchronously
    it.close()
    assert stager_threads() == []


def test_loader_serves_host_batches_for_fused_windows_at_accum_gt_1():
    """At accum>1 the loader must NOT device-place its batches: the fused
    window stager needs host micro-batches to stack (device-resident ones
    would restack through the default device and transfer twice) — so the
    loader iterator is unmarked and the ENGINE stager engages over it."""
    engine = build_engine(accum=2, staged=True)
    loader = _loader_for(engine, 4)
    assert loader.stage_to_device is False
    assert loader.device_place is False
    # the loader really yields host batches, not pre-placed jax.Arrays
    first = next(iter(loader))
    assert all(isinstance(leaf, np.ndarray) for leaf in first)
    it = iter(loader)
    assert not getattr(it, "already_staged", False)
    float(engine.train_batch(it))
    assert engine._stager is not None  # window staging over host batches
    float(engine.train_batch(it))
    assert engine.global_steps == 2
    engine.close_data_pipeline()


def test_loader_host_batches_when_stage_to_device_off_accum1():
    """data_pipeline enabled with stage_to_device=false at accum=1: the
    ENGINE stager places (on the consuming thread), so the loader must
    yield host batches — device-placed ones would be restacked
    device-side and transferred twice."""
    engine = build_engine(
        accum=1,
        staged=True,
        extra={"data_pipeline": {"enabled": True, "stage_to_device": False}},
    )
    loader = _loader_for(engine, 4)
    assert loader.stage_to_device is False
    assert loader.device_place is False
    first = next(iter(loader))
    assert all(isinstance(leaf, np.ndarray) for leaf in first)
    it = iter(loader)
    float(engine.train_batch(it))
    float(engine.train_batch(it))
    assert engine._stager is not None  # engine-side staging engaged
    assert engine.global_steps == 2
    engine.close_data_pipeline()


def test_close_staging_reaches_all_live_epoch_iterators():
    engine = build_engine(accum=1, staged=True)
    loader = _loader_for(engine, 8)
    it1 = iter(loader)
    next(it1)  # partially consumed; worker live
    it2 = iter(loader)
    next(it2)
    assert len(stager_threads()) >= 1
    engine.close_data_pipeline()
    assert stager_threads() == []


def test_arm_compile_cache_reacts_to_threshold_change(tmp_path):
    try:
        d = str(tmp_path / "cc")
        assert compile_cache.arm_compile_cache(d, 1.0) is not None
        import jax

        assert (
            jax.config.jax_persistent_cache_min_compile_time_secs == 1.0
        )
        # same dir, new threshold: must re-arm, not early-return
        assert compile_cache.arm_compile_cache(d, 0.0) is not None
        assert (
            jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
        )
    finally:
        compile_cache.disarm_compile_cache()


def test_stager_close_is_idempotent_and_bounded():
    src = iter(make_batches(64, 4))
    stager = WindowStager(
        source=src,
        accum=2,
        stack_fn=lambda batches: batches,
        place_fn=lambda x: x,
        buffers=2,
        stage_to_device=False,
    )
    stager.get_window()
    stager.close()
    stager.close()
    assert not stager.alive()
    assert stager.occupancy() == 0


# ---------------------------------------------------------------------------
# telemetry streams
# ---------------------------------------------------------------------------
def test_staging_telemetry_streams(tmp_path):
    engine = build_engine(
        accum=2,
        staged=True,
        extra={
            "telemetry": {
                "enabled": True,
                "output_path": str(tmp_path),
                "job_name": "stage",
                "watchdog": {"enabled": False},
            },
        },
    )
    batches = make_batches(2 * 3, global_rows(engine))
    it = iter(batches)
    for _ in range(3):
        float(engine.train_batch(it))
    snap = engine.telemetry.registry.snapshot()
    assert snap["dataloader/staging_wait_ms/count"] == 3
    assert snap["dataloader/staging_time_ms/count"] >= 3
    assert snap["dataloader/h2d_bytes"] > 0
    assert "dataloader/staging_occupancy" in snap
    engine.close_data_pipeline()
    engine.telemetry.close()


def test_window_tokens_counted_like_unstaged(tmp_path):
    """Throughput accounting parity: the stager's per-window (tokens,
    samples) meta matches what the unstaged path counts micro-batch by
    micro-batch."""
    def run(staged):
        engine = build_engine(
            accum=2,
            staged=staged,
            extra={
                "telemetry": {
                    "enabled": True,
                    "output_path": str(tmp_path),
                    "job_name": f"tok{int(staged)}",
                    "interval": 100,  # keep counts un-reset
                    "watchdog": {"enabled": False},
                },
            },
        )
        batches = make_batches(2 * 2, global_rows(engine))
        it = iter(batches)
        float(engine.train_batch(it))
        float(engine.train_batch(it))
        counted = (
            engine.telemetry._tokens_since_export,
            engine.telemetry._samples_since_export,
        )
        engine.close_data_pipeline()
        engine.telemetry.close()
        return counted

    assert run(False) == run(True)


# ---------------------------------------------------------------------------
# dataloader: staged _place path (accum=1 stager)
# ---------------------------------------------------------------------------
def test_dataloader_staged_place_matches_unstaged():
    from deepspeed_tpu.parallel import mesh as mesh_lib
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    mesh = mesh_lib.build_mesh()
    r = np.random.default_rng(0)
    data = (
        r.standard_normal((32, INPUT_DIM)).astype(np.float32),
        r.integers(0, 10, 32).astype(np.int32),
    )
    plain = DeepSpeedDataLoader(data, batch_size=8, mesh=mesh)
    staged = DeepSpeedDataLoader(
        data, batch_size=8, mesh=mesh, stage_to_device=True
    )
    for _ in range(2):  # two epochs: the staged path refills per epoch
        got_plain = list(plain)
        got_staged = list(staged)
        assert len(got_plain) == len(got_staged) == 4
        for bp, bs in zip(got_plain, got_staged):
            for lp, ls in zip(bp, bs):
                assert isinstance(ls, jax.Array)
                assert lp.sharding == ls.sharding
                assert np.array_equal(np.asarray(lp), np.asarray(ls))
    assert stager_threads() == []


def test_dataloader_queue_depth_refills_between_epochs():
    """The satellite fix: the producer side samples the gauge too, so the
    new epoch's refill is visible instead of the gauge sticking at the
    previous epoch's drained 0."""
    class StubTelemetry:
        def __init__(self):
            self.depths = []

        def set_dataloader_depth(self, depth):
            self.depths.append(depth)

    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    data = (np.arange(64, dtype=np.float32).reshape(16, 4),)
    stub = StubTelemetry()
    loader = DeepSpeedDataLoader(
        data, batch_size=4, mesh=None, prefetch=2, telemetry=stub
    )
    list(loader)
    first_epoch_samples = len(stub.depths)
    # producer-side samples exist, not only the 4 handoffs
    assert first_epoch_samples > 4
    assert any(d > 0 for d in stub.depths)
    list(loader)
    # the second epoch reported refill depths > 0 again
    assert any(d > 0 for d in stub.depths[first_epoch_samples:])


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "block",
    [
        {"data_pipeline": {"staging_buffers": 0}},
        {"data_pipeline": {"staging_buffers": True}},
        {"data_pipeline": {"staging_buffers": "2"}},
        {"data_pipeline": {"enabled": "yes"}},
        {"data_pipeline": {"stage_to_device": 1}},
        {"compile_cache": {"enabled": "on"}},
        {"compile_cache": {"cache_dir": 7}},
        {"compile_cache": {"min_compile_time_secs": -1}},
        {"compile_cache": {"min_compile_time_secs": "1"}},
    ],
)
def test_config_rejects_bad_blocks(block):
    cfg = {"train_batch_size": 8, **block}
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(None, param_dict=cfg, world_size=1)


def test_config_defaults():
    cfg = DeepSpeedConfig(
        None, param_dict={"train_batch_size": 8}, world_size=1
    )
    assert cfg.data_pipeline_enabled is False
    assert cfg.data_pipeline_staging_buffers == 2
    assert cfg.data_pipeline_stage_to_device is True
    assert cfg.compile_cache_enabled is False
    assert cfg.compile_cache_min_compile_time_secs == 1.0


def test_ragged_window_error_names_counts():
    err = ragged_window_error(3, 8)
    assert isinstance(err, RuntimeError)
    assert "3 of gradient_accumulation_steps=8" in str(err)


# ---------------------------------------------------------------------------
# compile cache: second initialize() hits
# ---------------------------------------------------------------------------
def test_compile_cache_hits_on_second_initialize(tmp_path):
    """Acceptance: with "compile_cache" enabled, a second initialize()
    in the same configuration reuses the persisted programs — the hit
    counter (exported next to jax/recompiles) moves."""
    extra = {
        "compile_cache": {
            "enabled": True,
            "cache_dir": str(tmp_path / "jax_cache"),
            "min_compile_time_secs": 0.0,
        },
        "telemetry": {
            "enabled": True,
            "output_path": str(tmp_path),
            "job_name": "cc",
            "watchdog": {"enabled": False},
        },
    }
    try:
        for i in range(2):
            engine = build_engine(accum=2, staged=True, extra=extra)
            batches = make_batches(2 * 2, global_rows(engine))
            it = iter(batches)
            float(engine.train_batch(it))
            snap = engine.telemetry.registry.snapshot()
            engine.close_data_pipeline()
            engine.telemetry.close()
        assert snap["jax/compile_cache_hits"] > 0
    finally:
        # the tmp cache dir dies with the test; leaving the global cache
        # armed would fail every later compile's cache write
        compile_cache.disarm_compile_cache()


def test_compile_cache_disabled_by_default():
    cfg = DeepSpeedConfig(
        None, param_dict={"train_batch_size": 8}, world_size=1
    )
    assert compile_cache.configure_compile_cache(cfg) is None
