"""Dynamic loss-scale semantics.

Mirrors the reference's tests/unit/test_dynamic_loss_scale.py: exact scale
values through overflow/halve and raise schedules, skipped-step behavior,
hysteresis. Exercises BOTH the pure jit-safe state machine (the one the
engine uses inside jit) and the reference-shaped mutable class.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.precision import (
    DynamicLossScaler,
    dynamic_loss_scale_state,
    static_loss_scale_state,
    update_scale,
)
from deepspeed_tpu.utils.numerics import global_norm, has_overflow


def test_pure_scaler_halves_on_overflow():
    state = dynamic_loss_scale_state(init_scale=2.0**8, scale_window=1000)
    state = update_scale(state, jnp.asarray(True))
    assert float(state.loss_scale) == 2.0**7
    state = update_scale(state, jnp.asarray(True))
    assert float(state.loss_scale) == 2.0**6
    assert int(state.good_steps) == 0


def test_pure_scaler_doubles_after_window():
    window = 4
    state = dynamic_loss_scale_state(init_scale=2.0**4, scale_window=window)
    for _ in range(window):
        state = update_scale(state, jnp.asarray(False))
    assert float(state.loss_scale) == 2.0**5
    # window resets: not doubled again until another full window
    for _ in range(window - 1):
        state = update_scale(state, jnp.asarray(False))
    assert float(state.loss_scale) == 2.0**5
    state = update_scale(state, jnp.asarray(False))
    assert float(state.loss_scale) == 2.0**6


def test_pure_scaler_min_scale_floor():
    state = dynamic_loss_scale_state(init_scale=4.0, scale_window=100, min_scale=1.0)
    for _ in range(10):
        state = update_scale(state, jnp.asarray(True))
    assert float(state.loss_scale) == 1.0


def test_pure_scaler_hysteresis():
    # delayed_shift=2: the first overflow only burns hysteresis.
    state = dynamic_loss_scale_state(
        init_scale=2.0**8, scale_window=1000, delayed_shift=2
    )
    state = update_scale(state, jnp.asarray(True))
    assert float(state.loss_scale) == 2.0**8
    assert int(state.hysteresis) == 1
    state = update_scale(state, jnp.asarray(True))
    assert float(state.loss_scale) == 2.0**7


def test_pure_scaler_under_jit():
    state = dynamic_loss_scale_state(init_scale=2.0**8, scale_window=2)

    @jax.jit
    def step(s, overflow):
        return update_scale(s, overflow)

    state = step(state, jnp.asarray(True))
    assert float(state.loss_scale) == 2.0**7
    state = step(state, jnp.asarray(False))
    state = step(state, jnp.asarray(False))
    assert float(state.loss_scale) == 2.0**8


def test_static_scaler_never_changes():
    state = static_loss_scale_state(128.0)
    for ov in (True, False, True):
        state = update_scale(state, jnp.asarray(ov))
    assert float(state.loss_scale) == 128.0


def test_overflow_every_two_steps_schedule():
    # Mirrors reference test: overflow every N steps keeps halving.
    state = dynamic_loss_scale_state(init_scale=2.0**16, scale_window=1000)
    expected = 2.0**16
    for i in range(6):
        overflow = i % 2 == 1
        state = update_scale(state, jnp.asarray(overflow))
        if overflow:
            expected /= 2
        assert float(state.loss_scale) == expected


# ------------------------------------------------------------ mutable wrapper
def test_class_scaler_matches_pure():
    cls = DynamicLossScaler(init_scale=2.0**10, scale_window=3, min_scale=1.0)
    pure = dynamic_loss_scale_state(init_scale=2.0**10, scale_window=3, min_scale=1.0)
    pattern = [False, False, True, False, False, False, True, True, False]
    for ov in pattern:
        cls.update_scale(ov)
        pure = update_scale(pure, jnp.asarray(ov))
        assert float(pure.loss_scale) == cls.cur_scale


# ------------------------------------------------------------ overflow/norms
def test_has_overflow():
    good = {"a": jnp.ones((4,)), "b": jnp.zeros((2, 2))}
    assert not bool(has_overflow(good))
    bad = {"a": jnp.array([1.0, jnp.inf]), "b": jnp.zeros((2,))}
    assert bool(has_overflow(bad))
    nan = {"a": jnp.array([jnp.nan])}
    assert bool(has_overflow(nan))


def test_global_norm():
    tree = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
    np.testing.assert_allclose(float(global_norm(tree)), 5.0, rtol=1e-6)
    inf_tree = {"a": jnp.array([jnp.inf])}
    assert float(global_norm(inf_tree)) == -1.0


def test_pure_scaler_hysteresis_refill_after_clean_window():
    # non-consecutive hysteresis refills when a full clean window passes
    state = dynamic_loss_scale_state(
        init_scale=2.0**8, scale_window=3, delayed_shift=2
    )
    state = update_scale(state, jnp.asarray(True))  # burns hysteresis -> 1
    assert int(state.hysteresis) == 1
    for _ in range(3):  # clean window
        state = update_scale(state, jnp.asarray(False))
    assert int(state.hysteresis) == 2  # refilled
    state = update_scale(state, jnp.asarray(True))
    assert float(state.loss_scale) == 2.0**9  # absorbed again (scale was doubled)


def test_class_scaler_matches_pure_with_hysteresis():
    cls = DynamicLossScaler(init_scale=2.0**10, scale_window=3, delayed_shift=3)
    pure = dynamic_loss_scale_state(
        init_scale=2.0**10, scale_window=3, delayed_shift=3
    )
    pattern = [True, False, False, False, True, True, True, False, True]
    for ov in pattern:
        cls.update_scale(ov)
        pure = update_scale(pure, jnp.asarray(ov))
        assert float(pure.loss_scale) == cls.cur_scale, pattern
