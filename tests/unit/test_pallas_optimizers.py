"""Pallas fused LAMB: numerical parity with the pure-JAX Lamb.

The analog of validating csrc/lamb/fused_lamb_cuda_kernel.cu against the
unfused torch math (the reference never shipped such a test; here parity is
asserted leaf-for-leaf including the trust-ratio coefficients)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import Lamb
from deepspeed_tpu.ops.pallas import BLOCK, FusedLamb

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    # leaf sizes chosen to cover: sub-block, exact block multiple, ragged
    shapes = [(17,), (BLOCK // 128, 128), (3, 1000), (257, 129)]
    params = {f"p{i}": jnp.asarray(rng.standard_normal(s), jnp.float32)
              for i, s in enumerate(shapes)}
    grads = {f"p{i}": jnp.asarray(rng.standard_normal(s) * 0.1, jnp.float32)
             for i, s in enumerate(shapes)}
    return params, grads


@pytest.mark.parametrize("weight_decay", [0.0, 0.01])
@pytest.mark.parametrize("eps_inside_sqrt", [False, True])
def test_fused_lamb_matches_pure_jax(weight_decay, eps_inside_sqrt):
    kw = dict(weight_decay=weight_decay, eps_inside_sqrt=eps_inside_sqrt)
    ref = Lamb(**kw)
    fused = FusedLamb(**kw)
    params, grads = _tree()
    state_r = ref.init(params)
    state_f = fused.init(params)
    lr = jnp.float32(1e-2)
    for step in range(3):
        params_r, state_r, aux_r = ref.apply(params, grads, state_r, lr)
        params_f, state_f, aux_f = fused.apply(params, grads, state_f, lr)
        for a, b in zip(
            jax.tree_util.tree_leaves(params_r),
            jax.tree_util.tree_leaves(params_f),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-6, atol=2e-6
            )
        for a, b in zip(
            jax.tree_util.tree_leaves(state_r["mu"]),
            jax.tree_util.tree_leaves(state_f["mu"]),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-6, atol=2e-6
            )
        # blocked partial sums reorder the norm accumulation: tiny float
        # drift in the trust ratios is expected
        np.testing.assert_allclose(
            np.asarray(jnp.stack(aux_r["lamb_coeffs"])),
            np.asarray(jnp.stack(aux_f["lamb_coeffs"])),
            rtol=1e-4,
        )
        params = params_r  # advance both from the same point
        grads = jax.tree_util.tree_map(lambda g: g * 0.9, grads)


def test_fused_lamb_under_jit():
    fused = FusedLamb()
    params, grads = _tree(seed=3)
    state = fused.init(params)

    @jax.jit
    def step(params, grads, state, lr):
        return fused.apply(params, grads, state, lr)

    new_params, new_state, aux = step(params, grads, state, jnp.float32(1e-3))
    assert int(new_state["step"]) == 1
    for leaf in jax.tree_util.tree_leaves(new_params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_multi_tensor_matches_per_leaf_path():
    """The packed multi-tensor launch (reference analog: apex
    multi_tensor_apply batching many tensors per kernel) must be
    numerically identical to the per-leaf kernel path."""
    params, grads = _tree(seed=3)
    batched = FusedLamb()                      # small leaves -> packed
    per_leaf = FusedLamb(multi_tensor_max=0)   # batching disabled
    sb, sp = batched.init(params), per_leaf.init(params)
    lr = jnp.float32(1e-2)
    pb, sb, ab = batched.apply(params, grads, sb, lr)
    pp, sp, ap = per_leaf.apply(params, grads, sp, lr)
    for a, b in zip(jax.tree_util.tree_leaves((pb, sb)),
                    jax.tree_util.tree_leaves((pp, sp))):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )
    np.testing.assert_allclose(
        np.asarray(jnp.stack(ab["lamb_coeffs"])),
        np.asarray(jnp.stack(ap["lamb_coeffs"])), rtol=1e-6,
    )


def test_multi_tensor_mixed_with_large_leaf():
    """A tree mixing a leaf above multi_tensor_max with many small ones
    routes each to its path and keeps coeffs in leaf order."""
    rng = np.random.default_rng(5)
    params = {"big": jnp.asarray(rng.standard_normal((40, 1024)), jnp.float32)}
    grads = {"big": jnp.asarray(rng.standard_normal((40, 1024)) * 0.1,
                                jnp.float32)}
    for i in range(6):
        params[f"s{i}"] = jnp.asarray(rng.standard_normal((33,)), jnp.float32)
        grads[f"s{i}"] = jnp.asarray(
            rng.standard_normal((33,)) * 0.1, jnp.float32
        )
    fused = FusedLamb(multi_tensor_max=BLOCK)  # "big" exceeds one block
    ref = Lamb()
    sf, sr = fused.init(params), ref.init(params)
    lr = jnp.float32(1e-2)
    for _ in range(2):
        pf, sf, af = fused.apply(params, grads, sf, lr)
        pr, sr, ar = ref.apply(params, grads, sr, lr)
        params_f, params_r = pf, pr
    for a, b in zip(jax.tree_util.tree_leaves((pf, sf)),
                    jax.tree_util.tree_leaves((pr, sr))):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )
    np.testing.assert_allclose(
        np.asarray(jnp.stack(af["lamb_coeffs"])),
        np.asarray(jnp.stack(ar["lamb_coeffs"])), rtol=1e-5,
    )
