"""Master-weights layout (reference ZeRO: fp16/bf16 model params
replicated, fp32 master partitioned into the optimizer state —
deepspeed_zero_optimizer.py:256-263).

Under bf16/fp16 + stage>=1 the engine stores params in the compute dtype
and keeps the fp32 master inside the dp-sharded optimizer state. These
tests pin: storage dtypes/shardings, exact numerical equivalence with the
fp32-param storage mode (the math is identical — only placement moves),
fp16 overflow-skip integrity, and exact checkpoint resume.
"""

import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, y, train=True):
        h = nn.relu(nn.Dense(32)(x))
        logp = jax.nn.log_softmax(nn.Dense(4)(h))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))


def _data(seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int32) + 2 * (X[:, 1] > 0).astype(np.int32)
    return X, Y


def _engine(master_weights, stage=2, precision="bf16", dp=8, seed=0):
    X, Y = _data()
    model = MLP()
    params = model.init(
        {"params": jax.random.PRNGKey(seed)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]
    mesh = build_mesh(
        devices=jax.devices()[:dp], data_parallel_size=dp
    )
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=mesh,
        config_params={
            "train_batch_size": 16,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            precision: {"enabled": True},
            "zero_optimization": {
                "stage": stage, "master_weights": master_weights,
            },
            "steps_per_print": 10_000,
        },
        rng_seed=0,
    )
    return engine


def _train(engine, steps=15):
    X, Y = _data()
    losses = []
    for _ in range(steps):
        loss = engine(X, Y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return np.asarray(losses)


def test_master_layout_dtypes_and_sharding():
    engine = _engine(master_weights=True)
    assert engine.master_in_opt
    # params stored in the compute dtype (the reference's replicated fp16)
    for leaf in jax.tree_util.tree_leaves(engine.params):
        assert leaf.dtype == engine.compute_dtype, leaf.dtype
    # fp32 master rides the optimizer state, dp-sharded where divisible
    masters = jax.tree_util.tree_leaves(engine.optimizer_state["master"])
    assert all(m.dtype == jnp.float32 for m in masters)
    assert any(
        "data" in str(m.sharding.spec) for m in masters
    ), [str(m.sharding.spec) for m in masters]


def test_master_mode_matches_fp32_param_storage_exactly():
    """Moving the master into the optimizer state must not change a single
    step: both modes compute bf16(master) forward + fp32 master update."""
    on = _train(_engine(master_weights=True))
    off = _train(_engine(master_weights=False))
    np.testing.assert_array_equal(on, off)
    assert on[-1] < 0.5 * on[0], on


def test_master_mode_off_keeps_fp32_params():
    engine = _engine(master_weights=False)
    assert not engine.master_in_opt
    for leaf in jax.tree_util.tree_leaves(engine.params):
        assert leaf.dtype == jnp.float32
    assert "master" not in engine.optimizer_state


def test_fp32_runs_never_use_master_mode():
    X, Y = _data()
    model = MLP()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        mesh=build_mesh(data_parallel_size=8),
        config_params={
            "train_batch_size": 16,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10_000,
        },
    )
    assert not engine.master_in_opt  # fp32 params ARE the master


def test_fp16_overflow_skip_with_master(monkeypatch):
    """Dynamic loss scaling on the fp16 (CPU) path: an overflow must skip
    the master update and halve the scale, same as without master mode."""
    engine = _engine(master_weights=True, stage=1, precision="fp16")
    assert engine.master_in_opt
    X, Y = _data()
    # poison one step with an exploding input to force an fp16 overflow
    loss = engine(X * 1e4, Y)
    engine.backward(loss)
    master_before = jax.tree_util.tree_map(
        np.asarray, engine.optimizer_state["master"]
    )
    engine.step()
    if engine.last_overflow:
        # (the first overflow may only burn hysteresis, not halve the
        # scale — reference delayed_shift semantics); the master update
        # MUST have been skipped either way
        assert engine.skipped_steps == 1
        for a, b in zip(
            jax.tree_util.tree_leaves(master_before),
            jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(
                    np.asarray, engine.optimizer_state["master"]
                )
            ),
        ):
            np.testing.assert_array_equal(a, b)
    # training continues afterwards
    losses = _train(engine, steps=10)
    assert np.isfinite(losses).all()


def test_master_mode_checkpoint_resume_exact(tmp_path):
    engine = _engine(master_weights=True)
    first = _train(engine, steps=8)
    engine.save_checkpoint(str(tmp_path), tag="mid")
    cont = _train(engine, steps=8)

    fresh = _engine(master_weights=True)
    # different init: only a real restore can match
    fresh.load_checkpoint(str(tmp_path), tag="mid")
    resumed = _train(fresh, steps=8)
    np.testing.assert_allclose(resumed, cont, rtol=1e-6)


def test_checkpoint_crosses_master_layouts(tmp_path):
    """The on-disk optimizer layout is canonical {master, inner}: a bf16
    checkpoint saved at dp=1 (fp32-param storage, no master mode) must
    resume at dp=8 (master mode) and vice versa, exactly."""
    # save at dp=1 (master OFF), resume at dp=8 (master ON)
    e1 = _engine(master_weights=True, dp=1)  # dp=1 forces master off
    assert not e1.master_in_opt
    _train(e1, steps=8)
    e1.save_checkpoint(str(tmp_path / "a"), tag="t")
    cont = _train(e1, steps=8)

    e8 = _engine(master_weights=True, dp=8, seed=7)
    assert e8.master_in_opt
    e8.load_checkpoint(str(tmp_path / "a"), tag="t")
    resumed = _train(e8, steps=8)
    # cross-dp resumes change the gradient-reduction order: bf16-forward
    # trajectories match to reduction noise, not bit-exactly
    np.testing.assert_allclose(resumed, cont, rtol=1e-2)

    # save at dp=8 (master ON), resume at dp=1 (master OFF): the fp32
    # master partition must override the bf16 module weights (the
    # reference's load_from_fp32_weights=True)
    e8b = _engine(master_weights=True, dp=8)
    _train(e8b, steps=8)
    e8b.save_checkpoint(str(tmp_path / "b"), tag="t")
    master_saved = jax.tree_util.tree_map(
        np.asarray, e8b.optimizer_state["master"]
    )
    cont_b = _train(e8b, steps=8)

    e1b = _engine(master_weights=True, dp=1, seed=7)
    assert not e1b.master_in_opt
    e1b.load_checkpoint(str(tmp_path / "b"), tag="t")
    # the engine's fp32 storage dtype must survive the bf16 module file:
    # params come from the fp32 master partition BIT-EXACTLY, never
    # truncated through the module file's bf16
    for leaf in jax.tree_util.tree_leaves(e1b.params):
        assert leaf.dtype == jnp.float32, leaf.dtype
    for saved, restored in zip(
        jax.tree_util.tree_leaves(master_saved),
        jax.tree_util.tree_leaves(e1b.params),
    ):
        np.testing.assert_array_equal(saved, np.asarray(restored))
    resumed_b = _train(e1b, steps=8)
    np.testing.assert_allclose(resumed_b, cont_b, rtol=1e-2)


def test_model_only_checkpoint_does_not_revert_weights(tmp_path):
    """Loading with load_optimizer_states=False must refresh the fp32
    master from the loaded weights — otherwise the first step would
    publish init-time values."""
    engine = _engine(master_weights=True)
    _train(engine, steps=8)
    engine.save_checkpoint(str(tmp_path), tag="t")
    ref = np.asarray(
        jax.tree_util.tree_leaves(engine.optimizer_state["master"])[0]
    )

    fresh = _engine(master_weights=True, seed=7)
    fresh.load_checkpoint(str(tmp_path), tag="t", load_optimizer_states=False)
    got = np.asarray(
        jax.tree_util.tree_leaves(fresh.optimizer_state["master"])[0]
    )
    # master now mirrors the loaded (bf16) weights, not seed-7 init
    np.testing.assert_allclose(got, ref, atol=1e-2)
    loss0 = float(fresh(*_data()[:2]))
    fresh.backward(loss0)
    fresh.step()
    loss1 = float(fresh(*_data()[:2]))
    assert loss1 < loss0 * 1.5, (loss0, loss1)  # no catastrophic revert
