"""Config-system tests.

Coverage mirrors the reference's tests/unit/test_config.py +
test_ds_config.py: batch-size triangle resolution in every combination,
consistency assertion, duplicate-key rejection, zero/fp16/scheduler blocks,
deprecated forms.
"""

import pytest

from deepspeed_tpu.config import (
    DeepSpeedConfig,
    DeepSpeedConfigError,
    loads_config_json,
)


def make(config_dict, world_size=1):
    return DeepSpeedConfig(None, param_dict=config_dict, world_size=world_size)


# ---------------------------------------------------------------- batch triangle
def test_batch_all_three_consistent():
    cfg = make(
        {
            "train_batch_size": 32,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2,
        },
        world_size=4,
    )
    assert cfg.train_batch_size == 32
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.gradient_accumulation_steps == 2


def test_batch_all_three_inconsistent():
    with pytest.raises(DeepSpeedConfigError):
        make(
            {
                "train_batch_size": 32,
                "train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 4,
            },
            world_size=4,
        )


def test_batch_train_and_micro():
    cfg = make(
        {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4}, world_size=4
    )
    assert cfg.gradient_accumulation_steps == 4


def test_batch_train_and_accum():
    cfg = make(
        {"train_batch_size": 64, "gradient_accumulation_steps": 4}, world_size=4
    )
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_batch_micro_and_accum():
    cfg = make(
        {"train_micro_batch_size_per_gpu": 4, "gradient_accumulation_steps": 4},
        world_size=4,
    )
    assert cfg.train_batch_size == 64


def test_batch_train_only():
    cfg = make({"train_batch_size": 64}, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 16
    assert cfg.gradient_accumulation_steps == 1


def test_batch_micro_only():
    cfg = make({"train_micro_batch_size_per_gpu": 16}, world_size=4)
    assert cfg.train_batch_size == 64
    assert cfg.gradient_accumulation_steps == 1


def test_batch_none_given():
    with pytest.raises(DeepSpeedConfigError):
        make({}, world_size=4)


def test_batch_not_divisible():
    with pytest.raises(DeepSpeedConfigError):
        make({"train_batch_size": 33, "train_micro_batch_size_per_gpu": 4}, world_size=4)


def test_batch_zero_invalid():
    with pytest.raises(DeepSpeedConfigError):
        make({"train_batch_size": 0}, world_size=1)


# ---------------------------------------------------------------- json handling
def test_duplicate_keys_rejected():
    with pytest.raises(ValueError):
        loads_config_json('{"train_batch_size": 4, "train_batch_size": 8}')


def test_nested_duplicate_keys_rejected():
    with pytest.raises(ValueError):
        loads_config_json(
            '{"fp16": {"enabled": true, "enabled": false}, "train_batch_size": 4}'
        )


def test_config_from_file(tmp_config_file):
    path = tmp_config_file({"train_batch_size": 16, "fp16": {"enabled": True}})
    cfg = DeepSpeedConfig(path, world_size=2)
    assert cfg.train_batch_size == 16
    assert cfg.fp16_enabled


# ---------------------------------------------------------------- sub-configs
def test_zero_dict_form():
    cfg = make(
        {
            "train_batch_size": 4,
            "fp16": {"enabled": True},
            "zero_optimization": {
                "stage": 2,
                "allgather_bucket_size": 1234,
                "overlap_comm": True,
            },
        }
    )
    assert cfg.zero_enabled
    assert cfg.zero_optimization_stage == 2
    assert cfg.zero_config.allgather_bucket_size == 1234
    assert cfg.zero_config.overlap_comm is True
    assert cfg.zero_config.reduce_scatter is True  # default


def test_zero_deprecated_bool_form():
    cfg = make(
        {"train_batch_size": 4, "fp16": {"enabled": True}, "zero_optimization": True}
    )
    assert cfg.zero_enabled
    assert cfg.zero_optimization_stage == 1


def test_zero_disabled_by_default():
    cfg = make({"train_batch_size": 4})
    assert not cfg.zero_enabled
    assert cfg.zero_optimization_stage == 0


def test_zero_stage_too_high():
    with pytest.raises(DeepSpeedConfigError):
        make(
            {
                "train_batch_size": 4,
                "fp16": {"enabled": True},
                "zero_optimization": {"stage": 4},
            }
        )


# ------------------------------------------------- ZeRO stage-3 validation
def _zero(z):
    return {"train_batch_size": 4, "zero_optimization": z}


@pytest.mark.parametrize("stage", [-1, 4, True, "2", 1.5])
def test_zero_stage_must_be_real_stage(stage):
    with pytest.raises(DeepSpeedConfigError):
        make(_zero({"stage": stage}))


@pytest.mark.parametrize(
    "key", ["stag", "stage3_gather_blocks", "overlap_com", "zero3"]
)
def test_zero_unknown_keys_rejected(key):
    # a typo'd knob must not silently mean its default
    with pytest.raises(DeepSpeedConfigError, match="unknown"):
        make(_zero({"stage": 3, key: 1}))


@pytest.mark.parametrize(
    "knob,value",
    [("stage3_gather_block", 2), ("stage3_latency_hiding", True)],
)
@pytest.mark.parametrize("stage", [0, 1, 2])
def test_zero_stage3_knobs_rejected_below_stage3(knob, value, stage):
    # stage-3 machinery spelled out while a typo'd stage leaves params
    # replicated must fail at init, not train at the wrong memory profile
    with pytest.raises(DeepSpeedConfigError, match="stage-3"):
        make(_zero({"stage": stage, knob: value}))


def test_zero_stage3_knobs_parse_at_stage3():
    cfg = make(
        _zero(
            {
                "stage": 3,
                "stage3_gather_block": 4,
                "stage3_latency_hiding": False,
            }
        )
    )
    assert cfg.zero_optimization_stage == 3
    assert cfg.zero_config.stage3_gather_block == 4
    assert cfg.zero_config.stage3_latency_hiding is False


def test_zero_stage3_knob_defaults():
    cfg = make(_zero({"stage": 3}))
    assert cfg.zero_config.stage3_gather_block == 2
    assert cfg.zero_config.stage3_latency_hiding is True


@pytest.mark.parametrize("gb", [0, -1, True, "2", 1.5])
def test_zero_stage3_gather_block_type_checked(gb):
    with pytest.raises(DeepSpeedConfigError):
        make(_zero({"stage": 3, "stage3_gather_block": gb}))


@pytest.mark.parametrize("lh", [1, "true", None])
def test_zero_stage3_latency_hiding_type_checked(lh):
    with pytest.raises(DeepSpeedConfigError):
        make(_zero({"stage": 3, "stage3_latency_hiding": lh}))


def test_fp16_block():
    cfg = make(
        {
            "train_batch_size": 4,
            "fp16": {
                "enabled": True,
                "loss_scale": 0,
                "initial_scale_power": 16,
                "loss_scale_window": 500,
                "hysteresis": 3,
                "min_loss_scale": 2,
            },
        }
    )
    assert cfg.fp16_enabled
    assert cfg.dynamic_loss_scale
    assert cfg.initial_scale_power == 16
    assert cfg.loss_scale_window == 500
    assert cfg.hysteresis == 3
    assert cfg.min_loss_scale == 2


def test_static_loss_scale():
    cfg = make({"train_batch_size": 4, "fp16": {"enabled": True, "loss_scale": 128}})
    assert not cfg.dynamic_loss_scale
    assert cfg.loss_scale == 128


def test_fp16_and_bf16_conflict():
    with pytest.raises(DeepSpeedConfigError):
        make(
            {
                "train_batch_size": 4,
                "fp16": {"enabled": True},
                "bf16": {"enabled": True},
            }
        )


def test_bf16_block():
    cfg = make({"train_batch_size": 4, "bf16": {"enabled": True}})
    assert cfg.bf16_enabled and not cfg.fp16_enabled


def test_optimizer_and_scheduler_blocks():
    cfg = make(
        {
            "train_batch_size": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 0.0015}},
            "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}},
        }
    )
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params["lr"] == 0.0015
    assert cfg.scheduler_name == "WarmupLR"
    assert cfg.scheduler_params["warmup_num_steps"] == 10


def test_activation_checkpointing_block():
    cfg = make(
        {
            "train_batch_size": 4,
            "activation_checkpointing": {
                "partition_activations": True,
                "number_checkpoints": 4,
                "cpu_checkpointing": True,
            },
        }
    )
    acfg = cfg.activation_checkpointing_config
    assert acfg.partition_activations
    assert acfg.number_checkpoints == 4
    assert acfg.cpu_checkpointing


def test_gradient_clipping_and_misc():
    cfg = make(
        {
            "train_batch_size": 4,
            "gradient_clipping": 1.0,
            "prescale_gradients": True,
            "gradient_predivide_factor": 2.0,
            "sparse_gradients": True,
            "steps_per_print": 7,
            "wall_clock_breakdown": True,
        }
    )
    assert cfg.gradient_clipping == 1.0
    assert cfg.prescale_gradients
    assert cfg.gradient_predivide_factor == 2.0
    assert cfg.sparse_gradients_enabled
    assert cfg.steps_per_print == 7
    assert cfg.wall_clock_breakdown


def test_mesh_block():
    cfg = make(
        {
            "train_batch_size": 8,
            "mesh": {"model_parallel_size": 2, "sequence_parallel_size": 2},
        },
        world_size=2,
    )
    assert cfg.model_parallel_size == 2
    assert cfg.sequence_parallel_size == 2
    assert cfg.pipeline_parallel_size == 1


def test_amp_block_rejected():
    """apex amp has no TPU path (reference deepspeed_light.py:516-521);
    an enabled amp block must fail loudly, never be silently ignored."""
    with pytest.raises(DeepSpeedConfigError, match="amp"):
        make({"train_batch_size": 8, "amp": {"enabled": True}})
    with pytest.raises(DeepSpeedConfigError, match="bf16"):
        make({"train_batch_size": 8, "amp": {"opt_level": "O2"}})
    # explicitly disabled amp is a no-op, as in the reference
    cfg = make({"train_batch_size": 8, "amp": {"enabled": False}})
    assert cfg.train_batch_size == 8


def test_zero_allow_untested_optimizer_key():
    cfg = make({"train_batch_size": 8})
    assert cfg.zero_allow_untested_optimizer is False
    cfg = make(
        {"train_batch_size": 8, "zero_allow_untested_optimizer": True}
    )
    assert cfg.zero_allow_untested_optimizer is True


# ---------------------------------------------------------------------------
# resilience self-healing blocks: fault_injection + supervisor
# (docs/resilience.md "Fault injection" / "Self-healing supervision")
# ---------------------------------------------------------------------------
def _res(block):
    return make({"train_batch_size": 8, "resilience": block})


def test_fault_injection_and_supervisor_defaults():
    cfg = make({"train_batch_size": 8})
    assert cfg.resilience_fault_injection_enabled is False
    assert cfg.resilience_fault_injection_seed == 0
    assert cfg.resilience_fault_injection_faults == []
    assert cfg.resilience_supervisor_enabled is False
    assert cfg.resilience_supervisor_max_rollbacks == 2
    assert cfg.resilience_supervisor_nonfinite_window == 3
    assert cfg.resilience_supervisor_spike_factor == 0.0


def test_fault_injection_valid_block_parses():
    cfg = _res({"fault_injection": {"enabled": True, "seed": 7, "faults": [
        {"site": "checkpoint.write", "times": 2},
        {"site": "step.stall", "probability": 0.5,
         "args": {"duration_ms": 10}},
    ]}})
    assert cfg.resilience_fault_injection_enabled is True
    assert len(cfg.resilience_fault_injection_faults) == 2


@pytest.mark.parametrize("block", [
    # unknown fault-site names must fail at init, not fire never
    {"fault_injection": {"enabled": True,
                         "faults": [{"site": "not.a.site"}]}},
    {"fault_injection": {"enabled": True, "faults": [{}]}},  # no site
    {"fault_injection": {"enabled": True, "faults": []}},  # armed but empty
    {"fault_injection": {"enabled": True, "faults": "checkpoint.write"}},
    {"fault_injection": {"enabled": True, "faults": [
        {"site": "grads.nan", "times": -1}]}},
    {"fault_injection": {"enabled": True, "faults": [
        {"site": "grads.nan", "probability": 1.5}]}},
    {"fault_injection": {"enabled": True, "faults": [
        {"site": "grads.nan", "after": -2}]}},
    {"fault_injection": {"enabled": True, "faults": [
        {"site": "step.stall", "args": 250}]}},
    {"fault_injection": {"enabled": "yes"}},
    {"fault_injection": {"seed": "abc"}},
    # negative retry budgets and degenerate detector windows
    {"supervisor": {"enabled": True, "max_rollbacks": -1}},
    {"supervisor": {"max_rollbacks": True}},
    {"supervisor": {"nonfinite_window": 0}},
    {"supervisor": {"spike_window": 1}},
    {"supervisor": {"min_history": 0}},
    {"supervisor": {"spike_factor": -0.5}},
    {"supervisor": {"enabled": "on"}},
])
def test_resilience_self_healing_rejects(block):
    from deepspeed_tpu.config.config import DeepSpeedConfigError

    with pytest.raises(DeepSpeedConfigError):
        _res(block)


# ---------------------------------------------------------------------------
# inference self-healing keys: deadlines, restart budget, degraded ratio
# ---------------------------------------------------------------------------
def _inf(block):
    return make({"train_batch_size": 8, "inference": block})


def test_inference_self_healing_defaults():
    cfg = make({"train_batch_size": 8})
    assert cfg.inference_deadline_secs is None
    assert cfg.inference_driver_restart_budget == 0
    assert cfg.inference_degraded_queue_ratio == 0.75


def test_inference_self_healing_valid_block_parses():
    cfg = _inf({"deadline_secs": 2.5, "driver_restart_budget": 3,
                "degraded_queue_ratio": 0.5})
    assert cfg.inference_deadline_secs == 2.5
    assert cfg.inference_driver_restart_budget == 3
    assert cfg.inference_degraded_queue_ratio == 0.5


@pytest.mark.parametrize("block", [
    {"deadline_secs": 0},      # deadline values <= 0 rejected
    {"deadline_secs": -1.0},
    {"deadline_secs": "1s"},
    {"driver_restart_budget": -1},
    {"driver_restart_budget": 1.5},
    {"driver_restart_budget": True},
    {"degraded_queue_ratio": 0},
    {"degraded_queue_ratio": 1.2},
    {"degraded_queue_ratio": "half"},
])
def test_inference_self_healing_rejects(block):
    from deepspeed_tpu.config.config import DeepSpeedConfigError

    with pytest.raises(DeepSpeedConfigError):
        _inf(block)


# ---------------------------------------------------------------------------
# paged KV cache + prefix cache keys (docs/inference.md "Paged KV cache")
# ---------------------------------------------------------------------------
def test_paged_kv_defaults_are_contiguous():
    cfg = make({"train_batch_size": 8})
    assert cfg.inference_kv_block_size == 0
    assert cfg.inference_kv_pool_blocks == 0
    assert cfg.inference_prefix_cache_enabled is None
    assert cfg.inference_prefix_cache_suffix_buckets is None


def test_paged_kv_valid_block_parses():
    cfg = _inf({"max_seq_len": 256, "kv_block_size": 32,
                "kv_pool_blocks": 40,
                "prefix_cache": {"enabled": True,
                                 "suffix_buckets": [16, 32, 64]}})
    assert cfg.inference_kv_block_size == 32
    assert cfg.inference_kv_pool_blocks == 40
    assert cfg.inference_prefix_cache_enabled is True
    assert cfg.inference_prefix_cache_suffix_buckets == [16, 32, 64]


@pytest.mark.parametrize("block", [
    {"kv_block_size": -1},
    {"kv_block_size": 16.0},
    {"kv_block_size": True},
    {"kv_pool_blocks": -4},
    {"kv_pool_blocks": "many"},
    {"kv_pool_blocks": 8},                     # pool without a page size
    {"max_seq_len": 100, "kv_block_size": 32}, # not a multiple
    {"prefix_cache": {"enabled": True}},       # prefix cache needs paging
    {"prefix_cache": {"suffix_buckets": [16]}},  # buckets need paging too
    {"kv_block_size": 32, "max_seq_len": 64,
     "prefix_cache": {"enabled": "yes"}},
    {"kv_block_size": 32, "max_seq_len": 64,
     "prefix_cache": {"suffix_buckets": []}},
    {"kv_block_size": 32, "max_seq_len": 64,
     "prefix_cache": {"suffix_buckets": [64, 16]}},   # not ascending
    {"kv_block_size": 32, "max_seq_len": 64,
     "prefix_cache": {"suffix_buckets": [0, 16]}},
    {"kv_block_size": 32, "max_seq_len": 64,
     "prefix_cache": {"suffix_buckets": 32}},
])
def test_paged_kv_rejects(block):
    from deepspeed_tpu.config.config import DeepSpeedConfigError

    with pytest.raises(DeepSpeedConfigError):
        _inf(block)


# ---------------------------------------------------------------------------
# fused decode + speculative decoding keys (docs/inference.md "Fused
# decode attention" / "Speculative decoding")
# ---------------------------------------------------------------------------
def test_fused_and_speculative_defaults_off():
    cfg = make({"train_batch_size": 8})
    assert cfg.inference_fused_decode is False
    assert cfg.inference_speculative_enabled is False
    assert cfg.inference_speculative_k == 4
    assert cfg.inference_speculative_draft_checkpoint == ""


def test_fused_and_speculative_valid_block_parses():
    cfg = _inf({
        "max_seq_len": 256, "kv_block_size": 32,
        "fused_decode": True,
        "speculative": {"k": 6, "draft_checkpoint": "/ckpts/draft"},
    })
    assert cfg.inference_fused_decode is True
    assert cfg.inference_speculative_enabled is True
    assert cfg.inference_speculative_k == 6
    assert cfg.inference_speculative_draft_checkpoint == "/ckpts/draft"


def test_speculative_empty_block_enables_with_defaults():
    cfg = _inf({"max_seq_len": 256, "kv_block_size": 32,
                "speculative": {}})
    assert cfg.inference_speculative_enabled is True
    assert cfg.inference_speculative_k == 4


@pytest.mark.parametrize("block", [
    {"fused_decode": "yes"},
    {"fused_decode": 1},
    {"fused_decode": True},                       # fused needs paging
    {"speculative": {}},                          # speculative needs paging
    {"max_seq_len": 256, "kv_block_size": 32,
     "speculative": {"k": 0}},
    {"max_seq_len": 256, "kv_block_size": 32,
     "speculative": {"k": -2}},
    {"max_seq_len": 256, "kv_block_size": 32,
     "speculative": {"k": True}},
    {"max_seq_len": 256, "kv_block_size": 32,
     "speculative": {"k": 2.5}},
    {"max_seq_len": 256, "kv_block_size": 32,
     "speculative": {"draft_checkpoint": 7}},
    {"max_seq_len": 256, "kv_block_size": 32,
     "speculative": {"kk": 4}},                   # typo'd key
    {"max_seq_len": 256, "kv_block_size": 32,
     "speculative": {"k": 4, "draft": "x"}},      # unknown key
])
def test_fused_and_speculative_rejects(block):
    from deepspeed_tpu.config.config import DeepSpeedConfigError

    with pytest.raises(DeepSpeedConfigError):
        _inf(block)


# ---------------------------------------------------------------------------
# adapters block: multi-tenant LoRA geometry (docs/adapters.md)
# ---------------------------------------------------------------------------
def _ada(block):
    return make({"train_batch_size": 8, "adapters": block})


def test_adapters_defaults():
    cfg = make({"train_batch_size": 8})
    assert cfg.adapters_enabled is False
    assert cfg.adapters_rank == 8
    assert cfg.adapters_alpha == 0.0
    assert cfg.adapters_targets is None
    assert cfg.adapters_pool_slots == 8


def test_adapters_valid_block_parses():
    cfg = _ada({
        "enabled": True,
        "rank": 4,
        "alpha": 16.0,
        "targets": ["attn_qkvw", "attn_ow"],
        "pool_slots": 32,
    })
    assert cfg.adapters_enabled is True
    assert cfg.adapters_rank == 4
    assert cfg.adapters_alpha == 16.0
    assert cfg.adapters_targets == ["attn_qkvw", "attn_ow"]
    assert cfg.adapters_pool_slots == 32


@pytest.mark.parametrize("block", [
    {"enabled": "yes"},
    {"rank": 0},
    {"rank": -2},
    {"rank": 2.5},
    {"rank": True},
    {"alpha": -1.0},
    {"alpha": "big"},
    {"targets": []},                       # empty = adapts nothing
    {"targets": "attn_qkvw"},              # bare string would iterate chars
    {"targets": ["attn_qkvw", "wte"]},     # not an adaptable matrix
    {"targets": ["attn_qkvw", "attn_qkvw"]},
    {"targets": [1]},
    {"pool_slots": 0},
    {"pool_slots": -1},
    {"pool_slots": True},
])
def test_adapters_rejects(block):
    from deepspeed_tpu.config.config import DeepSpeedConfigError

    with pytest.raises(DeepSpeedConfigError):
        _ada(block)


# ---------------------------------------------------------------------------
# serving block: fleet size, placement, admission limits (docs/serving.md)
# ---------------------------------------------------------------------------
def _srv(block):
    return make({"train_batch_size": 8, "serving": block})


def test_serving_defaults():
    cfg = make({"train_batch_size": 8})
    assert cfg.serving_replicas == 1
    assert cfg.serving_backend == "in_process"
    assert cfg.serving_placement == "least_loaded"
    assert cfg.serving_affinity_prefix_tokens == 16
    assert cfg.serving_capacity_floor == 0.5
    assert cfg.serving_shed_queue_ratio == 0.75
    assert cfg.serving_max_reroutes == 2
    assert cfg.serving_drain_on_preemption is False
    assert cfg.serving_rate_limit_rps is None
    assert cfg.serving_rate_limit_burst == 1
    assert cfg.serving_rate_limit_per_tenant == {}
    assert cfg.serving_rpc_timeout_secs == 10.0
    assert cfg.serving_rpc_retries == 2
    assert cfg.serving_rpc_backoff_secs == 0.05
    assert cfg.serving_zombie_secs == 0.0  # zombie sweep off by default
    assert cfg.serving_zombie_restart_budget == 2
    assert cfg.serving_cb_failure_threshold == 3
    assert cfg.serving_cb_backoff_secs == 0.5
    assert cfg.serving_cb_backoff_max_secs == 30.0
    assert cfg.serving_brownout_queue_ratio is None  # brownout off
    assert cfg.serving_brownout_max_new_tokens == 16
    assert cfg.serving_http_auth_token is None  # open door
    assert cfg.serving_slo_ttft_p99_ms is None  # no SLO targets
    assert cfg.serving_slo_token_p99_ms is None
    assert cfg.serving_slo_eval_window_secs == 60.0
    assert cfg.serving_autoscale_enabled is False  # passthrough
    assert cfg.serving_autoscale_min_replicas == 1
    assert cfg.serving_autoscale_max_replicas == 4
    assert cfg.serving_autoscale_cooldown_secs == 30.0
    assert cfg.serving_autoscale_hysteresis_secs == 60.0
    assert cfg.serving_autoscale_flap_budget == 4
    assert cfg.serving_autoscale_flap_window_secs == 600.0
    assert cfg.serving_autoscale_up_utilization == 0.85
    assert cfg.serving_autoscale_down_utilization == 0.30
    assert cfg.serving_autoscale_interval_secs == 1.0
    assert cfg.serving_autoscale_drain_timeout_secs == 30.0


def test_serving_slo_autoscale_auth_block_parses():
    cfg = _srv({
        "http": {"auth_token": "tok-123"},
        "slo": {"ttft_p99_ms": 250, "token_p99_ms": 40,
                "eval_window_secs": 30.0},
        "autoscale": {
            "enabled": True, "min_replicas": 2, "max_replicas": 8,
            "cooldown_secs": 10.0, "hysteresis_secs": 20.0,
            "flap_budget": 2, "flap_window_secs": 120.0,
            "scale_up_utilization": 0.7, "scale_down_utilization": 0.2,
            "interval_secs": 0.5, "drain_timeout_secs": 15.0,
        },
    })
    assert cfg.serving_http_auth_token == "tok-123"
    assert cfg.serving_slo_ttft_p99_ms == 250
    assert cfg.serving_slo_token_p99_ms == 40
    assert cfg.serving_slo_eval_window_secs == 30.0
    assert cfg.serving_autoscale_enabled is True
    assert cfg.serving_autoscale_min_replicas == 2
    assert cfg.serving_autoscale_max_replicas == 8
    assert cfg.serving_autoscale_cooldown_secs == 10.0
    assert cfg.serving_autoscale_hysteresis_secs == 20.0
    assert cfg.serving_autoscale_flap_budget == 2
    assert cfg.serving_autoscale_flap_window_secs == 120.0
    assert cfg.serving_autoscale_up_utilization == 0.7
    assert cfg.serving_autoscale_down_utilization == 0.2
    assert cfg.serving_autoscale_interval_secs == 0.5
    assert cfg.serving_autoscale_drain_timeout_secs == 15.0


def test_serving_valid_block_parses():
    cfg = _srv({
        "replicas": 4,
        "backend": "subprocess",
        "placement": "prefix_affinity",
        "affinity_prefix_tokens": 8,
        "capacity_floor": 0.25,
        "shed_queue_ratio": 0.9,
        "max_reroutes": 0,
        "drain_on_preemption": True,
        "rate_limit": {
            "requests_per_sec": 10.0,
            "burst": 5,
            "per_tenant": {"gold": {"requests_per_sec": 100}},
        },
        "rpc_timeout_secs": 2.5,
        "rpc_retries": 0,
        "rpc_backoff_secs": 0.2,
        "zombie_secs": 12.0,
        "zombie_restart_budget": 1,
        "circuit_breaker": {
            "failure_threshold": 1,
            "backoff_secs": 0.25,
            "backoff_max_secs": 8.0,
        },
        "brownout": {"queue_ratio": 0.4, "max_new_tokens": 8},
    })
    assert cfg.serving_rpc_timeout_secs == 2.5
    assert cfg.serving_rpc_retries == 0
    assert cfg.serving_rpc_backoff_secs == 0.2
    assert cfg.serving_zombie_secs == 12.0
    assert cfg.serving_zombie_restart_budget == 1
    assert cfg.serving_cb_failure_threshold == 1
    assert cfg.serving_cb_backoff_secs == 0.25
    assert cfg.serving_cb_backoff_max_secs == 8.0
    assert cfg.serving_brownout_queue_ratio == 0.4
    assert cfg.serving_brownout_max_new_tokens == 8
    assert cfg.serving_replicas == 4
    assert cfg.serving_backend == "subprocess"
    assert cfg.serving_placement == "prefix_affinity"
    assert cfg.serving_affinity_prefix_tokens == 8
    assert cfg.serving_capacity_floor == 0.25
    assert cfg.serving_max_reroutes == 0
    assert cfg.serving_drain_on_preemption is True
    assert cfg.serving_rate_limit_rps == 10.0
    assert cfg.serving_rate_limit_per_tenant == {
        "gold": {"requests_per_sec": 100}
    }


@pytest.mark.parametrize("block", [
    {"replicas": 0},
    {"replicas": -2},
    {"replicas": 1.5},
    {"replicas": True},
    {"backend": "thread"},          # unknown isolation backend
    {"placement": "random"},        # unknown placement policy
    {"affinity_prefix_tokens": 0},
    {"capacity_floor": 1.0},        # floor 1 => nothing could ever drain
    {"capacity_floor": -0.1},
    {"capacity_floor": "half"},
    {"shed_queue_ratio": 0},
    {"shed_queue_ratio": 1.5},
    {"max_reroutes": -1},
    {"max_reroutes": True},
    {"drain_on_preemption": "yes"},
    {"rate_limit": {"requests_per_second": 10}},  # typo'd key != unlimited
    {"rate_limit": {"requests_per_sec": 0}},
    {"rate_limit": {"requests_per_sec": -1}},
    {"rate_limit": {"burst": 0}},
    {"rate_limit": {"per_tenant": "gold"}},
    {"rate_limit": {"per_tenant": {"gold": "fast"}}},
    {"rate_limit": {"per_tenant": {"gold": {"rps": 1}}}},  # unknown key
    {"rate_limit": {"per_tenant": {"gold": {"requests_per_sec": 0}}}},
    {"rate_limit": {"per_tenant": {"gold": {"burst": 0}}}},
    {"rpc_timeout_secs": 0},
    {"rpc_timeout_secs": "fast"},
    {"rpc_retries": -1},
    {"rpc_retries": True},
    {"rpc_backoff_secs": 0},
    {"zombie_secs": -1},
    {"zombie_secs": "never"},
    {"zombie_restart_budget": -1},
    {"zombie_restart_budget": 1.5},
    {"circuit_breaker": {"threshold": 3}},        # typo'd key
    {"circuit_breaker": {"failure_threshold": 0}},
    {"circuit_breaker": {"backoff_secs": 0}},
    {"circuit_breaker": {"backoff_max_secs": -1}},
    {"circuit_breaker": {"backoff_secs": 5.0, "backoff_max_secs": 1.0}},
    {"brownout": {"ratio": 0.5}},                 # typo'd key != off
    {"brownout": {"queue_ratio": 0}},
    {"brownout": {"queue_ratio": 1.0}},           # must sit below shed
    {"brownout": {"queue_ratio": 0.8}},           # >= default shed 0.75
    {"brownout": {"queue_ratio": 0.5, "max_new_tokens": 0}},
    {"shed_queue_ratio": 0.5, "brownout": {"queue_ratio": 0.5}},
    {"http": {"auth_token": ""}},               # empty secret != open door
    {"http": {"auth_token": 123}},
    {"http": {"token": "x"}},                   # typo'd key
    {"slo": {"ttft_p99": 250}},                 # typo'd key != no SLO
    {"slo": {"ttft_p99_ms": 0}},
    {"slo": {"ttft_p99_ms": -5}},
    {"slo": {"ttft_p99_ms": True}},
    {"slo": {"token_p99_ms": 0}},
    {"slo": {"eval_window_secs": 0}},
    {"slo": {"eval_window_secs": "soon"}},
    {"autoscale": {"enable": True}},            # typo'd key != enabled
    {"autoscale": {"enabled": "yes"}},
    {"autoscale": {"min_replicas": 0}},
    {"autoscale": {"min_replicas": True}},
    {"autoscale": {"max_replicas": 0}},
    {"autoscale": {"min_replicas": 3, "max_replicas": 2}},
    {"autoscale": {"cooldown_secs": 0}},
    {"autoscale": {"hysteresis_secs": -1}},
    {"autoscale": {"flap_budget": -1}},
    {"autoscale": {"flap_budget": 1.5}},
    {"autoscale": {"flap_window_secs": 0}},
    {"autoscale": {"scale_up_utilization": 0}},
    {"autoscale": {"scale_up_utilization": 1.5}},
    {"autoscale": {"scale_down_utilization": 0}},
    # inverted bands would oscillate on every tick
    {"autoscale": {"scale_up_utilization": 0.3,
                   "scale_down_utilization": 0.5}},
    {"autoscale": {"interval_secs": 0}},
    {"autoscale": {"drain_timeout_secs": 0}},
])
def test_serving_rejects(block):
    from deepspeed_tpu.config.config import DeepSpeedConfigError

    with pytest.raises(DeepSpeedConfigError):
        _srv(block)


# ---------------------------------------------------------------------------
# serving.provisioner block: the whole-node lifecycle tier
# (docs/serving.md "Node failure domain")
# ---------------------------------------------------------------------------
def test_serving_provisioner_defaults_off():
    cfg = make({"train_batch_size": 8})
    assert cfg.serving_provisioner_enabled is False
    assert cfg.serving_provisioner_node_spec is None
    assert cfg.serving_provisioner_max_nodes == 4
    assert cfg.serving_provisioner_max_replicas_per_node == 4
    assert cfg.serving_provisioner_launch_timeout_secs == 120.0
    assert cfg.serving_provisioner_terminate_grace_secs == 5.0


def test_serving_provisioner_block_parses():
    spec = {"replicas": {}, "spawn_spec": {"stub": {"delay_secs": 0.01}}}
    cfg = _srv({"provisioner": {
        "enabled": True,
        "node_spec": spec,
        "max_nodes": 2,
        "max_replicas_per_node": 8,
        "launch_timeout_secs": 30.0,
        "terminate_grace_secs": 1.5,
    }})
    assert cfg.serving_provisioner_enabled is True
    assert cfg.serving_provisioner_node_spec == spec
    assert cfg.serving_provisioner_max_nodes == 2
    assert cfg.serving_provisioner_max_replicas_per_node == 8
    assert cfg.serving_provisioner_launch_timeout_secs == 30.0
    assert cfg.serving_provisioner_terminate_grace_secs == 1.5


@pytest.mark.parametrize("block", [
    {"provisioner": {"enable": True}},          # typo'd key != enabled
    {"provisioner": {"enabled": "yes"}},
    {"provisioner": {"enabled": 1}},
    {"provisioner": {"node_spec": "node.json"}},  # path != spec object
    {"provisioner": {"node_spec": ["r0"]}},
    {"provisioner": {"max_nodes": 0}},
    {"provisioner": {"max_nodes": -1}},
    {"provisioner": {"max_nodes": 2.5}},
    {"provisioner": {"max_nodes": True}},
    {"provisioner": {"max_replicas_per_node": 0}},
    {"provisioner": {"max_replicas_per_node": True}},
    {"provisioner": {"launch_timeout_secs": 0}},
    {"provisioner": {"launch_timeout_secs": "fast"}},
    {"provisioner": {"launch_timeout_secs": True}},
    {"provisioner": {"terminate_grace_secs": 0}},
    {"provisioner": {"terminate_grace_secs": -1}},
])
def test_serving_provisioner_rejects(block):
    from deepspeed_tpu.config.config import DeepSpeedConfigError

    with pytest.raises(DeepSpeedConfigError):
        _srv(block)


# ---------------------------------------------------------------------------
# telemetry.tracing keys (docs/observability.md "Request tracing &
# flight recorder")
# ---------------------------------------------------------------------------
def _trc(block):
    return make({
        "train_batch_size": 8,
        "telemetry": {"enabled": True, "tracing": block},
    })


def test_tracing_defaults_are_off():
    cfg = make({"train_batch_size": 8})
    assert cfg.telemetry_tracing_enabled is False
    assert cfg.telemetry_tracing_sample_rate == 1.0
    assert cfg.telemetry_tracing_ring_events == 512
    assert cfg.telemetry_tracing_export == "chrome"


def test_tracing_valid_block_parses():
    cfg = _trc({"enabled": True, "sample_rate": 0.25,
                "ring_events": 2048, "export": "none"})
    assert cfg.telemetry_tracing_enabled is True
    assert cfg.telemetry_tracing_sample_rate == 0.25
    assert cfg.telemetry_tracing_ring_events == 2048
    assert cfg.telemetry_tracing_export == "none"


def test_tracing_rides_the_telemetry_master_switch():
    # tracing under a disabled telemetry block is inert, like the watchdog
    cfg = make({
        "train_batch_size": 8,
        "telemetry": {"enabled": False, "tracing": {"enabled": True}},
    })
    assert cfg.telemetry_tracing_enabled is False


@pytest.mark.parametrize("block", [
    {"sample_rate": -0.1},
    {"sample_rate": 1.5},
    {"sample_rate": "half"},
    {"sample_rate": True},
    {"ring_events": 0},
    {"ring_events": -5},
    {"ring_events": 1.5},
    {"ring_events": True},
    {"export": "jaeger"},
    {"sample_rat": 0.5},   # a typo'd key must not mean "sample everything"
])
def test_tracing_rejects(block):
    with pytest.raises(DeepSpeedConfigError):
        _trc(block)
