"""OneCycle momentum cycling applied through the engine (VERDICT r04 #4).

The reference mutates optimizer momentum groups each step
(deepspeed/pt/deepspeed_lr_schedules.py:477-520: betas[0] for Adam-family,
``momentum`` for SGD-style). Here the engine threads the scheduler's
``get_mom()`` into the jitted update as a traced scalar (like lr), so the
cycle never recompiles. Two tiers of evidence:

- optimizer-level: ``apply(..., mom=x)`` is bit-equivalent to an optimizer
  constructed with that coefficient statically;
- engine-level: the effective beta reported by ``engine.get_mom()`` follows
  the configured cycle across steps, and cycling measurably changes the
  parameter trajectory vs ``cycle_momentum=False``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.optimizers import SGD, Adam, Lamb
from deepspeed_tpu.runtime.lr_schedules import OneCycle
from tests.unit.simple_model import SimpleModel, config_dict, init_model, random_dataset

INPUT_DIM = 16


def _tiny_tree(seed=0):
    rng = np.random.default_rng(seed)
    params = {
        "w": jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(4,)).astype(np.float32)),
    }
    grads = {
        "w": jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(4,)).astype(np.float32)),
    }
    return params, grads


@pytest.mark.parametrize("opt_cls", [Adam, Lamb])
def test_mom_override_matches_static_b1(opt_cls):
    params, grads = _tiny_tree()
    dynamic = opt_cls(b1=0.9)
    static = opt_cls(b1=0.85)
    state_d = dynamic.init(params)
    state_s = static.init(params)
    lr = jnp.float32(1e-2)
    p_d, s_d, _ = dynamic.apply(
        params, grads, state_d, lr, mom=jnp.float32(0.85)
    )
    p_s, s_s, _ = static.apply(params, grads, state_s, lr)
    for a, b in zip(
        jax.tree_util.tree_leaves((p_d, s_d["mu"], s_d["nu"])),
        jax.tree_util.tree_leaves((p_s, s_s["mu"], s_s["nu"])),
    ):
        # traced-scalar vs constant-folded b1 can differ by ~1 ulp through
        # the bias-correction power; numerically identical otherwise
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-6, atol=1e-6
        )


def test_sgd_mom_override_matches_static_momentum():
    params, grads = _tiny_tree()
    dynamic = SGD(momentum=0.9)
    static = SGD(momentum=0.7)
    lr = jnp.float32(1e-2)
    p_d, s_d, _ = dynamic.apply(
        params, grads, dynamic.init(params), lr, mom=jnp.float32(0.7)
    )
    p_s, s_s, _ = static.apply(params, grads, static.init(params), lr)
    for a, b in zip(
        jax.tree_util.tree_leaves((p_d, s_d["mom"])),
        jax.tree_util.tree_leaves((p_s, s_s["mom"])),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_mom_none_is_default_path():
    params, grads = _tiny_tree()
    opt = Adam(b1=0.9)
    lr = jnp.float32(1e-2)
    p_a, s_a, _ = opt.apply(params, grads, opt.init(params), lr)
    p_b, s_b, _ = opt.apply(
        params, grads, opt.init(params), lr, mom=jnp.float32(0.9)
    )
    for a, b in zip(
        jax.tree_util.tree_leaves((p_a, s_a["mu"])),
        jax.tree_util.tree_leaves((p_b, s_b["mu"])),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


# ---------------------------------------------------------------------------
# engine tier
# ---------------------------------------------------------------------------
ONE_CYCLE_CFG = {
    "type": "OneCycle",
    "params": {
        "cycle_min_lr": 1e-2,
        "cycle_max_lr": 2e-2,
        "cycle_first_step_size": 5,
        "cycle_min_mom": 0.5,
        "cycle_max_mom": 0.9,
    },
}


def _build(cycle_momentum=True, optimizer="Adam"):
    cfg = config_dict(batch_size=16, optimizer=optimizer)
    cfg["scheduler"] = {
        "type": "OneCycle",
        "params": dict(
            ONE_CYCLE_CFG["params"], cycle_momentum=cycle_momentum
        ),
    }
    model = SimpleModel(hidden_dim=32)
    params = init_model(model, INPUT_DIM)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg
    )
    return engine


@pytest.mark.slow
def test_engine_effective_beta_follows_cycle():
    engine = _build()
    ref_sched = OneCycle(**ONE_CYCLE_CFG["params"])
    x, y = random_dataset(16 * 8, INPUT_DIM)
    seen = []
    for b in range(8):
        xb, yb = x[b * 16 : (b + 1) * 16], y[b * 16 : (b + 1) * 16]
        # the value consumed by THIS step's update (pre-advance, like lr)
        seen.append(engine.get_mom()[0])
        loss = engine(xb, yb)
        engine.backward(loss)
        engine.step()
        ref_sched.step()
    # first step uses max mom; the up-phase then walks toward min mom
    assert seen[0] == pytest.approx(0.9, abs=1e-6)
    assert seen[4] < seen[1]  # momentum cycles DOWN while lr cycles up
    # exact parity with the standalone schedule
    ref2 = OneCycle(**ONE_CYCLE_CFG["params"])
    for i, m in enumerate(seen):
        assert m == pytest.approx(ref2.get_mom(), abs=1e-9), f"step {i}"
        ref2.step()


@pytest.mark.slow
def test_engine_cycling_changes_trajectory():
    eng_a = _build(cycle_momentum=True)
    eng_b = _build(cycle_momentum=False)
    x, y = random_dataset(16 * 6, INPUT_DIM)
    for b in range(6):
        xb, yb = x[b * 16 : (b + 1) * 16], y[b * 16 : (b + 1) * 16]
        for eng in (eng_a, eng_b):
            loss = eng(xb, yb)
            eng.backward(loss)
            eng.step()
    la = jax.tree_util.tree_leaves(eng_a.params)
    lb = jax.tree_util.tree_leaves(eng_b.params)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(la, lb)
    ), "momentum cycling had no effect on the update"


@pytest.mark.slow
def test_engine_mom_constant_without_scheduler():
    cfg = config_dict(batch_size=16)
    model = SimpleModel(hidden_dim=32)
    params = init_model(model, INPUT_DIM)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg
    )
    assert engine.get_mom() == [pytest.approx(0.9)]  # Adam default b1
