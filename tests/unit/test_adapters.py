"""Multi-tenant LoRA adapter tests (deepspeed_tpu/adapters/,
docs/adapters.md): adapter-off bitwise parity, rank-0/id-0 identity, the
frozen-base fine-tune contract, mixed-adapter batched decode parity, the
zero-recompile pin across adapter mix changes, adapter checkpoint
save/load through the verified path, pool eviction/refcounts, the
adapter-salted prefix cache, partition-spec placement, and the
_check_adapters validation matrix."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.adapters import (
    AdapterPool,
    AdapterPoolFull,
    adapter_layer_stacks,
    adapter_num_params,
    init_lora_params,
    merge_lora_params,
    split_lora_params,
)
from deepspeed_tpu.config.config import DeepSpeedConfigError
from deepspeed_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHeadModel,
    adapter_pool_partition_specs,
    partition_specs,
)

VOCAB = 97


def _small_model(seed=0, **kw):
    cfg = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        dropout=0.0, use_flash=False, **kw,
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jnp.asarray(
        np.random.default_rng(seed).integers(0, VOCAB, (1, 8)), jnp.int32
    )
    params = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        ids0, ids0,
    )["params"]
    return cfg, model, params


def _prompt(n=8, seed=1):
    return [int(t) for t in np.random.default_rng(seed).integers(0, VOCAB, n)]


def _synth_adapter(params, seed, rank=2, scale=0.2):
    """A synthetic NONZERO adapter (random A and B): behaves differently
    from the base model, which is what serving tests need to observe."""
    ada = init_lora_params(
        jax.tree_util.tree_map(np.asarray, params), rank,
        rng=jax.random.PRNGKey(seed),
    )
    return jax.tree_util.tree_map(
        lambda a: np.asarray(
            jax.random.normal(
                jax.random.fold_in(jax.random.PRNGKey(seed), a.size),
                a.shape,
            ) * scale,
            np.float32,
        ),
        ada,
    )


def _lora_engine(model, params, inference=None, adapters=None):
    block = {"max_batch_slots": 3, "max_seq_len": 48, "prefill_len": 16,
             "sampling": {"greedy": True}}
    block.update(inference or {})
    ad = {"enabled": True, "rank": 2, "pool_slots": 4}
    ad.update(adapters or {})
    return deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config={"inference": block, "adapters": ad},
    )


# ---------------------------------------------------------------------------
# pytree surgery
# ---------------------------------------------------------------------------
def test_split_merge_roundtrip_and_fresh_init_shapes():
    _cfg, _model, params = _small_model(lora_rank=3)
    base, adapters = split_lora_params(params)
    assert adapters, "flax-created lora leaves must split out"
    assert all(
        "_lora_" not in str(p[-1].key)
        for p, _ in jax.tree_util.tree_flatten_with_path(base)[0]
    )
    rebuilt = merge_lora_params(base, adapters)
    assert jax.tree_util.tree_structure(rebuilt) == (
        jax.tree_util.tree_structure(params)
    )
    for (kp, a), (_kq, b) in zip(
        jax.tree_util.tree_flatten_with_path(rebuilt)[0],
        jax.tree_util.tree_flatten_with_path(params)[0],
    ):
        assert a is b, kp
    # fresh growth beside a rank-0 base: same leaf names/shapes as flax's
    _c0, _m0, base0 = _small_model()
    fresh = init_lora_params(base0, 3)
    assert jax.tree_util.tree_structure(fresh) == (
        jax.tree_util.tree_structure(adapters)
    )
    for (kp, a), (_kq, b) in zip(
        jax.tree_util.tree_flatten_with_path(fresh)[0],
        jax.tree_util.tree_flatten_with_path(adapters)[0],
    ):
        assert a.shape == b.shape, kp
    stacks = adapter_layer_stacks(fresh)
    assert stacks["attn_qkvw"][0].shape == (2, 32, 3)
    assert stacks["attn_qkvw"][1].shape == (2, 3, 96)
    assert stacks["output_w"][0].shape == (2, 128, 3)


def test_init_lora_params_rejects_bad_rank_and_missing_targets():
    _cfg, _model, params = _small_model()
    with pytest.raises(ValueError, match="rank"):
        init_lora_params(params, 0)
    with pytest.raises(ValueError, match="unknown LoRA target"):
        init_lora_params(params, 2, targets=("attn_qkvw", "nope"))
    with pytest.raises(ValueError, match="no LoRA target"):
        init_lora_params({"x": np.zeros((4, 4))}, 2)


# ---------------------------------------------------------------------------
# adapter-off / identity parity
# ---------------------------------------------------------------------------
def test_fresh_adapter_forward_bitwise_matches_base():
    """B = 0 at init => the merged rank-r forward IS the base forward,
    bit for bit (the adapter-off parity contract)."""
    cfg0, model0, params = _small_model()
    ids = jnp.asarray([_prompt(12, seed=3)], jnp.int32)
    base_logits = model0.apply({"params": params}, ids, train=False)
    cfg_r = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        dropout=0.0, use_flash=False, lora_rank=4,
    )
    merged = merge_lora_params(
        params, init_lora_params(params, 4, rng=jax.random.PRNGKey(9))
    )
    lora_logits = GPT2LMHeadModel(cfg_r).apply(
        {"params": merged}, ids, train=False
    )
    assert np.array_equal(np.asarray(base_logits), np.asarray(lora_logits))


def test_id0_decode_bitwise_matches_adapter_free_engine():
    """A multi-LoRA engine serving a request WITHOUT an adapter (id 0 =
    all-zeros identity rows) generates bitwise what an engine with no
    adapter pool at all generates."""
    _cfg, model, params = _small_model()
    prompt = _prompt(9, seed=5)
    plain = deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config={"inference": {
            "max_batch_slots": 3, "max_seq_len": 48, "prefill_len": 16,
            "sampling": {"greedy": True},
        }},
    )
    base = plain.generate([prompt], max_new_tokens=10)[0]
    plain.close()
    eng = _lora_engine(model, params)
    assert eng.generate([prompt], max_new_tokens=10)[0] == base
    eng.close()


# ---------------------------------------------------------------------------
# fine-tune path: frozen base, adapter-only optimizer state, checkpoints
# ---------------------------------------------------------------------------
def _finetune_engine(model, params, tmpdir=None, lr=0.1, extra=None):
    config = {
        "train_batch_size": 8,  # conftest meshes 8 virtual CPU devices
        "optimizer": {"type": "adam", "params": {"lr": lr}},
        "adapters": {"enabled": True, "rank": 2},
    }
    config.update(extra or {})
    engine, _opt, _dl, _sched = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=config,
    )
    return engine


def test_finetune_updates_only_adapters_base_bitwise_frozen():
    _cfg, model, params = _small_model()
    before = jax.tree_util.tree_map(np.asarray, params)
    engine = _finetune_engine(model, params)
    ids = jnp.asarray(
        np.random.default_rng(2).integers(0, VOCAB, (8, 16)), jnp.int32
    )
    # trainable tree is the adapter leaves alone — no base params, so no
    # base optimizer state either
    leaf_names = {
        str(p[-1].key)
        for p, _ in jax.tree_util.tree_flatten_with_path(engine.params)[0]
    }
    assert leaf_names and all("_lora_" in n for n in leaf_names)
    losses = [float(engine.train_batch([(ids, ids)])) for _ in range(3)]
    assert losses[-1] < losses[0], losses
    frozen = jax.tree_util.tree_map(
        np.asarray, engine.frozen_base_params
    )
    for (kp, a), (_kq, b) in zip(
        jax.tree_util.tree_flatten_with_path(frozen)[0],
        jax.tree_util.tree_flatten_with_path(before)[0],
    ):
        assert np.array_equal(a, b.astype(a.dtype)), kp
    # the adapters actually moved (B left zero)
    moved = jax.tree_util.tree_map(np.asarray, engine.params)
    b_leaves = [
        a for p, a in jax.tree_util.tree_flatten_with_path(moved)[0]
        if str(p[-1].key).endswith("_lora_b")
    ]
    assert any(np.any(b != 0) for b in b_leaves)


def test_finetune_model_config_mismatch_rejected():
    cfg, model, params = _small_model(lora_rank=3)
    with pytest.raises(DeepSpeedConfigError, match="lora_rank"):
        _finetune_engine(model, params)  # block asks rank 2, model says 3


def test_adapter_checkpoint_roundtrip_and_size(tmp_path):
    """Adapter-only checkpoints commit through the atomic protocol with
    a manifest, self-describe their geometry, resume exactly, and load
    into a serving pool through the verified path."""
    _cfg, model, params = _small_model()
    engine = _finetune_engine(model, params)
    ids = jnp.asarray(
        np.random.default_rng(2).integers(0, VOCAB, (8, 16)), jnp.int32
    )
    for _ in range(2):
        engine.train_batch([(ids, ids)])
    tuned = jax.tree_util.tree_map(np.asarray, engine.params)
    ckpt = str(tmp_path / "adapter_ckpt")
    assert engine.save_checkpoint(ckpt, tag="t1")
    assert os.path.exists(os.path.join(ckpt, "t1", "MANIFEST.json"))
    # resume: a fresh adapter engine loads the exact tuned tree
    _cfg2, model2, params2 = _small_model()
    engine2 = _finetune_engine(model2, params2)
    path, client_state = engine2.load_checkpoint(ckpt, tag="t1")
    assert path is not None
    assert client_state["adapters"]["rank"] == 2
    for (kp, a), (_kq, b) in zip(
        jax.tree_util.tree_flatten_with_path(
            jax.tree_util.tree_map(np.asarray, engine2.params)
        )[0],
        jax.tree_util.tree_flatten_with_path(tuned)[0],
    ):
        assert np.array_equal(a, b), kp
    # serving: the checkpoint loads into a pool row and changes outputs
    eng = _lora_engine(model, params)
    eng.load_adapter("tenant", load_dir=ckpt)
    prompt = _prompt(9, seed=5)
    out_t = eng.generate([prompt], max_new_tokens=10, adapter="tenant")[0]
    out_0 = eng.generate([prompt], max_new_tokens=10)[0]
    assert out_t != out_0, "fine-tuned adapter did not change decode"
    # geometry mismatch (rank-3 pool vs rank-2 checkpoint) fails loudly
    eng3 = _lora_engine(model, params, adapters={"rank": 3})
    with pytest.raises(DeepSpeedConfigError, match="rank"):
        eng3.load_adapter("tenant", load_dir=ckpt)
    eng.close()
    eng3.close()


# ---------------------------------------------------------------------------
# batched multi-LoRA decode
# ---------------------------------------------------------------------------
def test_mixed_adapter_batch_bitwise_matches_single_slot_runs():
    """One fixed-shape decode program, three slots on three different
    adapters (including the base id 0): every slot's tokens bitwise-match
    a run where its adapter is alone in the batch."""
    _cfg, model, params = _small_model()
    prompt = _prompt(9, seed=5)
    eng = _lora_engine(model, params)
    eng.load_adapter("a", adapter_state=_synth_adapter(params, 1))
    eng.load_adapter("b", adapter_state=_synth_adapter(params, 2))
    solo_a = eng.generate([prompt], max_new_tokens=8, adapter="a")[0]
    solo_b = eng.generate([prompt], max_new_tokens=8, adapter="b")[0]
    solo_0 = eng.generate([prompt], max_new_tokens=8)[0]
    r_a = eng.submit(prompt, max_new_tokens=8, adapter="a")
    r_b = eng.submit(prompt, max_new_tokens=8, adapter="b")
    r_0 = eng.submit(prompt, max_new_tokens=8)
    eng.scheduler.run_until_idle()
    assert r_a.tokens == solo_a
    assert r_b.tokens == solo_b
    assert r_0.tokens == solo_0
    assert solo_a != solo_b and solo_a != solo_0
    eng.close()


@pytest.mark.parametrize("paged", [False, True])
def test_new_adapter_join_never_recompiles(paged):
    """The zero-recompile pin across adapter mix changes: after warmup,
    loading a NEVER-SEEN adapter and joining a request under it compiles
    nothing (ids are arrays; the pool row write is a traced index-put)."""
    _cfg, model, params = _small_model()
    inference = {"kv_block_size": 8} if paged else {}
    eng = _lora_engine(model, params, inference=inference)
    eng.load_adapter("warm", adapter_state=_synth_adapter(params, 1))
    prompt = _prompt(9, seed=5)
    eng.generate([prompt], max_new_tokens=6, adapter="warm")
    eng.generate([prompt], max_new_tokens=6)
    recompiles = eng.metrics.counter("jax/recompiles")
    warm = recompiles.value
    eng.load_adapter("cold", adapter_state=_synth_adapter(params, 3))
    r1 = eng.submit(prompt, max_new_tokens=6, adapter="cold")
    r2 = eng.submit(_prompt(7, seed=8), max_new_tokens=6, adapter="warm")
    eng.scheduler.run_until_idle()
    assert r1.tokens and r2.tokens
    assert recompiles.value == warm, (
        f"{recompiles.value - warm} recompiles after a new adapter joined"
    )
    eng.close()


def test_paged_decode_with_adapters_matches_contiguous():
    """Greedy multi-adapter decode is bitwise-identical across the two
    cache layouts (the paged path shares the decode core)."""
    _cfg, model, params = _small_model()
    prompt = _prompt(9, seed=5)
    outs = []
    for inference in ({}, {"kv_block_size": 8}):
        eng = _lora_engine(model, params, inference=inference)
        eng.load_adapter("a", adapter_state=_synth_adapter(params, 1))
        r1 = eng.submit(prompt, max_new_tokens=8, adapter="a")
        r2 = eng.submit(_prompt(6, seed=7), max_new_tokens=8)
        eng.scheduler.run_until_idle()
        outs.append((r1.tokens, r2.tokens))
        eng.close()
    assert outs[0] == outs[1]


def test_sgmv_kernel_matches_gathered_einsum():
    """The Pallas SGMV kernel (ops/decode_attention.py:lora_sgmv)
    reproduces the XLA gather path's per-slot delta to float tolerance,
    with the identity row contributing EXACT zeros — the primitive the
    fused multi-LoRA decode rides."""
    from deepspeed_tpu.ops.decode_attention import lora_sgmv

    rng = np.random.default_rng(5)
    b, din, r, dout, n = 4, 16, 2, 24, 3
    a_pool = np.asarray(rng.normal(size=(n + 1, din, r)), np.float32)
    b_pool = np.asarray(rng.normal(size=(n + 1, r, dout)), np.float32)
    a_pool[0] = 0.0
    b_pool[0] = 0.0
    x = np.asarray(rng.normal(size=(b, din)), np.float32)
    ids = np.asarray([2, 0, 3, 1], np.int32)
    out = np.asarray(lora_sgmv(
        jnp.asarray(x), jnp.asarray(a_pool), jnp.asarray(b_pool),
        jnp.asarray(ids),
    ))
    t = np.einsum("bi,bir->br", x, a_pool[ids])
    ref = np.einsum("br,bro->bo", t, b_pool[ids])
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
    assert np.all(out[1] == 0.0), "identity row must contribute exact 0"


def test_fused_decode_mixed_adapter_batch_matches_xla():
    """inference.fused_decode on a multi-LoRA engine: a batch mixing
    two adapters and the base model produces EXACTLY the XLA paged
    engine's greedy tokens (which are themselves pinned bitwise against
    the contiguous path) — the SGMV + flash-decode kernels change the
    arithmetic schedule, never the tokens."""
    _cfg, model, params = _small_model()
    ada = _synth_adapter(params, 1)
    adb = _synth_adapter(params, 2)
    outs = []
    for inference in (
        {"kv_block_size": 8},
        {"kv_block_size": 8, "fused_decode": True},
    ):
        eng = _lora_engine(model, params, inference=inference)
        eng.load_adapter("a", adapter_state=ada)
        eng.load_adapter("b", adapter_state=adb)
        r1 = eng.submit(_prompt(9, 5), max_new_tokens=8, adapter="a")
        r2 = eng.submit(_prompt(6, 7), max_new_tokens=8, adapter="b")
        r3 = eng.submit(_prompt(7, 9), max_new_tokens=8)  # base
        eng.scheduler.run_until_idle()
        outs.append((r1.tokens, r2.tokens, r3.tokens))
        eng.close()
    assert outs[0] == outs[1]


def test_fused_adapter_join_never_recompiles():
    """Adapter-mix changes stay recompile-free on the fused path: the
    SGMV kernel's ids are scalar-prefetch DATA, not shapes."""
    _cfg, model, params = _small_model()
    eng = _lora_engine(
        model, params,
        inference={"kv_block_size": 8, "fused_decode": True},
    )
    try:
        eng.load_adapter("a", adapter_state=_synth_adapter(params, 1))
        recompiles = eng.metrics.counter("jax/recompiles")
        eng.generate([_prompt(8, 1)], max_new_tokens=4, adapter="a")
        eng.generate([_prompt(8, 2)], max_new_tokens=4)
        warm = recompiles.value
        assert warm > 0
        # a NEVER-SEEN adapter joins mid-flight
        eng.load_adapter("z", adapter_state=_synth_adapter(params, 9))
        r1 = eng.submit(_prompt(5, 3), max_new_tokens=6, adapter="z")
        eng.scheduler.step()
        r2 = eng.submit(_prompt(6, 4), max_new_tokens=5, adapter="a")
        eng.scheduler.run_until_idle()
        assert r1.done and r2.done
        assert recompiles.value == warm, (
            f"fused adapter path recompiled: {recompiles.value - warm}"
        )
    finally:
        eng.close()


def test_prefix_cache_salted_by_adapter():
    """Prefix pages never share across adapters (or base<->adapter):
    cached k/v are a function of the weights that wrote them."""
    _cfg, model, params = _small_model()
    eng = _lora_engine(model, params, inference={"kv_block_size": 8})
    eng.load_adapter("a", adapter_state=_synth_adapter(params, 1))
    eng.load_adapter("b", adapter_state=_synth_adapter(params, 2))
    hits = eng.metrics.counter("infer/prefix_hits")
    misses = eng.metrics.counter("infer/prefix_misses")
    template = _prompt(8, seed=11)  # exactly one page
    p1 = template + _prompt(3, seed=12)
    p2 = template + _prompt(4, seed=13)
    eng.generate([p1], max_new_tokens=4, adapter="a")
    assert (hits.value, misses.value) == (0, 1)
    warm = eng.generate([p2], max_new_tokens=4, adapter="a")[0]
    assert (hits.value, misses.value) == (1, 1)  # same adapter: HIT
    eng.generate([p2], max_new_tokens=4, adapter="b")
    assert misses.value == 2  # other adapter: MISS despite same tokens
    eng.generate([p2], max_new_tokens=4)
    assert misses.value == 3  # base model: MISS too
    # the warm hit served the adapter's own pages: bitwise vs fresh cold
    eng2 = _lora_engine(model, params, inference={"kv_block_size": 8})
    eng2.load_adapter("a", adapter_state=_synth_adapter(params, 1))
    assert eng2.generate([p2], max_new_tokens=4, adapter="a")[0] == warm
    eng.close()
    eng2.close()


def test_adapter_reload_invalidates_its_old_prefix_pages():
    """Hot-reloading an adapter bumps its generation: pages its OLD
    weights wrote never match again (a stale-weight hit would silently
    serve the old model's k/v)."""
    _cfg, model, params = _small_model()
    eng = _lora_engine(model, params, inference={"kv_block_size": 8})
    eng.load_adapter("a", adapter_state=_synth_adapter(params, 1))
    misses = eng.metrics.counter("infer/prefix_misses")
    template = _prompt(8, seed=11)
    eng.generate([template + _prompt(3, seed=12)], max_new_tokens=4,
                 adapter="a")
    eng.load_adapter("a", adapter_state=_synth_adapter(params, 4))
    eng.generate([template + _prompt(4, seed=13)], max_new_tokens=4,
                 adapter="a")
    assert misses.value == 2  # reload => no stale hit
    eng.close()


# ---------------------------------------------------------------------------
# pool management / scheduler integration
# ---------------------------------------------------------------------------
def test_unknown_adapter_rejected_at_submit():
    _cfg, model, params = _small_model()
    eng = _lora_engine(model, params)
    with pytest.raises(ValueError, match="not loaded"):
        eng.submit(_prompt(), adapter="ghost")
    plain = deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config={"inference": {"max_batch_slots": 2, "max_seq_len": 48,
                              "prefill_len": 16}},
    )
    with pytest.raises(DeepSpeedConfigError, match="adapter"):
        plain.submit(_prompt(), adapter="any")
    plain.close()
    eng.close()


def test_pool_eviction_lru_and_snapshot_counters():
    _cfg, model, params = _small_model()
    eng = _lora_engine(model, params, adapters={"pool_slots": 2})
    eng.load_adapter("a", adapter_state=_synth_adapter(params, 1))
    eng.load_adapter("b", adapter_state=_synth_adapter(params, 2))
    prompt = _prompt(9, seed=5)
    eng.generate([prompt], max_new_tokens=4, adapter="a")  # a now MRU
    eng.load_adapter("c", adapter_state=_synth_adapter(params, 3))
    snap = eng.load_snapshot()
    assert snap["adapters_loaded"] == ["a", "c"]  # b was LRU: evicted
    assert snap["adapter_pool_used"] == 2
    assert snap["adapter_evictions"] == 1
    assert snap["adapter_requests"]["a"] == 1
    with pytest.raises(ValueError, match="not loaded"):
        eng.submit(prompt, adapter="b")
    eng.close()


def test_adapter_pool_refcounts_block_eviction_and_unload():
    pool = AdapterPool(2)
    pool.assign("a")
    pool.assign("b")
    pool.acquire("a")
    pool.acquire("b")
    with pytest.raises(AdapterPoolFull):
        pool.assign("c")  # both busy: nothing evictable
    with pytest.raises(RuntimeError, match="live"):
        pool.remove("a")
    pool.release("b")
    idx, evicted = pool.assign("c")  # b idle: evicted
    assert evicted == "b" and idx == pool.index_of("c")
    with pytest.raises(ValueError, match="no live"):
        pool.release("b")
    pool.release("a")
    assert pool.remove("a") in (1, 2)
    # reload bumps the generation (the prefix-salt input)
    g1 = pool.generation_of("c")
    pool.assign("c")
    assert pool.generation_of("c") > g1


def test_evicted_adapter_between_submit_and_join_fail_finishes():
    """An adapter evicted after submit but before slot join must fail
    that request loudly — never decode it against other weights."""
    _cfg, model, params = _small_model()
    eng = _lora_engine(model, params, adapters={"pool_slots": 2})
    eng.load_adapter("a", adapter_state=_synth_adapter(params, 1))
    req = eng.submit(_prompt(9, seed=5), max_new_tokens=4, adapter="a")
    eng.unload_adapter("a")
    eng.scheduler.run_until_idle()
    assert req.done and req.finish_reason == "error"
    eng.close()


# ---------------------------------------------------------------------------
# partition specs
# ---------------------------------------------------------------------------
def test_lora_partition_specs_ride_the_base_matrices_model_axis():
    from jax.sharding import PartitionSpec as P

    _cfg, _model, params = _small_model(lora_rank=2)
    specs = partition_specs(params)["transformer"]["h"]
    # column-parallel bases shard output dim -> B carries it, A replicates
    assert specs["attn_qkvw_lora_b"] == P(None, None, "model")
    assert specs["attn_qkvw_lora_a"] == P(None, None, None)
    assert specs["inter_w_lora_b"] == P(None, None, "model")
    # row-parallel bases shard input dim -> A carries it, B replicates
    assert specs["attn_ow_lora_a"] == P(None, "model", None)
    assert specs["attn_ow_lora_b"] == P(None, None, None)
    assert specs["output_w_lora_a"] == P(None, "model", None)
    pool_specs = adapter_pool_partition_specs()
    assert pool_specs["attn_qkvw"][1] == P(None, None, None, "model")
    assert pool_specs["attn_ow"][0] == P(None, None, "model", None)


def test_serving_rejects_lora_leaves_in_params():
    """Pool mode + *_lora_* leaves in the param tree would double-apply
    adapters; a mutated model CONFIG over a clean base tree is fine (the
    fine-tune engine arms the shared config in place)."""
    cfg, model, params = _small_model(lora_rank=2)
    with pytest.raises(DeepSpeedConfigError, match="BASE param tree"):
        _lora_engine(model, params)
    base, _ada = split_lora_params(params)
    eng = _lora_engine(model, base)  # config says rank 2; tree is clean
    assert eng.generate([_prompt(6)], max_new_tokens=2)[0]
    eng.close()


# ---------------------------------------------------------------------------
# fleet plumbing
# ---------------------------------------------------------------------------
def test_fleet_adapter_registry_and_affinity():
    _cfg, model, params = _small_model()

    def factory():
        return _lora_engine(model, params)

    router = deepspeed_tpu.init_fleet(
        engine_factory=factory,
        config={"serving": {
            "replicas": 2, "placement": "adapter_affinity",
        }},
    )
    try:
        res = router.load_adapter(
            "a", replica_ids=["1"],
            adapter_state=_synth_adapter(params, 1),
        )
        assert res == {"1": 1}
        prompt = _prompt(9, seed=5)
        reqs = [
            router.submit(prompt, adapter="a", max_new_tokens=4)
            for _ in range(3)
        ]
        outs = [r.result(60.0) for r in reqs]
        # every a-request landed on the holder replica
        assert all(r.replica_id == "1" for r in reqs)
        assert outs[0] == outs[1] == outs[2]
        base = router.submit(prompt, max_new_tokens=4).result(60.0)
        assert base != outs[0]
        router.refresh_telemetry()
        snap = router.metrics.snapshot()
        assert snap["fleet/adapters_loaded"] == 1
        assert snap["fleet/adapter_loads"] == 1
        assert snap["fleet/affinity_hits"] == 3
        assert snap["fleet/replica1/adapters_loaded"] == 1
        # fleet-wide load + unload round-trips on both replicas
        assert set(router.load_adapter(
            "b", adapter_state=_synth_adapter(params, 2)
        )) == {"0", "1"}
        assert set(router.unload_adapter("b")) == {"0", "1"}
    finally:
        router.shutdown()


def test_deferred_admission_releases_adapter_pin():
    """A slot join that DEFERS on KV page pressure (PoolExhausted) must
    drop the adapter pin it took — a leaked pin would make the adapter
    permanently un-evictable and leave a stale prefix-cache salt on the
    slot."""
    _cfg, model, params = _small_model()
    # pool fits ONE request (9 + 16 = 25 tokens -> 4 of 4 pages); both
    # submissions pass the submit-time gate on the empty pool, then the
    # second defers at its slot join
    eng = _lora_engine(
        model, params,
        inference={"max_batch_slots": 2, "kv_block_size": 8,
                   "kv_pool_blocks": 4},
    )
    eng.load_adapter("a", adapter_state=_synth_adapter(params, 1))
    r1 = eng.submit(_prompt(9, seed=5), max_new_tokens=16, adapter="a")
    r2 = eng.submit(_prompt(9, seed=6), max_new_tokens=16, adapter="a")
    eng.scheduler.step()  # r1 takes the pages; r2 pins, defers, unpins
    assert eng.adapter_registry.active_count("a") == 1  # r1 only
    eng.scheduler.run_until_idle()
    assert r1.tokens and r2.tokens
    assert eng.adapter_registry.active_count("a") == 0
    eng.unload_adapter("a")  # a leaked pin would refuse here
    eng.close()


def test_fleet_falls_through_replicas_missing_the_adapter():
    """A replica without the adapter raises the TYPED AdapterUnavailable:
    the router drops it from the candidate set and places on a holder
    instead of failing the submission."""
    _cfg, model, params = _small_model()

    def factory():
        return _lora_engine(model, params)

    # least_loaded placement would pick replica 0 (registration order);
    # the adapter lives only on replica 1
    router = deepspeed_tpu.init_fleet(
        engine_factory=factory, config={"serving": {"replicas": 2}},
    )
    try:
        router.load_adapter(
            "a", replica_ids=["1"],
            adapter_state=_synth_adapter(params, 1),
        )
        req = router.submit(_prompt(9, seed=5), adapter="a",
                            max_new_tokens=4)
        assert req.result(60.0)
        assert req.replica_id == "1"
    finally:
        router.shutdown()


def test_fleet_restart_replays_registered_adapters():
    """A replica rebuilt by restart_replica starts with an empty pool;
    the router's fleet-wide adapter registry replays onto it, so a
    rolling restart never sheds tenants' weights."""
    _cfg, model, params = _small_model()

    def factory():
        return _lora_engine(model, params)

    router = deepspeed_tpu.init_fleet(
        engine_factory=factory, config={"serving": {"replicas": 2}},
    )
    try:
        router.load_adapter("a", adapter_state=_synth_adapter(params, 1))
        prompt = _prompt(9, seed=5)
        before = router.submit(
            prompt, adapter="a", max_new_tokens=4
        ).result(60.0)
        for rid in router.replica_ids:
            router.restart_replica(rid, wait_timeout=60.0)
        after = router.submit(
            prompt, adapter="a", max_new_tokens=4
        ).result(60.0)
        assert after == before
    finally:
        router.shutdown()


def test_worker_protocol_adapter_ops_roundtrip(tmp_path):
    """The WorkerServer load/unload ops over in-process channel IO, with
    a stub engine — the subprocess replica's RPC surface without paying
    a process spawn."""
    import io
    import json as _json

    from deepspeed_tpu.serving.worker import WorkerServer

    class StubEngine:
        def __init__(self):
            self.loaded = {}

        def serve_forever(self):
            pass

        def load_adapter(self, name, load_dir=None, tag=None):
            if load_dir == "bad":
                raise RuntimeError("corrupt adapter checkpoint")
            self.loaded[name] = load_dir
            return len(self.loaded)

        def unload_adapter(self, name):
            del self.loaded[name]
            return 1

        def load_snapshot(self):
            return {"adapters_loaded": sorted(self.loaded)}

        def close(self):
            pass

    ops = [
        {"op": "init", "spec": {}},
        {"op": "load_adapter", "id": 1, "name": "a", "load_dir": "/d"},
        {"op": "load_adapter", "id": 2, "name": "x", "load_dir": "bad"},
        {"op": "unload_adapter", "id": 3, "name": "a"},
        {"op": "shutdown"},
    ]
    stdin = io.StringIO("".join(_json.dumps(m) + "\n" for m in ops))
    stdout = io.StringIO()
    server = WorkerServer(stdin, stdout, lambda spec: StubEngine())
    assert server.run() == 0
    events = [_json.loads(l) for l in stdout.getvalue().splitlines()]
    by_id = {e.get("id"): e for e in events if "id" in e}
    assert by_id[1]["index"] == 1
    assert "corrupt" in by_id[2]["error"]
    assert by_id[3]["index"] == 1


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------
def test_bert_lora_fresh_adapter_matches_base():
    """The LoRA path rides DeepSpeedTransformerLayer, so BERT adapts the
    same way GPT-2 does: fresh adapters (B = 0) merged over a rank-0
    base leave the forward unchanged. Near-exact rather than bitwise
    here: the scanned block compiles as one XLA computation, and the
    traced-but-zero delta lets XLA re-associate the post-LN fusion by
    ~1 ulp — the adapter-DISABLED path (rank 0, no lora ops traced)
    stays structurally bitwise, and the GPT-2 stacks pin exact equality
    in test_fresh_adapter_forward_bitwise_matches_base."""
    from deepspeed_tpu.models.bert import BertConfig, BertModel

    kw = dict(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, use_flash=False,
    )
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 96, (2, 16)), jnp.int32
    )
    base_model = BertModel(BertConfig(**kw))
    params = base_model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        ids,
    )["params"]
    out_base = base_model.apply({"params": params}, ids, train=False)
    merged = merge_lora_params(
        params, init_lora_params(params, 2, rng=jax.random.PRNGKey(3))
    )
    out_lora = BertModel(BertConfig(**kw, lora_rank=2)).apply(
        {"params": merged}, ids, train=False
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(out_base),
        jax.tree_util.tree_leaves(out_lora),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )


def test_adapter_num_params_is_small_fraction():
    _cfg, _model, params = _small_model()
    ada = init_lora_params(params, 2)
    total = sum(
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
    )
    assert adapter_num_params(ada) / total < 0.1
