"""End-to-end engine tests on the 8-device CPU mesh.

Coverage mirrors the reference's tests/unit/test_fp16.py (Adam/LAMB x
fp32/fp16, ZeRO stages parametrized, overflow skip, empty-grad asymmetry)
driven through the public initialize()/forward/backward/step contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from tests.unit.simple_model import (
    SimpleModel,
    SimpleMLPWithDropout,
    config_dict,
    init_model,
    random_dataset,
)

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

INPUT_DIM = 16


def build_engine(cfg, model=None, seed=0, optimizer=None):
    model = model or SimpleModel(hidden_dim=32)
    params = init_model(model, INPUT_DIM, seed=seed)
    engine, opt, _, sched = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        config_params=cfg,
        optimizer=optimizer,
    )
    return engine, opt


def train_steps(engine, n_batches=8, batch_size=None, seed=0):
    bs = batch_size or engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
    x, y = random_dataset(bs * n_batches, INPUT_DIM, seed=seed)
    losses = []
    for b in range(n_batches):
        xb = x[b * bs : (b + 1) * bs]
        yb = y[b * bs : (b + 1) * bs]
        loss = engine(xb, yb)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def test_engine_world_size_is_mesh():
    engine, _ = build_engine(config_dict(batch_size=16))
    assert engine.dp_world_size == 8  # conftest forces 8 CPU devices


def test_adam_fp32_converges():
    engine, _ = build_engine(config_dict(batch_size=16, lr=5e-2))
    losses = train_steps(engine, n_batches=20)
    assert losses[-1] < losses[0] * 0.7
    assert engine.global_steps == 20
    assert engine.skipped_steps == 0


def test_bf16_converges():
    engine, _ = build_engine(config_dict(batch_size=16, bf16=True, lr=5e-2))
    losses = train_steps(engine, n_batches=20)
    assert losses[-1] < losses[0] * 0.75


def test_fp16_dynamic_scale_runs():
    engine, opt = build_engine(
        config_dict(batch_size=16, fp16=True, lr=1e-2)
    )
    # initial dynamic scale = 2**32: first steps overflow and halve the scale
    losses = train_steps(engine, n_batches=4)
    assert all(np.isfinite(losses))
    assert opt.loss_scale < 2.0**32


def test_fp16_static_scale():
    engine, opt = build_engine(
        config_dict(
            batch_size=16, fp16=True, lr=1e-2, fp16_opts={"loss_scale": 128}
        )
    )
    train_steps(engine, n_batches=4)
    assert opt.loss_scale == 128.0
    assert engine.global_steps == 4


def test_overflow_skips_step():
    engine, opt = build_engine(
        config_dict(batch_size=16, fp16=True, lr=1e-2, fp16_opts={"loss_scale": 0})
    )
    bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
    x, y = random_dataset(bs, INPUT_DIM)
    # Huge input magnitudes overflow in fp16 compute
    loss = engine(x * 1e30, y)
    engine.backward(loss)
    params_before = jax.tree_util.tree_map(np.asarray, engine.params)
    engine.step()
    assert engine.skipped_steps >= 1 or opt.overflow
    params_after = jax.tree_util.tree_map(np.asarray, engine.params)
    for a, b in zip(
        jax.tree_util.tree_leaves(params_before),
        jax.tree_util.tree_leaves(params_after),
    ):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_match_stage0(stage):
    """ZeRO is a memory layout, not a numerics change: every stage must
    produce the same parameters as plain DP (the reference asserts the
    same invariant via loss-parity runs, run_func_test.py)."""
    ref_engine, _ = build_engine(config_dict(batch_size=16, lr=1e-2), seed=3)
    ref_losses = train_steps(ref_engine, n_batches=5, seed=7)

    engine, _ = build_engine(
        config_dict(batch_size=16, lr=1e-2, zero_stage=stage), seed=3
    )
    losses = train_steps(engine, n_batches=5, seed=7)
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, ref_engine.params)
        ),
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, engine.params)
        ),
    ):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("stage", [1, 2])
def test_zero_optimizer_state_is_sharded(stage):
    engine, _ = build_engine(
        config_dict(batch_size=16, lr=1e-2, zero_stage=stage)
    )
    train_steps(engine, n_batches=1)
    # at least one moment buffer must be sharded over the data axis
    sharded = []
    for leaf in jax.tree_util.tree_leaves(engine.optimizer_state):
        if hasattr(leaf, "sharding") and leaf.ndim >= 1:
            spec = getattr(leaf.sharding, "spec", None)
            if spec and "data" in jax.tree_util.tree_leaves(tuple(spec)):
                sharded.append(leaf)
    assert sharded, "expected sharded optimizer state at stage >= 1"


def test_gradient_accumulation_boundary():
    engine, _ = build_engine(
        config_dict(batch_size=32, micro_batch=2, accum=2, lr=1e-2)
    )
    assert engine.gradient_accumulation_steps() == 2
    bs = 2 * engine.dp_world_size
    x, y = random_dataset(bs * 2, INPUT_DIM)
    loss = engine(x[:bs], y[:bs])
    engine.backward(loss)
    assert engine.is_gradient_accumulation_boundary()
    engine.step()  # micro step 1: no update yet
    assert engine.global_steps == 0
    loss = engine(x[bs:], y[bs:])
    engine.backward(loss)
    engine.step()  # boundary: update applied
    assert engine.global_steps == 1


def test_grad_accum_matches_large_batch():
    """accum=2 over half-batches == one step on the full batch."""
    cfg_big = config_dict(batch_size=32, micro_batch=4, accum=1, lr=1e-2)
    cfg_acc = config_dict(batch_size=32, micro_batch=2, accum=2, lr=1e-2)
    big, _ = build_engine(cfg_big, seed=5)
    acc, _ = build_engine(cfg_acc, seed=5)

    bs = 32
    x, y = random_dataset(bs, INPUT_DIM, seed=11)
    loss = big(x, y)
    big.backward(loss)
    big.step()

    loss = acc(x[:16], y[:16])
    acc.backward(loss)
    acc.step()
    loss = acc(x[16:], y[16:])
    acc.backward(loss)
    acc.step()

    assert big.global_steps == 1 and acc.global_steps == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.tree_util.tree_map(np.asarray, big.params)),
        jax.tree_util.tree_leaves(jax.tree_util.tree_map(np.asarray, acc.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_lamb_optimizer_with_coeffs():
    engine, opt = build_engine(
        config_dict(batch_size=16, optimizer="Lamb", lr=1e-2)
    )
    train_steps(engine, n_batches=3)
    coeffs = opt.get_lamb_coeffs()
    assert len(coeffs) > 0
    assert all(0.01 <= float(c) <= 10.0 for c in np.asarray(coeffs))


def test_empty_grad_params_are_stable():
    model = SimpleModel(hidden_dim=32, empty_grad=True)
    engine, _ = build_engine(config_dict(batch_size=16, lr=1e-2), model=model)
    losses = train_steps(engine, n_batches=5)
    assert all(np.isfinite(losses))


def test_dropout_model_train_and_eval():
    model = SimpleMLPWithDropout(hidden_dim=32)
    engine, _ = build_engine(config_dict(batch_size=16, lr=5e-2), model=model)
    train_steps(engine, n_batches=10)
    engine.eval()
    bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
    x, y = random_dataset(bs, INPUT_DIM, seed=2)
    eval_loss1 = float(engine(x, y))
    eval_loss2 = float(engine(x, y))
    assert eval_loss1 == pytest.approx(eval_loss2)  # dropout off => deterministic
    engine.train()
    assert engine._training


def test_dataloader_roundtrip():
    model = SimpleModel(hidden_dim=32)
    params = init_model(model, INPUT_DIM)
    x, y = random_dataset(64, INPUT_DIM)
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        training_data=(x, y),
        config_params=config_dict(batch_size=16, lr=1e-2),
    )
    n = 0
    for xb, yb in loader:
        loss = engine(xb, yb)
        engine.backward(loss)
        engine.step()
        n += 1
    assert n == len(loader) == 64 // 16
    assert engine.global_steps == n


def test_scheduler_from_config():
    cfg = config_dict(batch_size=16, lr=1e-2)
    cfg["scheduler"] = {
        "type": "WarmupLR",
        "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01, "warmup_num_steps": 10},
    }
    engine, _ = build_engine(cfg)
    lrs = []
    bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
    x, y = random_dataset(bs * 6, INPUT_DIM)
    for b in range(6):
        loss = engine(x[b * bs : (b + 1) * bs], y[b * bs : (b + 1) * bs])
        engine.backward(loss)
        engine.step()
        lrs.append(engine.get_lr()[0])
    assert lrs[0] < lrs[-1] <= 0.01


def test_train_batch_matches_unfused_loop():
    """The fused single-jit window (train_batch) must train identically to
    the forward/backward/step loop: same per-window losses, same params."""
    cfg = config_dict(batch_size=32, lr=1e-2, zero_stage=2)
    cfg["train_micro_batch_size_per_gpu"] = 2  # dp=8 -> accum=2
    cfg["gradient_accumulation_steps"] = 2

    e_loop, _ = build_engine(cfg, seed=3)
    e_fused, _ = build_engine(cfg, seed=3)

    x, y = random_dataset(16 * 10, INPUT_DIM, seed=11)
    micro = 16  # global micro-batch = micro_per_gpu * dp
    for w in range(5):
        mbs = [
            (x[(2 * w + i) * micro:(2 * w + i + 1) * micro],
             y[(2 * w + i) * micro:(2 * w + i + 1) * micro])
            for i in range(2)
        ]
        loop_losses = []
        for xb, yb in mbs:
            loss = e_loop(xb, yb)
            e_loop.backward(loss)
            loop_losses.append(float(loss))
        e_loop.step()
        fused_loss = e_fused.train_batch(iter(mbs))
        np.testing.assert_allclose(
            float(fused_loss), np.mean(loop_losses), rtol=2e-4,
        )
    assert e_loop.global_steps == e_fused.global_steps == 5
    for a, b in zip(
        jax.tree_util.tree_leaves(e_loop.params),
        jax.tree_util.tree_leaves(e_fused.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
        )


def test_zero_untested_optimizer_requires_opt_in():
    """ZeRO + an optimizer outside the tested set (Adam family / Lamb)
    must demand zero_allow_untested_optimizer, mirroring the reference
    guard (deepspeed_light.py:506-515)."""
    from deepspeed_tpu.config import DeepSpeedConfigError

    cfg = config_dict(batch_size=16, zero_stage=2, optimizer="SGD")
    with pytest.raises(
        DeepSpeedConfigError, match="zero_allow_untested_optimizer"
    ):
        build_engine(cfg)
    # the opt-in unlocks it (warning, not error)
    cfg = config_dict(batch_size=16, zero_stage=2, optimizer="SGD", lr=5e-2)
    cfg["zero_allow_untested_optimizer"] = True
    engine, _ = build_engine(cfg)
    losses = train_steps(engine, n_batches=4)
    assert np.isfinite(losses).all()
    # tested optimizers never need the flag
    engine, _ = build_engine(config_dict(batch_size=16, zero_stage=2))
    assert engine is not None
