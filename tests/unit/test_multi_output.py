"""Multi-output model through the engine (reference:
tests/unit/test_multi_output_model.py + multi_output_model.py — a model
producing several losses, trained on their weighted combination under
gradient accumulation while the individual losses stay observable).

Contract here: a tuple return trains on element 0; the rest ride as aux
(`engine.last_aux`). After an optimizer step — on BOTH train paths —
last_aux holds the window's aux [accum]-stacked; between forward() and
step() it shows the latest micro-step's raw aux; in eval mode it is the
raw aux of the last forward."""

import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


class TwoHeadModel(nn.Module):
    """Two linear heads with separate CE losses; trains on the weighted
    sum, exposes the per-head losses (the reference's MultiOutputModel)."""

    hidden: int = 16
    w1: float = 1.0
    w2: float = 0.5

    @nn.compact
    def __call__(self, x, y1, y2, train=True):
        h = nn.relu(nn.Dense(self.hidden)(x))

        def ce(logits, y):
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

        loss1 = ce(nn.Dense(4, name="head1")(h), y1)
        loss2 = ce(nn.Dense(4, name="head2")(h), y2)
        return self.w1 * loss1 + self.w2 * loss2, loss1, loss2


def _data(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y1 = (x[:, 0] > 0).astype(np.int32) * 3
    y2 = (x[:, 1] > 0).astype(np.int32) * 2
    return x, y1, y2


def _make_engine():
    model = TwoHeadModel()
    x, y1, y2 = _data(4)
    params = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.asarray(x), jnp.asarray(y1), jnp.asarray(y2),
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        config_params={
            "train_batch_size": 32,
            "train_micro_batch_size_per_gpu": 2,  # dp=8 -> accum=2
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "steps_per_print": 10_000,
        },
    )
    return engine


def test_two_output_model_trains_and_exposes_head_losses():
    engine = _make_engine()
    first = None
    for step in range(30):
        x, y1, y2 = _data(32, seed=step % 4)
        b1 = (x[:16], y1[:16], y2[:16])
        b2 = (x[16:], y1[16:], y2[16:])
        loss = engine(*b1)
        engine.backward(loss)
        # mid-window view: this micro-step's raw aux tuple
        l1, l2 = engine.last_aux
        assert np.isfinite(float(l1)) and np.isfinite(float(l2))
        loss = engine(*b2)
        engine.backward(loss)
        engine.step()
        # post-step: the window's aux, [accum]-stacked — the same layout
        # train_batch() produces
        s1, s2 = engine.last_aux
        assert s1.shape == (2,) and s2.shape == (2,)
        if first is None:
            first = (float(jnp.mean(s1)), float(jnp.mean(s2)))
    # both heads must have learned, not just the combined objective
    last = tuple(float(jnp.mean(v)) for v in engine.last_aux)
    assert last[0] < 0.5 * first[0], (first, last)
    assert last[1] < 0.5 * first[1], (first, last)


def test_two_output_model_fused_window_stacks_aux():
    engine = _make_engine()
    x, y1, y2 = _data(32, seed=1)
    loss = engine.train_batch(
        iter([(x[:16], y1[:16], y2[:16]), (x[16:], y1[16:], y2[16:])])
    )
    assert np.isfinite(float(loss))
    l1, l2 = engine.last_aux
    # fused window stacks aux per micro-step: [accum]
    assert l1.shape == (2,) and l2.shape == (2,)
    # combined loss == w1*l1 + w2*l2 (mean over the window)
    np.testing.assert_allclose(
        float(loss),
        float(jnp.mean(1.0 * l1 + 0.5 * l2)),
        rtol=1e-5,
    )


def test_eval_mode_splits_aux_too():
    engine = _make_engine()
    x, y1, y2 = _data(16, seed=2)
    engine.eval()
    loss = engine(x, y1, y2)
    assert loss.ndim == 0  # scalar combined loss, not the raw tuple
    l1, l2 = engine.last_aux
    assert np.isfinite(float(l1)) and np.isfinite(float(l2))
    engine.train()


def test_fused_window_aux_uniform_at_accum_1():
    """aux keeps its [accum]-leading axis even when accum == 1."""
    model = TwoHeadModel()
    x, y1, y2 = _data(8)
    params = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.asarray(x), jnp.asarray(y1), jnp.asarray(y2),
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "steps_per_print": 10_000,
        },
    )
    engine.train_batch(iter([(x, y1, y2)]))
    l1, l2 = engine.last_aux
    assert l1.shape == (1,) and l2.shape == (1,)
