"""Tiny model fixtures (the analog of the reference's
tests/unit/simple_model.py: SimpleModel + random dataloaders)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel(nn.Module):
    """Linear stack + cross-entropy loss; __call__(x, y) -> scalar loss,
    matching the reference fixture's contract (simple_model.py:7-23)."""

    hidden_dim: int
    num_classes: int = 10
    empty_grad: bool = False  # second layer that never sees gradients

    @nn.compact
    def __call__(self, x, y):
        h = nn.Dense(self.hidden_dim, name="linear")(x)
        if self.empty_grad:
            # Parameters exist but are unused in the loss — the analog of
            # the reference's rank-asymmetric missing-grad layer.
            nn.Dense(self.hidden_dim, name="unused")
        logits = nn.Dense(self.num_classes, name="head")(h)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        onehot = jax.nn.one_hot(y, self.num_classes, dtype=jnp.float32)
        return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


class SimpleMLPWithDropout(nn.Module):
    hidden_dim: int
    num_classes: int = 10
    dropout_rate: float = 0.1

    @nn.compact
    def __call__(self, x, y, train: bool = True):
        h = nn.Dense(self.hidden_dim)(x)
        h = nn.relu(h)
        h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        logits = nn.Dense(self.num_classes)(h)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        onehot = jax.nn.one_hot(y, self.num_classes, dtype=jnp.float32)
        return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def init_model(model, input_dim, seed=0):
    rng = jax.random.PRNGKey(seed)
    x = jnp.ones((2, input_dim), jnp.float32)
    y = jnp.zeros((2,), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, x, y)
    return variables["params"]


def random_dataset(num_samples, input_dim, num_classes=10, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(num_samples, input_dim)).astype(np.float32)
    w = rng.normal(size=(input_dim, num_classes)).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.normal(size=(num_samples, num_classes)), axis=-1)
    return x, y.astype(np.int32)


def config_dict(
    batch_size=16,
    micro_batch=None,
    accum=1,
    fp16=False,
    bf16=False,
    zero_stage=0,
    optimizer="Adam",
    lr=1e-2,
    **extra,
):
    cfg = {
        "train_batch_size": batch_size,
        "gradient_accumulation_steps": accum,
        "steps_per_print": 1000,
        "optimizer": {"type": optimizer, "params": {"lr": lr}},
    }
    if micro_batch:
        cfg["train_micro_batch_size_per_gpu"] = micro_batch
    if fp16:
        cfg["fp16"] = {"enabled": True, **extra.pop("fp16_opts", {})}
    if bf16:
        cfg["bf16"] = {"enabled": True}
    if zero_stage:
        cfg["zero_optimization"] = {"stage": zero_stage}
    cfg.update(extra)
    return cfg
