"""Activation checkpointing API (reference deepspeed_checkpointing.py:
RNG tracker, checkpoint(), partitioning, config plumbing)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import checkpointing as ckpt
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.parallel.mpu import TPUMpu

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    ckpt.configure(
        None, partition_activations=False, checkpoint_in_cpu=False,
        contiguous_checkpointing=False, num_checkpoints=1, profile=False,
        synchronize=False,
    )


def _fn(x, w):
    for _ in range(3):
        x = jnp.tanh(x @ w)
    return jnp.sum(x**2)


def test_checkpoint_preserves_value_and_grad():
    ckpt.configure(None)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((16, 16)) * 0.1, jnp.float32)

    ref_val, ref_grad = jax.value_and_grad(_fn, argnums=1)(x, w)
    val, grad = jax.value_and_grad(
        lambda x, w: ckpt.checkpoint(_fn, x, w), argnums=1
    )(x, w)
    np.testing.assert_allclose(np.asarray(val), np.asarray(ref_val), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad), rtol=1e-6)


@pytest.mark.parametrize("flag", ["partition", "cpu"])
def test_checkpoint_modes_match_baseline(flag):
    mesh = build_mesh(data_parallel_size=4, model_parallel_size=2)
    ckpt.configure(
        TPUMpu(mesh),
        partition_activations=(flag == "partition"),
        checkpoint_in_cpu=(flag == "cpu"),
    )
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((16, 16)) * 0.1, jnp.float32)

    @jax.jit
    def loss(x, w):
        return ckpt.checkpoint(_fn, x, w)

    ref = _fn(x, w)
    val, grad = jax.value_and_grad(loss, argnums=1)(x, w)
    ref_grad = jax.grad(_fn, argnums=1)(x, w)
    # sharding the saved residual reorders f32 reductions: tolerance is
    # parity-level, not bit-level
    np.testing.assert_allclose(np.asarray(val), np.asarray(ref), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grad), np.asarray(ref_grad), rtol=1e-3, atol=1e-5
    )


def test_configure_from_deepspeed_config(tmp_path):
    import json

    cfg_path = tmp_path / "ds.json"
    cfg_path.write_text(json.dumps({
        "train_batch_size": 8,
        "activation_checkpointing": {
            "partition_activations": True,
            "cpu_checkpointing": True,
            "profile": True,
            "number_checkpoints": 4,
        },
    }))
    from deepspeed_tpu.config import DeepSpeedConfig

    ds_config = DeepSpeedConfig(str(cfg_path), world_size=1)
    ckpt.configure(None, deepspeed_config=ds_config)
    assert ckpt.is_configured()
    assert ckpt.PARTITION_ACTIVATIONS and ckpt.CPU_CHECKPOINT and ckpt.PROFILE_TIME


def test_contiguous_requires_num_checkpoints():
    with pytest.raises(AssertionError, match="number of checkpoints"):
        ckpt.configure(None, contiguous_checkpointing=True, num_checkpoints=-1)


def test_rng_tracker_fork_streams():
    tracker = ckpt.model_parallel_seed(1234)
    with tracker.fork() as k1:
        a = jax.random.normal(k1, (4,))
    with tracker.fork() as k2:
        b = jax.random.normal(k2, (4,))
    # consecutive forks advance the stream
    assert not np.allclose(np.asarray(a), np.asarray(b))
    # re-seeding reproduces the same stream
    tracker = ckpt.model_parallel_seed(1234)
    with tracker.fork() as k1b:
        a2 = jax.random.normal(k1b, (4,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(a2))


def test_rng_tracker_mp_rank_dependence():
    class FakeMpu:
        def __init__(self, r):
            self.r = r

        def get_model_parallel_rank(self):
            return self.r

    t0 = ckpt.model_parallel_seed(7, mpu=FakeMpu(0))
    with t0.fork() as k:
        a = jax.random.normal(k, (4,))
    t1 = ckpt.model_parallel_seed(7, mpu=FakeMpu(1))
    with t1.fork() as k:
        b = jax.random.normal(k, (4,))
    assert not np.allclose(np.asarray(a), np.asarray(b))
    # default (replicated) stream is rank-independent
    d0 = t0.get_states()["default"]
    d1 = t1.get_states()["default"]
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


def test_duplicate_seed_rejected():
    tracker = ckpt.RNGStatesTracker()
    tracker.add("a", 1)
    with pytest.raises(ValueError, match="seed"):
        tracker.add("b", 1)
    with pytest.raises(ValueError, match="state"):
        tracker.add("a", 2)


def test_engine_configures_checkpointing():
    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return jnp.sum(nn.Dense(4)(x) ** 2)

    m = M()
    params = m.init(jax.random.PRNGKey(0), jnp.ones((2, 4)))["params"]
    deepspeed_tpu.initialize(
        model=m, model_parameters=params,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "activation_checkpointing": {"partition_activations": True},
        },
    )
    assert ckpt.is_configured()
    assert ckpt.PARTITION_ACTIVATIONS
