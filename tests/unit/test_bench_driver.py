"""bench.py orchestration logic (no hardware): north-star-first section
order, per-attempt emit, soft-budget skips, vs_prev regression deltas.

The round-3 driver run died compiling GPT-2 LAST (BENCH_r03.json rc 124,
extras.gpt2 null) — these tests pin the round-4 fixes so the flagship
number can't silently fall off the end of the budget again."""

import importlib
import json
import sys

import pytest


@pytest.fixture()
def bench(monkeypatch):
    monkeypatch.delenv("BENCH_ONLY", raising=False)
    monkeypatch.delenv("BENCH_GPT2", raising=False)
    monkeypatch.delenv("BENCH_WORKER", raising=False)
    mod = importlib.import_module("bench")
    importlib.reload(mod)
    return mod


def _result(metric, value=100.0):
    return {
        "metric": metric, "value": value, "unit": "u", "vs_baseline": 1.5,
    }


def test_gpt2_runs_first_and_emits_per_attempt(bench, monkeypatch, capsys):
    calls = []

    def fake_attempt(spec, timeout=1500):
        calls.append(spec)
        kind = spec["kind"]
        if kind == "gpt2":
            return _result(
                f"{spec['model']}_causal_lm_seq1024_tokens_per_sec_per_chip"
            )
        return _result(f"{kind}_metric")

    monkeypatch.setattr(bench, "_run_attempt", fake_attempt)
    bench.main()
    out = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    # the FIRST dispatched attempt is the GPT-2 north star
    assert calls[0]["kind"] == "gpt2"
    assert calls[0]["model"] == "gpt2_1.5b"
    # every successful attempt re-emitted a full JSON line
    assert len(out) >= 4
    # the north star rides extras.gpt2 in every line from the first on
    assert "gpt2_1.5b" in out[0]["extras"]["gpt2"]["metric"]
    assert "gpt2_1.5b" in out[-1]["extras"]["gpt2"]["metric"]


def test_budget_skips_tail_sections_not_gpt2(bench, monkeypatch, capsys):
    calls = []

    def fake_attempt(spec, timeout=1500):
        calls.append(spec)
        if spec["kind"] == "gpt2":
            return _result("gpt2_1.5b_causal_lm")
        return _result(spec["kind"])

    monkeypatch.setattr(bench, "_run_attempt", fake_attempt)
    monkeypatch.setattr(bench, "_BUDGET", -1.0)  # budget already exhausted
    bench.main()
    out = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    kinds = {c["kind"] for c in calls}
    assert "gpt2" in kinds          # the north star always runs
    assert "bert" not in kinds      # stable sections skipped on low budget
    assert out and "gpt2" in out[-1]["extras"]


def _bench_round_file(tmp_path, n, extras):
    """Driver-shaped BENCH_r{n}.json with the given parsed extras."""
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps({
        "n": n, "rc": 0, "parsed": {"metric": "m", "extras": extras},
    }))


def test_vs_prev_attached_from_previous_round(bench, monkeypatch, capsys,
                                              tmp_path):
    """A prior round's bert=374.41 in a BENCH file must give a new bert
    result with the same metric name a vs_prev ratio. Hermetic: reads a
    tmpdir, not the repo root."""
    _bench_round_file(tmp_path, 3, {
        "bert": _result(
            "bert_large_pretrain_seq128_samples_per_sec_per_chip",
            value=374.41,
        ),
    })
    orig = bench._load_prev_extras
    monkeypatch.setattr(
        bench, "_load_prev_extras", lambda: orig(search_dir=str(tmp_path))
    )

    def fake_attempt(spec, timeout=1500):
        if spec["kind"] == "bert" and spec.get("seq", 128) == 128:
            return _result(
                "bert_large_pretrain_seq128_samples_per_sec_per_chip",
                value=411.85,  # = 1.1 * 374.41
            )
        return None

    monkeypatch.setattr(bench, "_run_attempt", fake_attempt)
    monkeypatch.setenv("BENCH_ONLY", "bert")
    bench.main()
    out = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert out, "no emit"
    bert = out[-1]["extras"]["bert"]
    assert bert.get("vs_prev") == pytest.approx(1.1, abs=0.01)


def test_prev_extras_merge_across_partial_rounds(bench, tmp_path):
    """r03 measured bert+squad (gpt2 null), r04 only gpt2: the merged view
    must keep ALL three sections, taking the newest value per section."""
    _bench_round_file(tmp_path, 3, {
        "bert": _result("bert_metric", value=374.41),
        "squad": _result("squad_metric", value=99.3),
        "gpt2": None,
    })
    _bench_round_file(tmp_path, 4, {
        "gpt2": _result("gpt2_metric", value=5352.7),
        "bert": None,
    })
    merged = bench._load_prev_extras(search_dir=str(tmp_path))
    assert merged["bert"]["value"] == 374.41
    assert merged["squad"]["value"] == 99.3
    assert merged["gpt2"]["value"] == 5352.7


def test_prev_extras_newer_round_wins_per_section(bench, tmp_path):
    _bench_round_file(tmp_path, 3, {"bert": _result("bert_metric", 374.41)})
    _bench_round_file(tmp_path, 4, {"bert": _result("bert_metric", 380.0)})
    merged = bench._load_prev_extras(search_dir=str(tmp_path))
    assert merged["bert"]["value"] == 380.0


def test_headline_sections_run_before_gpt2_proxies(bench, monkeypatch):
    """r04 lesson: the driver run died compiling the 774m PROXY before
    BERT ever ran. Order must be: 1.5B north star, then bert/bert512/
    squad, then proxies only on leftover budget."""
    calls = []

    def fake_attempt(spec, timeout=1500):
        calls.append(spec)
        if spec["kind"] == "gpt2":
            return _result(
                f"{spec['model']}_causal_lm_seq1024_tokens_per_sec_per_chip"
            )
        return _result(f"{spec['kind']}_metric")

    monkeypatch.setattr(bench, "_run_attempt", fake_attempt)
    bench.main()
    order = [
        c["model"] if c["kind"] == "gpt2" else c["kind"] for c in calls
    ]
    first_bert = order.index("bert")
    first_proxy = order.index("gpt2_large_774m")
    assert order[0] == "gpt2_1.5b"
    assert first_bert < first_proxy
    assert "squad" in order[:first_proxy]


def test_worker_attempt_timeout_capped_by_budget(bench, monkeypatch):
    seen = {}

    class FakeProc:
        returncode = bench.OOM_EXIT
        stdout = ""
        stderr = ""

    def fake_run(cmd, env=None, capture_output=None, text=None, timeout=None):
        seen["timeout"] = timeout
        return FakeProc()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench, "_BUDGET", 0.0)
    assert bench._run_attempt({"kind": "bert"}) is None
    # grace window (~60s) past the exhausted budget, floored at 120s
    assert seen["timeout"] <= 121.0
