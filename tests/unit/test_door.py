"""HTTP/SSE front-door tests (deepspeed_tpu/serving/http.py,
docs/serving.md "Networked fleet"): genuinely-incremental token
streaming (the first SSE event arrives BEFORE generation completes —
the TTFT pin), client-disconnect slot reclamation within one decode
step through a REAL ContinuousBatchingScheduler, the typed-rejection
status-code table, and the slow-client overrun policies.

The replica engine here is a host-side harness around the real
scheduler (jax-free: the decode hooks are plain Python), so the
"within one decode step" claim is pinned against the production slot
machinery, not a mock of it."""

import asyncio
import json
import socket
import threading
import time

import pytest

from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.serving import FleetRouter, HTTPDoor, InProcessReplica
from deepspeed_tpu.telemetry.registry import MetricsRegistry


class _HostEngine:
    """The two scheduler hooks in plain Python: each decode step yields
    ``prev + 1`` per active slot, paced by ``step_secs`` so requests
    stay in flight long enough to stream / cancel against."""

    prefill_len = 16
    paged = False
    speculative = False

    def __init__(self, step_secs=0.02):
        self.step_secs = float(step_secs)
        self._last = {}
        self.scheduler = None  # attached by _make_engine

    def prefill_request(self, slot, prompt_tokens, temperature):
        del temperature
        first = (int(prompt_tokens[-1]) + 1) % 1000
        self._last[slot] = first
        return first

    def decode_tokens(self, active_slots):
        time.sleep(self.step_secs)
        out = []
        for slot in active_slots:
            nxt = (self._last.get(slot, 0) + 1) % 1000
            self._last[slot] = nxt
            out.append(nxt)
        return out

    # -- the InferenceEngine surface the replica tier drives ------------
    def submit(self, prompt_tokens, **kwargs):
        return self.scheduler.submit(prompt_tokens, **kwargs)

    def load_snapshot(self):
        return self.scheduler.load_snapshot()

    def serve_forever(self):
        self.scheduler.serve_forever(idle_sleep=0.001)

    def close(self):
        self.scheduler.shutdown()


def _make_engine(step_secs=0.02, num_slots=4):
    engine = _HostEngine(step_secs=step_secs)
    engine.scheduler = ContinuousBatchingScheduler(
        engine, num_slots=num_slots, max_seq_len=512, queue_depth=16,
        queue_timeout=0.0, eos_token_id=None, temperature=0.0,
        registry=MetricsRegistry(),
    )
    return engine


def _expected(prompt, n):
    base = int(prompt[-1])
    return [(base + i + 1) % 1000 for i in range(n)]


def _streams_closed(router, timeout=2.0):
    """Wait for door/open_streams to settle at 0. The handler thread
    decrements the gauge in its ``finally`` AFTER the client has already
    read the terminal frame, so an immediate snapshot races it."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if router.metrics.snapshot().get("door/open_streams") == 0:
            return True
        time.sleep(0.005)
    return False


def _fleet(step_secs=0.02, **router_kw):
    engines = []

    def factory():
        engine = _make_engine(step_secs=step_secs)
        engines.append(engine)
        return engine

    router = FleetRouter(
        [InProcessReplica("0", factory)], monitor_interval=0.005,
        **router_kw,
    ).start()
    return router, engines


def _sse_request(host, port, payload):
    """Open a streamed generate and return the raw socket (caller reads
    SSE frames incrementally)."""
    sock = socket.create_connection((host, port))
    body = json.dumps(payload).encode()
    sock.sendall(
        b"POST /v1/generate HTTP/1.1\r\nHost: door\r\n"
        b"Content-Length: %d\r\n\r\n%s" % (len(body), body)
    )
    sock.settimeout(30.0)
    return sock


def _read_until(sock, marker, buf=b""):
    while marker not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            break
        buf += chunk
    return buf


def _events(buf):
    out = []
    for block in buf.split(b"\n\n"):
        name = data = None
        for line in block.split(b"\n"):
            if line.startswith(b"event: "):
                name = line[7:].decode()
            elif line.startswith(b"data: "):
                data = json.loads(line[6:])
        if name is not None:
            out.append((name, data))
    return out


def _http_json(host, port, method, target, payload=None, headers=None):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30.0)
    body = None if payload is None else json.dumps(payload)
    conn.request(method, target, body, headers or {})
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp, (json.loads(raw) if raw else None)


# ---------------------------------------------------------------------------
# streaming incrementality (the TTFT pin)
# ---------------------------------------------------------------------------
def test_first_sse_event_arrives_before_generation_completes():
    """The acceptance pin: the door's first token event is on the wire
    at TTFT, while the scheduler is still decoding — asserted by
    checking the engine-side request is NOT done when the first event
    arrives, and that every token then arrives as its own event."""
    router, engines = _fleet(step_secs=0.05)
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        sock = _sse_request(host, port, {
            "prompt": [7], "max_new_tokens": 8, "stream": True,
        })
        buf = _read_until(sock, b"event: token")
        # the first token event has arrived; generation must still be
        # running (7 more tokens x 50ms steps remain)
        sched = engines[0].scheduler
        assert len(sched.active_slots) == 1, (
            "first SSE event arrived only after the request left its "
            "slot — streaming is not incremental"
        )
        buf = _read_until(sock, b"event: done", buf)
        sock.close()
        events = _events(buf)
        tokens = [d for name, d in events if name == "token"]
        dones = [d for name, d in events if name == "done"]
        assert len(tokens) == 8, "each token must be its own SSE event"
        assert [t["t"] for t in tokens] == _expected([7], 8)
        assert [t["i"] for t in tokens] == list(range(8))
        assert dones and dones[0]["tokens"] == _expected([7], 8)
        assert dones[0]["finish_reason"] == "max_new_tokens"
        assert dones[0]["usage"] == {
            "prompt_tokens": 1, "completion_tokens": 8,
        }
        snap = router.metrics.snapshot()
        assert snap["door/stream_ttft_ms/count"] >= 1
        assert _streams_closed(router), "open_streams gauge never closed"
    finally:
        door.shutdown()
        router.shutdown()


def test_client_disconnect_frees_slot_within_one_decode_step():
    """The acceptance pin's second half: an abandoned stream's KV slot
    is reclaimed within ONE decode step of the disconnect being seen —
    through the real scheduler's cancel sweep, with the cancelled
    request finishing "cancelled" instead of decoding to the budget."""
    router, engines = _fleet(step_secs=0.05)
    door = HTTPDoor(router, poll_interval=0.002)
    host, port = door.start()
    try:
        sock = _sse_request(host, port, {
            "prompt": [3], "max_new_tokens": 400, "stream": True,
        })
        _read_until(sock, b"event: token")
        sched = engines[0].scheduler
        assert len(sched.active_slots) == 1
        sock.close()  # the client walks away mid-generation
        # disconnect poll + cancel + one decode-step boundary; pad x4
        # for scheduling noise, still far below the 20s full generation
        deadline = time.monotonic() + 4 * 0.05 + 1.0
        while sched.active_slots and time.monotonic() < deadline:
            time.sleep(0.005)
        assert sched.active_slots == [], (
            "abandoned stream still holds its slot"
        )
        snap = router.metrics.snapshot()
        assert snap["door/client_disconnects"] == 1
        assert _streams_closed(router), "open_streams gauge never closed"
    finally:
        door.shutdown()
        router.shutdown()


def test_unary_response_and_healthz():
    router, _engines = _fleet(step_secs=0.005)
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        resp, out = _http_json(host, port, "POST", "/v1/generate", {
            "prompt": [5], "max_new_tokens": 4, "stream": False,
        })
        assert resp.status == 200
        assert out["tokens"] == _expected([5], 4)
        assert out["finish_reason"] == "max_new_tokens"
        resp, health = _http_json(host, port, "GET", "/healthz")
        assert resp.status == 200 and health["ok"] is True
        assert health["replicas_available"] == 1
    finally:
        door.shutdown()
        router.shutdown()


# ---------------------------------------------------------------------------
# status-code table
# ---------------------------------------------------------------------------
def test_rate_limited_tenant_gets_429_with_retry_after():
    router, _engines = _fleet(
        step_secs=0.005,
        rate_limit=(0.001, 1),  # 1-token burst, effectively no refill
    )
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        resp, _ = _http_json(host, port, "POST", "/v1/generate", {
            "prompt": [1], "max_new_tokens": 1, "stream": False,
        })
        assert resp.status == 200
        resp, out = _http_json(host, port, "POST", "/v1/generate", {
            "prompt": [1], "max_new_tokens": 1, "stream": False,
        })
        assert resp.status == 429
        assert out["reason"] == "rate_limit"
        # the header carries the bucket's ACTUAL refill time (ceiled to
        # whole seconds), not a constant: 1 token at 0.001/s is ~1000s
        retry_after = int(resp.getheader("Retry-After"))
        assert 900 <= retry_after <= 1000, retry_after
    finally:
        door.shutdown()
        router.shutdown()


def test_draining_fleet_gets_503():
    router, _engines = _fleet(step_secs=0.005)
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        router.drain_fleet()
        resp, out = _http_json(host, port, "POST", "/v1/generate", {
            "prompt": [1], "max_new_tokens": 1, "stream": False,
        })
        assert resp.status == 503
        assert out["reason"] == "draining"
        assert resp.getheader("Retry-After") == "1"
    finally:
        door.shutdown()
        router.shutdown()


def test_malformed_requests_get_400_and_routes_404_405():
    router, _engines = _fleet(step_secs=0.005)
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        for bad in (
            {"prompt": "a string"},
            {"prompt": []},
            {"prompt": [1.5]},
            {},
        ):
            resp, out = _http_json(
                host, port, "POST", "/v1/generate", bad
            )
            assert resp.status == 400, bad
            assert "prompt" in out["error"]
        resp, _ = _http_json(host, port, "GET", "/nope")
        assert resp.status == 404
        resp, _ = _http_json(host, port, "GET", "/v1/generate")
        assert resp.status == 405
    finally:
        door.shutdown()
        router.shutdown()


def test_deadline_propagates_to_scheduler():
    """A deadline that expires mid-generation finishes "deadline" with
    the partial tokens — the door reports it, never hangs."""
    router, _engines = _fleet(step_secs=0.05)
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        resp, out = _http_json(host, port, "POST", "/v1/generate", {
            "prompt": [5], "max_new_tokens": 400, "stream": False,
            "deadline_secs": 0.4,
        })
        assert resp.status == 200
        assert out["finish_reason"] == "deadline"
        assert 0 < len(out["tokens"]) < 400
    finally:
        door.shutdown()
        router.shutdown()


# ---------------------------------------------------------------------------
# slow-client backpressure (the policy seam, deterministic)
# ---------------------------------------------------------------------------
class _FakeTransport:
    def __init__(self, pending):
        self.pending = pending

    def get_write_buffer_size(self):
        return self.pending


class _FakeWriter:
    def __init__(self, pending):
        self.transport = _FakeTransport(pending)
        self.wrote = []
        self.drained = 0

    def write(self, data):
        self.wrote.append(data)

    async def drain(self):
        self.drained += 1
        self.transport.pending = 0


class _FakeFleetReq:
    request_id = 99
    tokens = ()


def test_overrun_policy_drop_cancels_and_counts():
    router, _engines = _fleet(step_secs=0.005)
    door = HTTPDoor(router, max_buffer_bytes=1024, overrun_policy="drop")
    cancelled = []
    router.cancel = lambda fr: cancelled.append(fr) or True
    writer = _FakeWriter(pending=4096)
    alive = asyncio.run(door._flush_stream(writer, _FakeFleetReq()))
    assert alive is False
    assert len(cancelled) == 1
    assert router.metrics.snapshot()["fleet/net_slow_client_drops"] == 1
    assert any(b"slow_client" in w for w in writer.wrote)
    router.shutdown()


def test_overrun_policy_block_drains_instead_of_dropping():
    router, _engines = _fleet(step_secs=0.005)
    door = HTTPDoor(router, max_buffer_bytes=1024, overrun_policy="block")
    cancelled = []
    router.cancel = lambda fr: cancelled.append(fr) or True
    writer = _FakeWriter(pending=4096)
    alive = asyncio.run(door._flush_stream(writer, _FakeFleetReq()))
    assert alive is True
    assert writer.drained == 1
    assert cancelled == []
    assert router.metrics.snapshot()["fleet/net_slow_client_drops"] == 0
    router.shutdown()


def test_fast_path_never_touches_policy():
    router, _engines = _fleet(step_secs=0.005)
    door = HTTPDoor(router, max_buffer_bytes=1024)
    writer = _FakeWriter(pending=10)
    alive = asyncio.run(door._flush_stream(writer, _FakeFleetReq()))
    assert alive is True and writer.drained == 0
    router.shutdown()


# ---------------------------------------------------------------------------
# the scheduler-level cancel contract the door's disconnect path rides
# ---------------------------------------------------------------------------
def test_inflight_cancel_reclaims_slot_at_next_step_boundary():
    """Driven step by step (no serve thread): cancelling a DECODING
    request frees its slot on the very next step() call and finishes it
    "cancelled" — the one-decode-step guarantee itself."""
    engine = _make_engine(step_secs=0.0)
    sched = engine.scheduler
    req = sched.submit([5], max_new_tokens=100)
    sched.step()  # admit + prefill + first decode
    assert sched.active_slots == [0]
    req.cancel()
    sched.step()  # the reap boundary
    assert sched.active_slots == []
    assert req.done and req.finish_reason == "cancelled"
    assert 0 < len(req.tokens) < 100  # partial answer retained
    # the freed slot is immediately admittable
    req2 = sched.submit([8], max_new_tokens=2)
    sched.step()
    sched.step()
    assert req2.done and req2.tokens == _expected([8], 2)
    sched.shutdown()


def test_queued_cancel_never_takes_a_slot():
    engine = _make_engine(step_secs=0.0, num_slots=1)
    sched = engine.scheduler
    runner = sched.submit([1], max_new_tokens=50)
    queued = sched.submit([2], max_new_tokens=50)
    sched.step()
    assert sched.active_slots == [0]
    queued.cancel()
    runner.cancel()
    sched.step()
    assert queued.done and queued.finish_reason == "cancelled"
    assert queued.tokens == []
    assert runner.done and runner.finish_reason == "cancelled"
    assert sched.active_slots == []
    sched.shutdown()


# ---------------------------------------------------------------------------
# bearer auth (serving.http.auth_token): 401 on mismatch, probes exempt,
# token never logged
# ---------------------------------------------------------------------------
def test_auth_token_gates_generate_but_not_probes(caplog):
    import logging

    router, _engines = _fleet(step_secs=0.0)
    door = HTTPDoor(router, auth_token="s3kr1t-token")
    host, port = door.start()
    try:
        with caplog.at_level(logging.DEBUG):
            # no token -> 401 with the WWW-Authenticate challenge
            resp, out = _http_json(host, port, "POST", "/v1/generate", {
                "prompt": [1], "max_new_tokens": 1, "stream": False,
            })
            assert resp.status == 401
            assert resp.getheader("WWW-Authenticate") == "Bearer"
            # wrong token -> 401; wrong scheme -> 401
            for header in (
                {"Authorization": "Bearer wrong"},
                {"Authorization": "Basic s3kr1t-token"},
            ):
                resp, _ = _http_json(
                    host, port, "POST", "/v1/generate",
                    {"prompt": [1], "max_new_tokens": 1, "stream": False},
                    headers=header,
                )
                assert resp.status == 401, header
            # right token -> served
            resp, out = _http_json(
                host, port, "POST", "/v1/generate",
                {"prompt": [7], "max_new_tokens": 2, "stream": False},
                headers={"Authorization": "Bearer s3kr1t-token"},
            )
            assert resp.status == 200
            assert out["tokens"] == _expected([7], 2)
            # probes stay open: external LBs carry no tenant credentials
            resp, _ = _http_json(host, port, "GET", "/healthz")
            assert resp.status == 200
            resp, _ = _http_json(host, port, "GET", "/readyz")
            assert resp.status == 200
        # the secret must never reach a log line — not on the 401 paths,
        # not on the accepted request
        assert "s3kr1t-token" not in caplog.text
    finally:
        door.shutdown()
        router.shutdown()


def test_auth_token_never_logged_by_config_print(caplog):
    import logging

    from deepspeed_tpu.config.config import DeepSpeedConfig

    cfg = DeepSpeedConfig(None, param_dict={
        "train_batch_size": 1,
        "serving": {"http": {"auth_token": "print-me-not"}},
    }, world_size=1)
    assert cfg.serving_http_auth_token == "print-me-not"
    with caplog.at_level(logging.DEBUG):
        cfg.print()
    assert "print-me-not" not in caplog.text


# ---------------------------------------------------------------------------
# GET /readyz: readiness (take traffic?) vs /healthz liveness
# ---------------------------------------------------------------------------
def test_readyz_503_while_draining_healthz_stays_200():
    router, _engines = _fleet(step_secs=0.0)
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        resp, out = _http_json(host, port, "GET", "/readyz")
        assert resp.status == 200 and out["ready"] is True
        router.drain_fleet()
        resp, out = _http_json(host, port, "GET", "/readyz")
        assert resp.status == 503
        assert "draining" in out["reasons"]
        # liveness is a different question: the process still serves
        resp, _ = _http_json(host, port, "GET", "/healthz")
        assert resp.status == 200
    finally:
        door.shutdown()
        router.shutdown()


def test_readyz_503_under_brownout():
    router, _engines = _fleet(step_secs=0.0, brownout_queue_ratio=0.5)
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        resp, out = _http_json(host, port, "GET", "/readyz")
        assert resp.status == 200, out
        router._update_brownout(0.9)  # force the band (fill 0.9 >= 0.5)
        resp, out = _http_json(host, port, "GET", "/readyz")
        assert resp.status == 503
        assert "brownout" in out["reasons"]
        router._update_brownout(0.0)
        resp, out = _http_json(host, port, "GET", "/readyz")
        assert resp.status == 200, out
    finally:
        door.shutdown()
        router.shutdown()


def test_readyz_names_cause_when_zero_routable():
    """A 503 for zero routable capacity carries the no_capacity_cause
    buckets in its body — probes (and humans) see WHY the fleet cannot
    take traffic, not just that it can't."""
    router, _engines = _fleet(step_secs=0.0)
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        router.drain("0")  # the only replica: zero routable capacity
        resp, out = _http_json(host, port, "GET", "/readyz")
        assert resp.status == 503
        assert "no_routable_replicas" in out["reasons"]
        cause = out["cause"]
        assert cause["replicas_total"] == 1
        assert cause["not_routable"] == 1
        assert cause["fenced"] is False
        assert cause["evicted"] == 0
    finally:
        door.shutdown()
        router.shutdown()


def test_429_retry_after_tracks_bucket_refill_rate():
    # 1-token burst refilling at 0.5/s: the second request's Retry-After
    # must say ~2s (ceil of the bucket's real refill time), not 1
    router, _engines = _fleet(step_secs=0.005, rate_limit=(0.5, 1))
    door = HTTPDoor(router)
    host, port = door.start()
    try:
        resp, _ = _http_json(host, port, "POST", "/v1/generate", {
            "prompt": [1], "max_new_tokens": 1, "stream": False,
        })
        assert resp.status == 200
        resp, out = _http_json(host, port, "POST", "/v1/generate", {
            "prompt": [1], "max_new_tokens": 1, "stream": False,
        })
        assert resp.status == 429
        assert out["reason"] == "rate_limit"
        assert resp.getheader("Retry-After") == "2"
    finally:
        door.shutdown()
        router.shutdown()
