"""Continuous-batching inference engine tests (deepspeed_tpu/inference/,
docs/inference.md): decode correctness against the training forward,
slot lifecycle, front-door overload shedding, the fixed-shape
no-recompile pin, the verified param-load path, and config validation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedConfigError
from deepspeed_tpu.inference import (
    RequestRejected,
    gpt2_prefill,
    init_kv_cache,
)
from deepspeed_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHeadModel,
    kv_cache_partition_specs,
)

VOCAB = 97


def _small_model(seed=0, **kw):
    cfg = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        dropout=0.0, use_flash=False, **kw,
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jnp.asarray(
        np.random.default_rng(seed).integers(0, VOCAB, (1, 8)), jnp.int32
    )
    params = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        ids0, ids0,
    )["params"]
    return cfg, model, params


def _engine(model, params, inference=None, **kw):
    block = {"max_batch_slots": 4, "max_seq_len": 48, "prefill_len": 16,
             "sampling": {"greedy": True}}
    block.update(inference or {})
    return deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config={"inference": block}, **kw,
    )


def _prompt(n=8, seed=1):
    return [int(t) for t in np.random.default_rng(seed).integers(0, VOCAB, n)]


def _reference_rollout(model, params, prompt, steps):
    """Full-sequence forward argmax rollout — the training model itself,
    jitted (the regime every engine program runs under)."""
    fwd = jax.jit(lambda p, t: model.apply({"params": p}, t, train=False))
    seq = list(prompt)
    out = []
    for _ in range(steps):
        logits = fwd(params, jnp.asarray([seq], jnp.int32))
        nxt = int(jnp.argmax(logits[0, -1, :VOCAB]))
        out.append(nxt)
        seq.append(nxt)
    return out


# ---------------------------------------------------------------------------
# decode correctness
# ---------------------------------------------------------------------------
def test_prefill_logits_bitwise_match_full_forward():
    """The KV-cache prefill IS the training forward: same params, same
    jitted arithmetic, bit-identical logits (plus per-layer k/v out)."""
    cfg, model, params = _small_model()
    prompt = jnp.asarray([_prompt(8)], jnp.int32)
    full = jax.jit(
        lambda p, t: model.apply({"params": p}, t, train=False)
    )(params, prompt)
    pre, ks, vs = jax.jit(
        lambda p, t: gpt2_prefill(cfg, p, t)
    )(params, prompt)
    np.testing.assert_array_equal(np.asarray(pre), np.asarray(full))
    assert ks.shape == (cfg.n_layer, 1, cfg.n_head, 8,
                        cfg.n_embd // cfg.n_head)
    assert vs.shape == ks.shape


def test_right_padded_prefill_matches_unpadded_rows():
    """Causality makes the fixed prefill window's padding columns inert:
    every real row's logits are bitwise-identical to the unpadded run."""
    cfg, model, params = _small_model()
    prompt = _prompt(6)
    jit_pre = jax.jit(lambda p, t: gpt2_prefill(cfg, p, t))
    plain, _, _ = jit_pre(params, jnp.asarray([prompt], jnp.int32))
    padded, _, _ = jit_pre(params, jnp.asarray([prompt + [0] * 10], jnp.int32))
    np.testing.assert_array_equal(
        np.asarray(padded[:, :6]), np.asarray(plain)
    )


def test_greedy_decode_parity_with_full_forward():
    """Acceptance pin: prefill + 16 KV-cache decode steps reproduce the
    full-sequence forward's argmax rollout exactly."""
    cfg, model, params = _small_model()
    prompt = _prompt(8)
    engine = _engine(model, params)
    out = engine.generate([prompt], max_new_tokens=16)[0]
    engine.close()
    assert len(out) == 16
    assert out == _reference_rollout(model, params, prompt, 16)


def test_concurrent_requests_decode_independently():
    """Continuous batching must not cross-contaminate slots: two prompts
    decoded in the SAME slot batch produce exactly what each produces
    alone."""
    cfg, model, params = _small_model()
    p1, p2 = _prompt(8, seed=1), _prompt(5, seed=2)
    engine = _engine(model, params)
    together = engine.generate([p1, p2], max_new_tokens=10)
    engine.close()
    for prompt, got in zip((p1, p2), together):
        assert got == _reference_rollout(model, params, prompt, 10)


def test_mid_flight_join_keeps_running_request_exact():
    """A request admitted while another is mid-decode (the continuous-
    batching moment) must not perturb the running request's trajectory,
    and must itself decode exactly."""
    cfg, model, params = _small_model()
    p1, p2 = _prompt(8, seed=3), _prompt(7, seed=4)
    engine = _engine(model, params, inference={"max_batch_slots": 2})
    r1 = engine.submit(p1, max_new_tokens=12)
    for _ in range(4):  # r1 alone for 4 steps
        engine.scheduler.step()
    r2 = engine.submit(p2, max_new_tokens=8)  # joins mid-flight
    engine.scheduler.run_until_idle()
    engine.close()
    assert r1.result(0) == _reference_rollout(model, params, p1, 12)
    assert r2.result(0) == _reference_rollout(model, params, p2, 8)


# ---------------------------------------------------------------------------
# slot lifecycle
# ---------------------------------------------------------------------------
def test_eos_finishes_request_and_slot_is_reused():
    cfg, model, params = _small_model()
    prompt = _prompt(8)
    ref = _reference_rollout(model, params, prompt, 8)
    eos = ref[3]  # the greedy trajectory reaches this token
    expected = ref[: ref.index(eos) + 1]  # truncated AT its first hit

    engine = _engine(model, params, inference={"max_batch_slots": 1})
    r1 = engine.submit(prompt, max_new_tokens=8, eos_token_id=eos)
    engine.scheduler.run_until_idle()
    assert r1.finish_reason == "eos"
    assert r1.result(0) == expected
    assert engine.scheduler.active_slots == []

    # the single slot frees and serves the next request correctly even
    # though the cache still holds the finished request's rows
    p2 = _prompt(6, seed=9)
    r2 = engine.submit(p2, max_new_tokens=6)
    engine.scheduler.run_until_idle()
    engine.close()
    assert r2.finish_reason == "max_new_tokens"
    assert r2.result(0) == _reference_rollout(model, params, p2, 6)


def test_length_cap_finishes_request():
    cfg, model, params = _small_model()
    engine = _engine(
        model, params, inference={"max_seq_len": 12, "prefill_len": 8}
    )
    r = engine.submit(_prompt(8), max_new_tokens=100)
    engine.scheduler.run_until_idle()
    engine.close()
    assert r.finish_reason == "length"
    assert len(r.result(0)) == 12 - 8


# ---------------------------------------------------------------------------
# the front door
# ---------------------------------------------------------------------------
def test_queue_overload_rejection():
    cfg, model, params = _small_model()
    engine = _engine(
        model, params,
        inference={"max_batch_slots": 1, "queue_depth": 2,
                   "queue_timeout_secs": 0.0},
    )
    # no scheduler steps run, so submissions pile up in the queue
    engine.submit(_prompt(4), max_new_tokens=4)
    engine.submit(_prompt(4), max_new_tokens=4)
    with pytest.raises(RequestRejected):
        engine.submit(_prompt(4), max_new_tokens=4)
    snap = engine.metrics.snapshot()
    assert snap["infer/requests_rejected"] == 1
    assert snap["infer/requests_admitted"] == 2
    # shed load drains once the scheduler runs again
    engine.scheduler.run_until_idle()
    engine.close()


def test_failed_generate_submit_cancels_earlier_prompts():
    """A rejected later prompt must not orphan the earlier submissions:
    they cancel instead of burning decode work on a future call with
    nobody holding their handles."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, inference={"prefill_len": 8})
    with pytest.raises(ValueError, match="prefill_len"):
        engine.generate([_prompt(4), _prompt(9)], max_new_tokens=4)
    engine.scheduler.run_until_idle()
    snap = engine.metrics.snapshot()
    assert snap["infer/tokens_generated"] == 0
    assert engine.scheduler.active_slots == []
    # the engine still serves normally afterwards
    out = engine.generate([_prompt(4)], max_new_tokens=4)
    engine.close()
    assert len(out[0]) == 4


def test_prefill_window_validated_against_model_positions():
    """prefill_len larger than the model-derived max_seq_len must fail at
    init_inference, not as a wpe broadcast error in the first prefill."""
    cfg, model, params = _small_model()  # n_positions=64
    with pytest.raises(DeepSpeedConfigError, match="prefill_len"):
        deepspeed_tpu.init_inference(
            model=model, model_parameters=params,
            config={"inference": {"prefill_len": 128}},
        )


def test_prompt_longer_than_prefill_window_rejected():
    cfg, model, params = _small_model()
    engine = _engine(model, params, inference={"prefill_len": 8})
    with pytest.raises(ValueError, match="prefill_len"):
        engine.submit(_prompt(9))
    with pytest.raises(ValueError, match="empty"):
        engine.submit([])
    with pytest.raises(ValueError, match="max_new_tokens"):
        engine.submit(_prompt(4), max_new_tokens=0)
    engine.close()


def test_server_mode_generate_and_shutdown_release_waiters():
    """generate() on a serve_forever engine waits on the server thread
    instead of racing it, and shutdown fail-finishes outstanding requests
    so result() waiters never hang."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, inference={"max_batch_slots": 2})
    engine.serve_forever()
    out = engine.generate([_prompt(6)], max_new_tokens=5)
    assert out[0] == _reference_rollout(model, params, _prompt(6), 5)
    # park requests (they may be queued or decoding), then shut down:
    # every handle must resolve, none may hang
    rs = [engine.submit(_prompt(4, seed=s), max_new_tokens=30)
          for s in range(4)]
    engine.close()
    for r in rs:
        r.result(timeout=5)  # raises TimeoutError on a hung waiter
        assert r.done
    assert engine.scheduler.active_slots == []
    # a closed scheduler sheds new submissions instead of queueing them
    # for a driver that no longer exists
    with pytest.raises(RequestRejected, match="shut down"):
        engine.submit(_prompt(4), max_new_tokens=2)


# ---------------------------------------------------------------------------
# fixed-shape pin: joins never recompile
# ---------------------------------------------------------------------------
def test_decode_steps_do_not_recompile_on_joins():
    """After the first request warms every program (prefill, cache write,
    decode+sample, first-token), requests of DIFFERENT prompt lengths
    joining and leaving must add zero XLA backend compiles — the
    continuous-batching engine's core latency invariant."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, inference={"max_batch_slots": 3})
    recompiles = engine.metrics.counter("jax/recompiles")
    engine.generate([_prompt(8)], max_new_tokens=4)
    warm = recompiles.value
    assert warm > 0

    r1 = engine.submit(_prompt(5, seed=5), max_new_tokens=6)
    engine.scheduler.step()
    r2 = engine.submit(_prompt(11, seed=6), max_new_tokens=5)
    r3 = engine.submit(_prompt(3, seed=7), max_new_tokens=7)
    engine.scheduler.run_until_idle()
    engine.close()
    assert all(r.done for r in (r1, r2, r3))
    assert recompiles.value == warm, (
        f"decode path recompiled: {recompiles.value - warm} new backend "
        "compiles after warmup"
    )


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------
def test_inference_telemetry_streams_populate_and_export(tmp_path):
    cfg, model, params = _small_model()
    engine = deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config={
            "inference": {"max_batch_slots": 2, "max_seq_len": 48,
                          "prefill_len": 16, "sampling": {"greedy": True}},
            "telemetry": {
                "enabled": True,
                "output_path": str(tmp_path),
                "job_name": "infer",
                "watchdog": {"enabled": False},
            },
        },
    )
    engine.generate([_prompt(8), _prompt(6, seed=2)], max_new_tokens=8)
    snap = engine.metrics.snapshot()
    engine.close()
    assert snap["infer/ttft_ms/count"] == 2
    assert snap["infer/token_latency_ms/count"] >= 7
    assert snap["infer/tokens_generated"] == 16
    assert snap["infer/requests_completed"] == 2
    assert snap["infer/slot_occupancy"] == 0
    # infer/* streams ride the SAME exporters as the training engine's
    import json

    lines = [
        json.loads(l)
        for l in open(tmp_path / "infer" / "metrics.jsonl").read().splitlines()
    ]
    tags = {l["tag"] for l in lines}
    assert {"infer/ttft_ms", "infer/token_latency_ms",
            "infer/tokens_per_sec", "infer/queue_depth",
            "infer/slot_occupancy"} <= tags
    ttft = [l for l in lines if l["tag"] == "infer/ttft_ms"][-1]
    assert ttft["kind"] == "histogram" and ttft["count"] == 2
    prom = open(tmp_path / "infer" / "metrics.prom").read()
    assert "infer_ttft_ms_bucket" in prom
    assert "infer_tokens_per_sec" in prom


# ---------------------------------------------------------------------------
# verified param load
# ---------------------------------------------------------------------------
def test_init_inference_serves_checkpoint_through_verified_load(tmp_path):
    """Params load through the resilience verified-load path: the trained
    checkpoint's weights (not the fresh init) answer generation, and a
    corrupt 'latest' falls back to the newest valid tag."""
    cfg, model, params = _small_model()
    trainer, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "steps_per_print": 10_000,
        },
    )
    ids = jnp.asarray(
        np.random.default_rng(3).integers(0, VOCAB, (8, 16)), jnp.int32
    )
    for _ in range(2):
        loss = trainer(ids, ids)
        trainer.backward(loss)
        trainer.step()
    save_dir = str(tmp_path / "ckpt")
    trainer.save_checkpoint(save_dir, tag="step2")
    trained = jax.tree_util.tree_map(np.asarray, trainer.params)

    engine = deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config={
            "inference": {
                "max_batch_slots": 2, "max_seq_len": 48, "prefill_len": 16,
                "sampling": {"greedy": True},
                "checkpoint": {"load_dir": save_dir},
            },
        },
    )
    assert engine.loaded_tag == "step2"
    for got, want in zip(
        jax.tree_util.tree_leaves(engine.params),
        jax.tree_util.tree_leaves(trained),
    ):
        np.testing.assert_allclose(
            np.asarray(got), want, rtol=0, atol=0
        )
    out = engine.generate([_prompt(8)], max_new_tokens=4)[0]
    engine.close()
    ref = _reference_rollout(
        model, jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params),
            jax.tree_util.tree_leaves(trained),
        ),
        _prompt(8), 4,
    )
    assert out == ref


def test_init_inference_verified_load_falls_back_on_corruption(tmp_path):
    cfg, model, params = _small_model()
    trainer, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "steps_per_print": 10_000,
        },
    )
    save_dir = str(tmp_path / "ckpt")
    trainer.save_checkpoint(save_dir, tag="good")
    ids = jnp.asarray(
        np.random.default_rng(3).integers(0, VOCAB, (8, 16)), jnp.int32
    )
    loss = trainer(ids, ids)
    trainer.backward(loss)
    trainer.step()
    trainer.save_checkpoint(save_dir, tag="bad")
    # corrupt the newest checkpoint's model states
    import os

    victim = os.path.join(save_dir, "bad", "mp_rank_00_model_states.msgpack")
    with open(victim, "wb") as f:
        f.write(b"torn write")

    engine = deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config={
            "inference": {
                "max_batch_slots": 2, "max_seq_len": 48, "prefill_len": 16,
                "checkpoint": {"load_dir": save_dir},
            },
        },
    )
    assert engine.loaded_tag == "good"
    assert engine.metrics.snapshot()["resilience/corruption_fallbacks"] >= 1
    engine.close()


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------
def test_sampling_modes():
    from deepspeed_tpu.inference.sampling import sample_tokens

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((4, 128)), jnp.float32)
    key = jax.random.PRNGKey(0)
    zeros = jnp.zeros((4,), jnp.float32)
    ones = jnp.ones((4,), jnp.float32)

    # temperature 0 => greedy, and the vocab padding can never win even
    # when it holds the largest raw logit
    spiked = logits.at[:, 100:].set(100.0)
    greedy = sample_tokens(spiked, key, zeros, vocab_size=100)
    assert np.all(np.asarray(greedy) < 100)
    np.testing.assert_array_equal(
        np.asarray(greedy), np.argmax(np.asarray(spiked)[:, :100], axis=-1)
    )
    # top_k=1 collapses sampling onto argmax
    topk1 = sample_tokens(logits, key, ones, vocab_size=100, top_k=1)
    np.testing.assert_array_equal(
        np.asarray(topk1), np.argmax(np.asarray(logits)[:, :100], axis=-1)
    )
    # a tiny nucleus keeps the argmax reachable and excludes the tail
    topp = sample_tokens(logits, key, ones, vocab_size=100, top_p=1e-6)
    np.testing.assert_array_equal(
        np.asarray(topp), np.argmax(np.asarray(logits)[:, :100], axis=-1)
    )
    # same key + same inputs => bit-identical draw (explicit threading)
    a = sample_tokens(logits, key, ones, vocab_size=100)
    b = sample_tokens(logits, key, ones, vocab_size=100)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # mixed greedy/sampled rows in one call
    mixed_t = jnp.asarray([0.0, 1.0, 0.0, 1.0], jnp.float32)
    mixed = np.asarray(sample_tokens(logits, key, mixed_t, vocab_size=100))
    am = np.argmax(np.asarray(logits)[:, :100], axis=-1)
    assert mixed[0] == am[0] and mixed[2] == am[2]


# ---------------------------------------------------------------------------
# config + cache plumbing
# ---------------------------------------------------------------------------
def test_kv_cache_layout_and_specs():
    cfg, _, _ = _small_model()
    cache = init_kv_cache(cfg, num_slots=4, max_len=32)
    assert cache.k.shape == (2, 4, 4, 32, 8)
    assert cache.num_slots == 4 and cache.max_len == 32
    spec = kv_cache_partition_specs()
    assert spec[2] == "model" and spec[0] is None and spec[3] is None


@pytest.mark.parametrize("block", [
    {"max_batch_slots": 0},
    {"max_batch_slots": "four"},
    {"queue_depth": 0},
    {"queue_timeout_secs": -1},
    {"dtype": "fp64"},
    {"sampling": {"temperature": -0.5}},
    {"sampling": {"top_p": 0.0}},
    {"sampling": {"top_p": 2.0}},
    {"sampling": {"greedy": "yes"}},
    {"eos_token_id": "eos"},
    {"max_seq_len": 8, "prefill_len": 16},
    {"checkpoint": {"load_dir": 7}},
])
def test_inference_config_validation_rejects(block):
    from deepspeed_tpu.config.config import DeepSpeedConfig

    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(
            None,
            param_dict={"train_batch_size": 8, "inference": block},
            world_size=1,
        )


def test_init_inference_rejects_unsupported_stacks():
    cfg, model, params = _small_model()
    moe_model = GPT2LMHeadModel(
        GPT2Config(
            vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=2,
            n_head=4, dropout=0.0, moe_experts=2,
        )
    )
    with pytest.raises(DeepSpeedConfigError, match="MoE"):
        deepspeed_tpu.init_inference(
            model=moe_model, model_parameters=params, config={}
        )
    with pytest.raises(DeepSpeedConfigError, match="n_positions"):
        deepspeed_tpu.init_inference(
            model=model, model_parameters=params,
            config={"inference": {"max_seq_len": 1024}},
        )
    with pytest.raises(ValueError, match="model_parameters"):
        deepspeed_tpu.init_inference(model=model, config={})


# ---------------------------------------------------------------------------
# self-healing serving: deadlines, health-state machine, driver restart
# (docs/inference.md "Self-healing serving")
# ---------------------------------------------------------------------------
import time as _time


def _healing_engine(inference=None, resilience=None):
    cfg, model, params = _small_model()
    block = {"max_batch_slots": 2, "max_seq_len": 48, "prefill_len": 16,
             "sampling": {"greedy": True}, "queue_depth": 4}
    block.update(inference or {})
    config = {"inference": block}
    if resilience:
        config["resilience"] = resilience
    return deepspeed_tpu.init_inference(
        model=model, model_parameters=params, config=config,
    )


def test_unmeetable_deadline_rejected_at_admission():
    """A request whose deadline is already unmeetable finishes with
    reason 'deadline' at admission — the slot is never taken and no
    prefill runs for it."""
    eng = _healing_engine()
    try:
        req = eng.submit(_prompt(6), max_new_tokens=8, deadline_secs=1e-4)
        _time.sleep(0.01)  # the deadline passes while queued
        eng.scheduler.step()
        assert req.finish_reason == "deadline"
        assert req.tokens == []
        snap = eng.metrics.snapshot()
        assert snap["infer/deadline_misses"] == 1
        assert snap["infer/requests_completed"] == 0
        assert snap["infer/slot_occupancy"] == 0
    finally:
        eng.close()


def test_inflight_deadline_frees_slot_within_one_step():
    eng = _healing_engine()
    try:
        req = eng.submit(_prompt(6), max_new_tokens=500, deadline_secs=30.0)
        eng.scheduler.step()  # admit + first decode step
        assert eng.scheduler.active_slots == [0]
        produced = len(req.tokens)
        assert produced >= 1
        # force the deadline into the past; the NEXT step must reclaim
        req.deadline = _time.monotonic() - 0.001
        eng.scheduler.step()
        assert req.finish_reason == "deadline"
        assert eng.scheduler.active_slots == []
        assert req.tokens[:produced] == req.tokens[:produced]  # partial kept
        assert eng.metrics.snapshot()["infer/deadline_misses"] == 1
    finally:
        eng.close()


def test_submit_rejects_nonpositive_deadline():
    eng = _healing_engine()
    try:
        with pytest.raises(ValueError):
            eng.submit(_prompt(6), deadline_secs=0)
        with pytest.raises(ValueError):
            eng.submit(_prompt(6), deadline_secs=-1.5)
    finally:
        eng.close()


def test_config_default_deadline_applies_to_requests():
    eng = _healing_engine(inference={"deadline_secs": 30.0})
    try:
        req = eng.submit(_prompt(6), max_new_tokens=1)
        assert req.deadline is not None
        eng.scheduler.run_until_idle()
        assert req.finish_reason == "max_new_tokens"
    finally:
        eng.close()


def test_degraded_health_sheds_low_priority_only():
    from deepspeed_tpu.inference.scheduler import (
        HEALTH_DEGRADED,
        HEALTH_HEALTHY,
    )

    eng = _healing_engine(inference={"degraded_queue_ratio": 0.5})
    try:
        assert eng.scheduler.health == HEALTH_HEALTHY
        a = eng.submit(_prompt(6), max_new_tokens=2)
        b = eng.submit(_prompt(6), max_new_tokens=2)
        # queue 2/4 >= 0.5 ratio: degraded — priority > 0 shed at the door
        assert eng.scheduler.health == HEALTH_DEGRADED
        with pytest.raises(RequestRejected):
            eng.submit(_prompt(6), max_new_tokens=2, priority=1)
        c = eng.submit(_prompt(6), max_new_tokens=2, priority=0)
        snap = eng.metrics.snapshot()
        assert snap["infer/requests_shed"] == 1
        assert snap["infer/health_state"] == HEALTH_DEGRADED
        eng.scheduler.run_until_idle()
        assert {a.finish_reason, b.finish_reason, c.finish_reason} == {
            "max_new_tokens"
        }
        assert eng.scheduler.health == HEALTH_HEALTHY
    finally:
        eng.close()


def test_drain_stops_admission_finishes_inflight():
    from deepspeed_tpu.inference.scheduler import HEALTH_DRAINING

    eng = _healing_engine()
    try:
        req = eng.submit(_prompt(6), max_new_tokens=3)
        eng.scheduler.drain()
        assert eng.metrics.snapshot()["infer/health_state"] == HEALTH_DRAINING
        with pytest.raises(RequestRejected):
            eng.submit(_prompt(6), max_new_tokens=2)
        eng.scheduler.run_until_idle()
        assert req.finish_reason == "max_new_tokens"
    finally:
        eng.close()


def test_decode_crash_auto_restarts_within_budget():
    """An injected decode crash fails the in-flight request (its KV rows
    died), resets the decode state from the pinned params, and the
    scheduler keeps serving — the next request completes normally."""
    eng = _healing_engine(
        inference={"driver_restart_budget": 1},
        resilience={"fault_injection": {"enabled": True, "faults": [
            {"site": "decode.step", "after": 1, "times": 1},
        ]}},
    )
    try:
        r1 = eng.submit(_prompt(6), max_new_tokens=6)
        eng.scheduler.run_until_idle()  # decode traversal 2 crashes
        snap = eng.metrics.snapshot()
        assert snap["infer/driver_restarts"] == 1
        assert r1.finish_reason == "error"
        assert len(r1.tokens) >= 1  # prefill token landed before the crash
        # post-restart the engine serves from the same pinned params
        r2 = eng.submit(_prompt(6), max_new_tokens=4)
        eng.scheduler.run_until_idle()
        assert r2.finish_reason == "max_new_tokens"
        assert len(r2.tokens) == 4
    finally:
        eng.close()


def test_decode_crash_exhausted_budget_drains():
    from deepspeed_tpu.inference.scheduler import HEALTH_DRAINING

    eng = _healing_engine(
        resilience={"fault_injection": {"enabled": True, "faults": [
            {"site": "decode.step", "after": 1, "times": 0},
        ]}},
    )
    try:
        r1 = eng.submit(_prompt(6), max_new_tokens=6)
        eng.serve_forever()
        r1.result(timeout=30)  # fail-finished, never hangs
        assert r1.finish_reason in ("cancelled", "error")
        deadline = _time.monotonic() + 5
        while eng.scheduler.driving and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert not eng.scheduler.driving
        assert eng.metrics.snapshot()["infer/health_state"] == HEALTH_DRAINING
        with pytest.raises(RequestRejected):
            eng.submit(_prompt(6), max_new_tokens=2)
    finally:
        eng.close()


def test_restarted_decode_matches_clean_engine_greedy():
    """Driver restart serves from the PINNED params: a post-restart
    greedy generation is bitwise what a never-crashed engine produces."""
    prompt = _prompt(8, seed=3)
    eng = _healing_engine(
        inference={"driver_restart_budget": 1},
        resilience={"fault_injection": {"enabled": True, "faults": [
            {"site": "decode.step", "times": 1},
        ]}},
    )
    clean = _healing_engine()
    try:
        crash = eng.submit(_prompt(6), max_new_tokens=4)
        eng.scheduler.run_until_idle()  # first decode step crashes
        assert crash.finish_reason == "error"
        out = eng.generate([prompt], max_new_tokens=8)[0]
        ref = clean.generate([prompt], max_new_tokens=8)[0]
        assert out == ref
    finally:
        eng.close()
        clean.close()


def test_prefill_crash_does_not_orphan_request():
    """A prefill that raises must leave the popped request reachable by
    the recovery sweeps — its result() waiter gets an answer instead of
    hanging forever (the request owns its slot before prefill runs)."""
    eng = _healing_engine(inference={"driver_restart_budget": 1})
    try:
        orig = eng.prefill_request
        calls = {"n": 0}

        def crashing_prefill(slot, tokens, temperature):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected prefill crash")
            return orig(slot, tokens, temperature)

        eng.prefill_request = crashing_prefill
        req = eng.submit(_prompt(6), max_new_tokens=3)
        eng.scheduler.run_until_idle()  # crash -> auto-restart
        assert req.done  # NOT hanging
        assert req.finish_reason == "error"
        assert eng.metrics.snapshot()["infer/driver_restarts"] == 1
        # and the restarted driver still serves
        req2 = eng.submit(_prompt(6), max_new_tokens=3)
        eng.scheduler.run_until_idle()
        assert req2.finish_reason == "max_new_tokens"
    finally:
        eng.close()


def test_queued_request_past_deadline_expires_without_free_slot():
    """Deadline expiry reaches QUEUED requests too: with every slot busy
    on a long generation, an expired queued request gets its 'deadline'
    finish at the next step boundary, not when a slot eventually frees."""
    eng = _healing_engine(inference={"max_batch_slots": 1})
    try:
        long_req = eng.submit(_prompt(6), max_new_tokens=30)
        eng.scheduler.step()  # long_req occupies the only slot
        queued = eng.submit(_prompt(6), max_new_tokens=5, deadline_secs=60)
        queued.deadline = _time.monotonic() - 0.001  # force expiry
        eng.scheduler.step()  # slot still busy; queued must expire NOW
        assert queued.finish_reason == "deadline"
        assert long_req.finish_reason is None  # untouched
        eng.scheduler.run_until_idle()
        assert long_req.finish_reason == "max_new_tokens"
        # the expired husk was discarded at admission, never decoded
        assert queued.tokens == []
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# router-facing surface: rejection reason codes + load_snapshot
# (deepspeed_tpu/serving/ builds on exactly these)
# ---------------------------------------------------------------------------
def test_rejections_carry_machine_readable_reason_codes():
    """Every RequestRejected raise site classifies itself with a REJECT_*
    code — the router and tests branch on exc.reason, not on prose."""
    cfg, model, params = _small_model()
    eng = _engine(
        model, params,
        inference={"max_batch_slots": 1, "queue_depth": 1,
                   "queue_timeout_secs": 0.0},
    )
    try:
        eng.submit(_prompt(4), max_new_tokens=4)
        with pytest.raises(RequestRejected) as exc:
            eng.submit(_prompt(4), max_new_tokens=4)
        assert exc.value.reason == "overload"
        eng.scheduler.run_until_idle()
        eng.scheduler.drain()
        with pytest.raises(RequestRejected) as exc:
            eng.submit(_prompt(4), max_new_tokens=4)
        assert exc.value.reason == "draining"
    finally:
        eng.close()
    with pytest.raises(RequestRejected) as exc:
        eng.submit(_prompt(4), max_new_tokens=4)  # shut down
    assert exc.value.reason == "draining"


def test_request_rejected_rejects_unknown_reason():
    with pytest.raises(ValueError, match="unknown rejection reason"):
        RequestRejected("msg", reason="bogus")


def test_degraded_shed_reason_is_overload():
    eng = _healing_engine(
        inference={"queue_depth": 4, "degraded_queue_ratio": 0.5}
    )
    try:
        for _ in range(2):  # 2/4 fills to the degraded ratio
            eng.submit(_prompt(4), max_new_tokens=2)
        with pytest.raises(RequestRejected) as exc:
            eng.submit(_prompt(4), max_new_tokens=2, priority=1)
        assert exc.value.reason == "overload"
        eng.scheduler.run_until_idle()
    finally:
        eng.close()


def test_load_snapshot_reports_live_idle_state():
    """load_snapshot() is the router's placement input: queue depth and
    slot occupancy must be LIVE values even when no drive loop is
    running — and sampling must refresh the infer/queue_depth gauge an
    idle replica would otherwise leave stale."""
    cfg, model, params = _small_model()
    eng = _engine(model, params, inference={"max_batch_slots": 2})
    try:
        snap = eng.load_snapshot()
        assert snap["queue_depth"] == 0
        assert snap["active_slots"] == 0
        assert snap["free_slots"] == 2
        assert snap["health"] == 0
        assert snap["driving"] is False
        assert snap["stopped"] is False
        assert snap["driver_failed"] is False
        assert snap["mean_prefill_ms"] == 0.0

        # completion-progress markers: the fleet tier's zombie detection
        # watches these move (docs/serving.md "Zombie detection")
        assert snap["requests_completed"] == 0
        assert snap["tokens_generated"] == 0

        # pile submissions up WITHOUT stepping: an idle replica, loaded
        for _ in range(3):
            eng.submit(_prompt(4), max_new_tokens=2)
        snap = eng.load_snapshot()
        assert snap["queue_depth"] == 3
        # the gauge refreshed from the snapshot sample, not a drive loop
        assert eng.metrics.snapshot()["infer/queue_depth"] == 3

        eng.scheduler.run_until_idle()
        snap = eng.load_snapshot()
        assert snap["queue_depth"] == 0
        assert snap["mean_prefill_ms"] > 0.0
        assert snap["mean_decode_ms"] > 0.0
        assert eng.metrics.snapshot()["infer/queue_depth"] == 0
        # progress moved with the completed work, JSON-safe ints
        assert snap["requests_completed"] == 3
        assert snap["tokens_generated"] == 6
        assert isinstance(snap["requests_completed"], int)
        assert isinstance(snap["tokens_generated"], int)
    finally:
        eng.close()
