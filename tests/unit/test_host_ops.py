"""Native host-ops extension + numpy fallback parity."""

import time

import numpy as np
import pytest

from deepspeed_tpu.runtime import host_ops


def _arrays():
    rng = np.random.default_rng(0)
    return [
        rng.standard_normal((4, 8)).astype(np.float32),
        rng.integers(0, 100, (16,)).astype(np.int64),
        rng.standard_normal((2, 3, 5)).astype(np.float32),
    ]


def test_flatten_unflatten_roundtrip():
    arrays = _arrays()
    flat = host_ops.flatten(arrays)
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    targets = [np.zeros_like(a) for a in arrays]
    host_ops.unflatten_into(flat, targets)
    for a, b in zip(arrays, targets):
        np.testing.assert_array_equal(a, b)


def test_unflatten_size_mismatch_raises():
    arrays = _arrays()
    flat = host_ops.flatten(arrays)
    bad = [np.zeros((1,), np.float32)]
    with pytest.raises(ValueError):
        host_ops.unflatten_into(flat, bad)


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(1)
    src = rng.standard_normal((100, 32)).astype(np.float32)
    idx = rng.integers(0, 100, (17,)).astype(np.int64)
    out = host_ops.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_rows_bad_index():
    src = np.zeros((4, 2), np.float32)
    if not host_ops.HAVE_NATIVE:
        pytest.skip("native bounds check only")
    with pytest.raises(ValueError):
        host_ops.gather_rows(src, np.asarray([5], np.int64))


def test_shuffled_indices_deterministic_permutation():
    a = host_ops.shuffled_indices(1000, seed=42)
    b = host_ops.shuffled_indices(1000, seed=42)
    c = host_ops.shuffled_indices(1000, seed=43)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    np.testing.assert_array_equal(np.sort(a), np.arange(1000))


def test_prefetch_queue_orders_and_exhausts():
    items = iter(range(5))

    def producer():
        try:
            return next(items)
        except StopIteration:
            raise StopIteration

    q = host_ops.make_prefetch_queue(producer, capacity=2)
    got = [q.get(timeout=10.0) for _ in range(5)]
    assert got == [0, 1, 2, 3, 4]
    with pytest.raises((StopIteration, TimeoutError)):
        q.get(timeout=5.0)
    q.stop()


def test_prefetch_queue_overlaps_producer():
    """Producer sleeps; consumer should see items already buffered."""

    state = {"n": 0}

    def producer():
        if state["n"] >= 3:
            raise StopIteration
        state["n"] += 1
        time.sleep(0.05)
        return state["n"]

    q = host_ops.make_prefetch_queue(producer, capacity=4)
    time.sleep(0.5)  # let the worker fill the buffer
    assert q.qsize() >= 2
    assert q.get(timeout=5.0) == 1
    q.stop()


def test_prefetch_queue_stop_mid_stream():
    def producer():
        time.sleep(0.01)
        return 1

    q = host_ops.make_prefetch_queue(producer, capacity=2)
    assert q.get(timeout=5.0) == 1
    q.stop()  # must not hang or crash


@pytest.mark.skipif(not host_ops.HAVE_NATIVE, reason="extension not built")
def test_native_extension_is_loaded():
    assert host_ops.HAVE_NATIVE


@pytest.mark.skipif(not host_ops.HAVE_NATIVE, reason="needs both backends")
def test_shuffled_indices_native_matches_numpy_fallback():
    """Checkpoint resume of the data order must not depend on whether the
    extension is built: both backends emit the identical permutation."""
    for n, seed in [(1, 0), (17, 3), (1000, 42), (4096, 2**63)]:
        native = host_ops.shuffled_indices(n, seed)
        s0 = host_ops._splitmix64(np.asarray(seed, np.uint64))
        keys = host_ops._splitmix64(
            s0 ^ host_ops._splitmix64(np.arange(n, dtype=np.uint64))
        )
        fallback = np.argsort(keys, kind="stable").astype(np.int64)
        np.testing.assert_array_equal(native, fallback)


def test_gather_rows_empty_src_rejected():
    if not host_ops.HAVE_NATIVE:
        pytest.skip("native guard only")
    import _ds_host_ops as C

    with pytest.raises(ValueError):
        C.gather_rows(
            np.zeros((0, 4), np.float32), 0,
            np.asarray([0], np.int64), np.zeros((1, 4), np.float32),
        )


@pytest.mark.parametrize("backend", ["native", "fallback"])
def test_prefetch_queue_surfaces_producer_error(backend):
    """A data-pipeline bug must fail the consumer, not silently truncate
    the epoch — on both the C++ queue and the Python fallback."""
    if backend == "native" and not host_ops.HAVE_NATIVE:
        pytest.skip("extension not built")
    calls = {"n": 0}

    def producer():
        calls["n"] += 1
        if calls["n"] > 1:
            raise RuntimeError("pipeline bug")
        return 7

    if backend == "native":
        import _ds_host_ops as C

        q = C.PrefetchQueue(producer, 2)
    else:
        q = host_ops._PyPrefetchQueue(producer, capacity=2)
    assert q.get(timeout=5.0) == 7
    with pytest.raises(RuntimeError, match="pipeline bug"):
        q.get(timeout=5.0)
    q.stop()


def test_gather_rows_empty_indices_parity():
    """Empty gathers succeed identically with and without the extension."""
    out = host_ops.gather_rows(
        np.zeros((0, 4), np.float32), np.zeros((0,), np.int64)
    )
    assert out.shape == (0, 4)
    out = host_ops.gather_rows(
        np.zeros((3, 4), np.float32), np.zeros((0,), np.int64)
    )
    assert out.shape == (0, 4)


def test_shuffled_indices_negative_seed():
    a = host_ops.shuffled_indices(64, -1)
    np.testing.assert_array_equal(np.sort(a), np.arange(64))
