"""Fleet observability plane (telemetry/hub.py + timeseries.py, the
door's /metrics //statz //dashboard endpoints, and the node agent's
metrics_snapshot / drain_telemetry control ops — docs/observability.md
"fleet-wide view").

jax-free throughout: nodes host worker.py's StubWorkerEngine on real
loopback sockets, the hub is driven with an injected clock, and the
router under the door is a live FleetRouter over socket replicas.
"""

import http.client
import json
import threading
import time

import pytest

from deepspeed_tpu.config.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.serving import FleetRouter, HTTPDoor, SocketReplica
from deepspeed_tpu.serving.node import NodeServer
from deepspeed_tpu.serving.transport import NodeControlClient
from deepspeed_tpu.telemetry.hub import (
    ALERT_BREAKER_FLOOD,
    ALERT_SLO_BURN,
    HUB_HTTP_PATHS,
    TelemetryHub,
)
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.timeseries import TimeSeriesStore
from deepspeed_tpu.telemetry.tracing import NOOP_TRACER, SpanTracer


def _node(replicas=("r0",), *, node_id="n0", tracing=False):
    spec = {
        "node_id": node_id,
        "replicas": {
            name: {"stub": {"delay_secs": 0.01}} for name in replicas
        },
        "lease_secs": 5.0,
        "resume_grace_secs": 5.0,
    }
    if tracing:
        spec["config"] = {
            "telemetry": {"tracing": {"enabled": True, "sample_rate": 1.0}},
        }
    return NodeServer(spec)


class _FakeRouter:
    """The slice of FleetRouter the hub touches: a registry, a tracer,
    and the back-pointer attribute."""

    def __init__(self, tracer=None):
        self.metrics = MetricsRegistry()
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        self.hub = None


# ---------------------------------------------------------------------------
# the time-series ring
# ---------------------------------------------------------------------------
def test_timeseries_retention_bounds_each_ring():
    store = TimeSeriesStore(retention_points=4, clock=lambda: 0.0)
    for i in range(10):
        store.record("c", float(i), now=float(i))
    pts = store.window("c", window_secs=100.0, now=9.0)
    assert pts == [(6.0, 6.0), (7.0, 7.0), (8.0, 8.0), (9.0, 9.0)]
    assert store.latest("c") == (9.0, 9.0)
    assert store.latest("unknown") is None


def test_timeseries_window_queries():
    store = TimeSeriesStore(retention_points=64)
    for i in range(6):
        store.record("reqs", 10.0 * i, now=100.0 + i)
    # delta and rate over the trailing window
    assert store.window_delta("reqs", 100.0, now=105.0) == 50.0
    assert store.window_rate("reqs", 100.0, now=105.0) == 10.0
    # a narrow window sees fewer points
    assert store.window_delta("reqs", 2.0, now=105.0) == 20.0
    # < 2 points -> None, not 0 (an empty window is unknown, not quiet)
    assert store.window_delta("reqs", 0.5, now=105.0) is None
    assert store.window_rate("empty", 10.0, now=105.0) is None
    stats = store.window_stats("reqs", 100.0, now=105.0)
    assert stats == {"count": 6, "min": 0.0, "max": 50.0, "last": 50.0}
    assert store.sparkline("reqs", points=3) == [30.0, 40.0, 50.0]


def test_timeseries_counter_reset_clamps_to_zero():
    store = TimeSeriesStore(retention_points=8)
    store.record("c", 100.0, now=1.0)
    store.record("c", 3.0, now=2.0)  # process restart reset the counter
    assert store.window_delta("c", 10.0, now=2.0) == 0.0


def test_timeseries_rejects_degenerate_retention():
    with pytest.raises(ValueError):
        TimeSeriesStore(retention_points=1)


# ---------------------------------------------------------------------------
# the hub: scrape, windows, alert rules (injected clock, no sockets)
# ---------------------------------------------------------------------------
def _hub(router, clock, **kw):
    kw.setdefault("interval_secs", 1.0)
    kw.setdefault("slo_target", 0.99)
    kw.setdefault("alert_fast_window_secs", 10.0)
    kw.setdefault("alert_slow_window_secs", 30.0)
    hub = TelemetryHub(clock=clock, **kw)
    hub.attach(router)
    router.hub = hub
    return hub


def test_hub_local_scrape_feeds_windows_and_budget():
    t = {"now": 1000.0}
    router = _FakeRouter()
    hub = _hub(router, lambda: t["now"])
    routed = router.metrics.counter("fleet/requests_routed")
    violations = router.metrics.counter("fleet/slo_violations")
    samples = router.metrics.counter("fleet/slo_samples")
    # before two points, every windowed read abstains
    assert hub.observed_rate("fleet/requests_routed", 10.0) is None
    assert hub.error_budget_remaining(10.0) is None
    for _ in range(5):
        routed.inc(8)
        samples.inc(4)
        violations.inc(1)  # 25% violating
        hub.scrape_once()
        t["now"] += 1.0
    assert hub.observed_rate(
        "fleet/requests_routed", 10.0, now=t["now"]
    ) == pytest.approx(8.0)
    assert hub.error_budget_remaining(
        10.0, now=t["now"]
    ) == pytest.approx(0.75)
    # 25% violating / 1% budget = burn 25: both windows over threshold
    assert ALERT_SLO_BURN in hub._active_alerts
    assert router.metrics.counter("fleet/alerts_slo_burn").value == 1


def test_hub_alert_fires_on_rising_edge_only():
    t = {"now": 0.0}
    router = _FakeRouter()
    hub = _hub(router, lambda: t["now"], alert_breaker_flood=3)
    opens = router.metrics.counter("fleet/breaker_opens")
    for _ in range(4):
        opens.inc(2)  # 8 opens over ~4s >> flood threshold 3
        hub.scrape_once()
        t["now"] += 1.0
    alerts = router.metrics.counter(f"fleet/alerts_{ALERT_BREAKER_FLOOD}")
    assert ALERT_BREAKER_FLOOD in hub._active_alerts
    assert alerts.value == 1  # many evaluations, ONE rising edge
    # the flood subsides past the window: the alert resolves, and a new
    # flood later is a NEW rising edge
    t["now"] += 60.0
    hub.scrape_once()
    t["now"] += 1.0
    hub.scrape_once()
    assert ALERT_BREAKER_FLOOD not in hub._active_alerts
    for _ in range(3):
        opens.inc(2)
        hub.scrape_once()
        t["now"] += 1.0
    assert alerts.value == 2


def test_hub_alert_event_lands_in_flight_ring(tmp_path):
    t = {"now": 0.0}
    tracer = SpanTracer(
        sample_rate=1.0, ring_events=64, dump_dir=str(tmp_path)
    )
    router = _FakeRouter(tracer=tracer)
    hub = _hub(router, lambda: t["now"])
    samples = router.metrics.counter("fleet/slo_samples")
    violations = router.metrics.counter("fleet/slo_violations")
    for _ in range(3):
        samples.inc(2)
        violations.inc(2)  # 100% violating
        hub.scrape_once()
        t["now"] += 1.0
    names = [e["name"] for e in tracer.flight_snapshot()]
    assert "hub.alert" in names
    tracer.close()


def test_hub_statz_and_dashboard_shapes():
    t = {"now": 50.0}
    router = _FakeRouter()
    hub = _hub(router, lambda: t["now"])
    router.metrics.counter("fleet/requests_routed").inc(3)
    hub.scrape_once()
    t["now"] += 1.0
    hub.scrape_once()
    statz = hub.statz()
    assert statz["nodes"] == [] and statz["nodes_up"] == 0
    assert "10s" in statz["windows"] and "30s" in statz["windows"]
    assert statz["windows"]["10s"]["request_rate"] == pytest.approx(0.0)
    assert statz["alerts"]["active"] == []
    assert statz["fleet"]["fleet/requests_routed"] == 3.0
    # the dashboard page is self-contained and carries the state inline
    html = hub.dashboard_html()
    assert "__INITIAL_STATE__" not in html
    assert "EventSource" in html and "/statz/stream" in html
    state = hub.dashboard_state()
    assert set(state["spark"]) == {
        "ttft_p99_ms", "utilization", "queue_depth", "budget_remaining",
    }


def test_hub_prometheus_text_merges_remote_with_labels():
    router = _FakeRouter()
    hub = _hub(router, time.time)
    router.metrics.counter("fleet/requests_routed", help="routed").inc(2)
    # a cached remote view, as scrape_once would leave it
    hub._remote[("n9", "r0")] = (time.time(), [
        {"name": "infer/requests_completed", "kind": "counter",
         "help": "done", "value": 7.0},
    ])
    text = hub.prometheus_text()
    assert "fleet_requests_routed 2.0" in text
    assert (
        'infer_requests_completed{node="n9",replica="r0"} 7.0' in text
    )
    # HELP/TYPE once per family
    assert text.count("# TYPE infer_requests_completed counter") == 1


def test_hub_scrape_failure_backoff_and_recovery():
    t = {"now": 0.0}
    router = _FakeRouter()
    hub = _hub(
        router, lambda: t["now"],
        nodes={"gone": "127.0.0.1:1"},  # nothing listens there
        node_backoff_secs=30.0, op_timeout_secs=0.2,
    )
    assert hub.scrape_once() == 0
    failures = router.metrics.counter("fleet/hub_scrape_failures").value
    assert failures == 1
    # within the backoff the dead node is not re-dialed
    t["now"] += 1.0
    assert hub.scrape_once() == 0
    assert (
        router.metrics.counter("fleet/hub_scrape_failures").value
        == failures
    )
    # past the backoff it is
    t["now"] += 60.0
    hub.scrape_once()
    assert (
        router.metrics.counter("fleet/hub_scrape_failures").value
        == failures + 1
    )


# ---------------------------------------------------------------------------
# the node agent's control ops over a real loopback socket
# ---------------------------------------------------------------------------
def test_node_metrics_snapshot_op_ships_engine_registries():
    node = _node(("r0", "r1"))
    node.start()
    try:
        client = NodeControlClient(node.address)
        # drive one request through r0 so its counters move
        replica = SocketReplica(
            "n0:r0", node.address, remote_name="r0", rpc_timeout=2.0,
        )
        replica.start()
        try:
            req = replica.submit([5], max_new_tokens=2)
            assert req.result(10.0) == [6, 7]
        finally:
            replica.shutdown()
        reply = client.metrics_snapshot()
        assert reply["node"] == "n0"
        assert set(reply["replicas"]) == {"r0", "r1"}
        by_name = {
            e["name"]: e for e in reply["replicas"]["r0"]
        }
        assert by_name["infer/requests_submitted"]["value"] == 1.0
        assert by_name["infer/requests_completed"]["value"] == 1.0
        assert by_name["infer/tokens_generated"]["value"] == 2.0
        assert by_name["infer/ttft_ms"]["kind"] == "histogram"
        # the idle replica answers too, with zeroed counters
        idle = {e["name"]: e for e in reply["replicas"]["r1"]}
        assert idle["infer/requests_submitted"]["value"] == 0.0
        # everything JSON-safe end to end (it crossed the wire already,
        # but pin the round-trip explicitly)
        json.dumps(reply)
    finally:
        node.shutdown()


def test_node_drain_telemetry_op_ships_spans_and_flight():
    node = _node(tracing=True)
    node.start()
    try:
        replica = SocketReplica(
            "n0:r0", node.address, remote_name="r0", rpc_timeout=2.0,
        )
        replica.start()
        try:
            req = replica.submit([9], max_new_tokens=2)
            assert req.result(10.0) == [10, 11]
        finally:
            replica.shutdown()
        client = NodeControlClient(node.address)
        reply = client.drain_telemetry()
        spans = reply["spans"]
        assert any(s["name"] == "node.submit" for s in spans)
        sub = next(s for s in spans if s["name"] == "node.submit")
        assert sub["attrs"]["node"] == "n0"
        assert sub["attrs"]["replica"] == "r0"
        assert sub["sampled"] is True
        assert "flight_events" not in reply
        # the drain drained: a second pull is empty until new traffic
        assert client.drain_telemetry()["spans"] == []
        # flight=True additionally ships the ring, with the drain
        # breadcrumb recorded INSIDE it
        flight = client.drain_telemetry(flight=True, reason="test")
        names = [e["name"] for e in flight["flight_events"]]
        assert "node.flight_drain" in names
        assert "node.submit" in names  # the ring keeps history
    finally:
        node.shutdown()


def test_node_without_tracing_drains_empty():
    node = _node(tracing=False)
    node.start()
    try:
        reply = NodeControlClient(node.address).drain_telemetry(flight=True)
        assert reply["spans"] == []
        assert reply["flight_events"] == []
    finally:
        node.shutdown()


def test_hub_drain_once_ingests_remote_spans(tmp_path):
    node = _node(node_id="nd", tracing=True)
    node.start()
    router_tracer = SpanTracer(
        sample_rate=1.0, ring_events=64,
        export_path=str(tmp_path / "trace.json"),
        dump_dir=str(tmp_path),
    )
    router = _FakeRouter(tracer=router_tracer)
    try:
        replica = SocketReplica(
            "nd:r0", node.address, remote_name="r0", rpc_timeout=2.0,
        )
        replica.start()
        try:
            assert replica.submit([1], max_new_tokens=1).result(10.0)
        finally:
            replica.shutdown()
        host, port = node.address
        hub = _hub(
            router, time.time, nodes={"nd": f"{host}:{port}"},
        )
        # the node's spans carry the node PROCESS pid; NodeServer here is
        # in-process, so re-stamp them remote-looking via a fake pid to
        # exercise the ingest path the way a real fleet does
        real_drain = hub._make_client(f"{host}:{port}").drain_telemetry()
        assert real_drain["spans"]  # sanity: there was something to ship
        for s in real_drain["spans"]:
            s["pid"] = 999999
        ingested = router_tracer.ingest(real_drain["spans"])
        assert ingested == len(real_drain["spans"])
        router_tracer.flush()
        router_tracer.close()
        from deepspeed_tpu.telemetry.tracing import load_chrome_trace

        events = load_chrome_trace(str(tmp_path / "trace.json"))
        assert any(e["name"] == "node.submit" for e in events)
        assert {e["pid"] for e in events} == {999999}
        # the drain counters move through the real drain_once sweep
        spans, dump = hub.drain_once(flight=True, reason="unit")
        assert router.metrics.counter("fleet/hub_drains").value == 1
        assert dump is None or dump  # dump path only when dump_dir set
    finally:
        node.shutdown()


# ---------------------------------------------------------------------------
# the door's observability endpoints
# ---------------------------------------------------------------------------
def _get(host, port, path, headers=None):
    conn = http.client.HTTPConnection(host, port, timeout=30.0)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _fleet(hub=None, **door_kw):
    node = _node(node_id="dn", tracing=False)
    node.start()
    replica = SocketReplica(
        "dn:r0", node.address, remote_name="r0", rpc_timeout=2.0,
    )
    router = FleetRouter(
        [replica], monitor_interval=0.01, telemetry_refresh_secs=3600.0,
        hub=hub,
    ).start()
    door = HTTPDoor(router, **door_kw)
    host, port = door.start()
    return node, router, door, host, port


def test_door_hub_endpoints_serve_the_fleet_view():
    t_node = _node(node_id="dn2", tracing=False)
    t_node.start()
    try:
        host_n, port_n = t_node.address
        hub = TelemetryHub(
            nodes={"dn2": f"{host_n}:{port_n}"}, interval_secs=0.05,
            alert_fast_window_secs=10.0, alert_slow_window_secs=30.0,
        )
        node, router, door, host, port = _fleet(hub=hub)
        try:
            assert router.submit([4], max_new_tokens=2).result(10.0)
            hub.scrape_once()
            status, headers, body = _get(host, port, "/metrics")
            assert status == 200
            assert headers["Content-Type"].startswith("text/plain")
            text = body.decode()
            assert "fleet_requests_completed 1.0" in text
            assert 'node="dn2",replica="r0"' in text
            status, _h, body = _get(host, port, "/statz")
            assert status == 200
            statz = json.loads(body)
            assert statz["nodes"] == ["dn2"]
            assert "dn2/r0" in statz["replicas"]
            status, headers, body = _get(host, port, "/dashboard")
            assert status == 200
            assert headers["Content-Type"].startswith("text/html")
            assert b"EventSource" in body
            # wrong method on a hub path: 405, not 404
            conn = http.client.HTTPConnection(host, port, timeout=10.0)
            conn.request("POST", "/metrics")
            assert conn.getresponse().status == 405
            conn.close()
        finally:
            door.shutdown()
            router.shutdown()
            node.shutdown()
    finally:
        t_node.shutdown()


def test_door_404s_hub_paths_without_a_hub():
    node, router, door, host, port = _fleet(hub=None)
    try:
        assert router.hub is None
        for path in HUB_HTTP_PATHS:
            status, _h, _b = _get(host, port, path)
            assert status == 404, path
        # and no hub thread exists anywhere in the process
        assert not [
            t for t in threading.enumerate()
            if t.name.startswith("ds-hub")
        ]
    finally:
        door.shutdown()
        router.shutdown()
        node.shutdown()


def test_door_auth_exemption_covers_hub_paths():
    hub = TelemetryHub(auth_exempt=("/metrics", "/statz"))
    node, router, door, host, port = _fleet(
        hub=hub, auth_token="hub-secret",
    )
    try:
        hub.scrape_once()
        # exempted paths answer without credentials (probe-style)
        assert _get(host, port, "/metrics")[0] == 200
        assert _get(host, port, "/statz")[0] == 200
        # the exemption prefix covers the SSE sub-path too -- but the
        # dashboard was NOT exempted, so it still wants the bearer token
        assert _get(host, port, "/dashboard")[0] == 401
        assert _get(
            host, port, "/dashboard",
            headers={"Authorization": "Bearer hub-secret"},
        )[0] == 200
    finally:
        door.shutdown()
        router.shutdown()
        node.shutdown()


def test_statz_stream_emits_sse_frames():
    hub = TelemetryHub(interval_secs=0.05)
    node, router, door, host, port = _fleet(hub=hub)
    try:
        hub.scrape_once()
        import socket as socketlib

        sock = socketlib.create_connection((host, port))
        sock.settimeout(10.0)
        sock.sendall(
            b"GET /statz/stream HTTP/1.1\r\nHost: door\r\n\r\n"
        )
        buf = b""
        # two frames prove the loop re-arms, not just the first emit
        while buf.count(b"event: statz") < 2:
            chunk = sock.recv(4096)
            assert chunk, "stream closed before two statz frames"
            buf += chunk
        assert b"200 OK" in buf
        assert b"text/event-stream" in buf
        frame = [
            line for line in buf.split(b"\n")
            if line.startswith(b"data: ")
        ][0]
        state = json.loads(frame[6:])
        assert "windows" in state and "spark" in state
        sock.close()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if router.metrics.gauge("door/open_streams").value == 0:
                break
            time.sleep(0.01)
        assert router.metrics.gauge("door/open_streams").value == 0
    finally:
        door.shutdown()
        router.shutdown()
        node.shutdown()


# ---------------------------------------------------------------------------
# router wiring: tick drives the hub; shutdown closes it
# ---------------------------------------------------------------------------
def test_router_tick_drives_hub_and_shutdown_joins_it():
    hub = TelemetryHub(interval_secs=0.02)
    node, router, door, host, port = _fleet(hub=hub)
    try:
        assert router.hub is hub
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if router.metrics.gauge("fleet/hub_series").value > 0:
                break
            time.sleep(0.01)
        assert router.metrics.gauge("fleet/hub_series").value > 0, (
            "the router monitor never drove a hub scrape"
        )
    finally:
        door.shutdown()
        router.shutdown()
        node.shutdown()
    assert hub._closed
    assert not [
        t for t in threading.enumerate() if t.name.startswith("ds-hub")
    ]


# ---------------------------------------------------------------------------
# config validation (serving.hub block)
# ---------------------------------------------------------------------------
def _cfg(hub_block):
    return DeepSpeedConfig(None, param_dict={
        "train_batch_size": 1,
        "serving": {"hub": hub_block},
    }, world_size=1)


def test_hub_config_defaults_and_arming():
    cfg = _cfg({"enabled": True, "interval_secs": 0.5,
                "alerts": {"fast_window_secs": 5, "slow_window_secs": 50}})
    assert cfg.serving_hub_enabled is True
    assert cfg.serving_hub_interval_secs == 0.5
    assert cfg.serving_hub_retention_points == 512
    assert cfg.serving_hub_alerts_fast_window_secs == 5
    assert cfg.serving_hub_alerts_slow_window_secs == 50
    disabled = DeepSpeedConfig(
        None, param_dict={"train_batch_size": 1}, world_size=1,
    )
    assert disabled.serving_hub_enabled is False


@pytest.mark.parametrize("block", [
    {"enabled": True, "bogus_key": 1},
    {"enabled": "yes"},
    {"enabled": True, "interval_secs": 0},
    {"enabled": True, "retention_points": 1},
    {"enabled": True, "drain_interval_secs": -1},
    {"enabled": True, "auth_exempt": ["/not-a-hub-path"]},
    {"enabled": True, "auth_exempt": "/metrics"},
    {"enabled": True, "alerts": {"bogus": 1}},
    {"enabled": True, "alerts": {"slo_target": 1.0}},
    {"enabled": True, "alerts": {"fast_window_secs": 60,
                                 "slow_window_secs": 60}},
    {"enabled": True, "alerts": {"fast_burn": 0}},
])
def test_hub_config_rejects_bad_blocks(block):
    with pytest.raises(DeepSpeedConfigError):
        _cfg(block)
