"""TPU-VM provisioning helper (launcher/cloud.py) — the reference's
azure/ cluster-script analog, tested as pure command construction (no
gcloud in CI, mirroring how azure/create_vms.sh is config-driven)."""

import json

import pytest

from deepspeed_tpu.launcher import cloud


CFG = {
    "name": "ds-pod",
    "zone": "us-central2-b",
    "accelerator_type": "v5e-16",
    "version": "tpu-ubuntu2204-base",
}


def test_create_command():
    cmd = cloud.build_create_command(dict(CFG))
    assert cmd[:6] == [
        "gcloud", "compute", "tpus", "tpu-vm", "create", "ds-pod"
    ]
    assert "--accelerator-type" in cmd and "v5e-16" in cmd
    assert "--spot" not in cmd
    spot = cloud.build_create_command(dict(CFG, spot=True))
    assert "--spot" in spot


def test_project_override_and_delete():
    cmd = cloud.build_delete_command(dict(CFG, project="my-proj"))
    assert ["--project", "my-proj"] == cmd[-3:-1]
    assert cmd[-1] == "--quiet"


def test_ssh_command_with_worker_and_remote_command():
    cmd = cloud.build_ssh_command(dict(CFG), worker="3", command="hostname")
    assert "--worker=3" in cmd
    assert cmd[-2:] == ["--command", "hostname"]


def test_hostfile_from_describe():
    describe = json.dumps({
        "acceleratorType": "v5litepod-8",
        "networkEndpoints": [
            {"ipAddress": "10.0.0.2"},
            {"ipAddress": "10.0.0.3"},
        ]
    })
    text = cloud.hostfile_from_describe(describe)
    assert text == "10.0.0.2 slots=4\n10.0.0.3 slots=4\n"
    # round-trips through the launcher's hostfile parser
    from deepspeed_tpu.launcher.runner import fetch_hostfile

    import tempfile, os

    with tempfile.NamedTemporaryFile("w", suffix=".host", delete=False) as f:
        f.write(text)
        path = f.name
    try:
        pool = fetch_hostfile(path)
    finally:
        os.unlink(path)
    assert pool == {"10.0.0.2": 4, "10.0.0.3": 4}


def test_hostfile_slots_derive_from_accelerator_type():
    """Slot counts come from the SAME acceleratorType logic the runtime
    --tpu discovery uses (runner.pod_resource_pool_from_describe) — a
    sub-host v5litepod-1 slice gets 1 slot, not a hardcoded 4."""
    describe = json.dumps({
        "acceleratorType": "v5litepod-1",
        "networkEndpoints": [{"ipAddress": "10.0.0.2"}],
    })
    assert cloud.hostfile_from_describe(describe) == "10.0.0.2 slots=1\n"
    # explicit override still wins
    assert cloud.hostfile_from_describe(
        describe, slots_per_host=2
    ) == "10.0.0.2 slots=2\n"


def test_hostfile_errors():
    with pytest.raises(ValueError, match="networkEndpoints"):
        cloud.hostfile_from_describe("{}")
    with pytest.raises(ValueError, match="networkEndpoints"):
        cloud.hostfile_from_describe(
            json.dumps({"networkEndpoints": [{"port": 8470}]})
        )


def test_config_validation(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"name": "x", "zone": "z"}))
    with pytest.raises(ValueError, match="accelerator_type"):
        cloud.load_config(str(p))
    p.write_text(json.dumps(CFG))
    assert cloud.load_config(str(p))["name"] == "ds-pod"


def test_cli_dry_run_hostfile(tmp_path, monkeypatch, capsys):
    import io, sys

    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(CFG))
    describe = json.dumps(
        {"networkEndpoints": [{"ipAddress": "10.1.0.9"}]}
    )
    monkeypatch.setattr(sys, "stdin", io.StringIO(describe))
    rc = cloud.main(["hostfile", "--config", str(p), "--dry-run"])
    assert rc == 0
    assert capsys.readouterr().out == "10.1.0.9 slots=4\n"


def test_cli_dry_run_create(tmp_path, capsys):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(CFG))
    rc = cloud.main(["create", "--config", str(p), "--dry-run"])
    assert rc == 0
    assert "tpu-vm create ds-pod" in capsys.readouterr().err
