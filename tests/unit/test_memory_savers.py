"""Memory-saver features that put GPT-2 1.5B on one chip: blocked LM-head
cross-entropy (ops/cross_entropy.py) and reduced-precision optimizer-moment
storage (ops/quant.py via Adam/Lamb state_dtype)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.bert import cross_entropy_ignore_index
from deepspeed_tpu.ops.cross_entropy import blocked_lm_head_loss
from deepspeed_tpu.ops.optimizers import Adam, Lamb
from deepspeed_tpu.ops import quant

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


# ------------------------------------------------------------ blocked CE
@pytest.mark.parametrize("block_rows", [32, 100, 256])
def test_blocked_ce_matches_naive_forward(block_rows):
    rng = np.random.default_rng(0)
    B, S, H, V = 2, 33, 16, 257
    x = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    W = jnp.asarray(rng.normal(size=(V, H)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, size=(B, S)), jnp.int32)
    labels = labels.at[0, :5].set(-1)  # ignore some positions
    naive = cross_entropy_ignore_index(x @ W.T, labels)
    blocked = blocked_lm_head_loss(x, W, labels, block_rows=block_rows)
    np.testing.assert_allclose(
        np.asarray(blocked), np.asarray(naive), rtol=1e-5, atol=1e-5
    )


def test_blocked_ce_matches_naive_gradients():
    rng = np.random.default_rng(1)
    B, S, H, V = 2, 17, 16, 130
    x = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    W = jnp.asarray(rng.normal(size=(V, H)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, size=(B, S)), jnp.int32)

    def loss_naive(x, W):
        return cross_entropy_ignore_index(x @ W.T, labels)

    def loss_blocked(x, W):
        return blocked_lm_head_loss(x, W, labels, block_rows=64)

    gx1, gW1 = jax.grad(loss_naive, argnums=(0, 1))(x, W)
    gx2, gW2 = jax.grad(loss_blocked, argnums=(0, 1))(x, W)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gW1), np.asarray(gW2), rtol=2e-5, atol=2e-5)


def test_blocked_ce_empty_ignore_values_counts_all_labels():
    """ignore_values=() with a non-dividing T: pad positions are masked by
    index, so label-0 padding is never counted and the empty tuple doesn't
    crash (round-3 advisor finding)."""
    rng = np.random.default_rng(4)
    B, S, H, V = 2, 13, 16, 64  # 13 % block_rows(8) != 0 -> padded
    x = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    W = jnp.asarray(rng.normal(size=(V, H)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, size=(B, S)), jnp.int32)
    labels = labels.at[:, :3].set(0)  # real label-0 targets must count
    naive = cross_entropy_ignore_index(x @ W.T, labels, ignore_values=())
    blocked = blocked_lm_head_loss(
        x, W, labels, block_rows=8, ignore_values=()
    )
    np.testing.assert_allclose(
        np.asarray(blocked), np.asarray(naive), rtol=1e-5, atol=1e-5
    )


def test_blocked_ce_all_ignored_is_zero():
    x = jnp.zeros((1, 4, 8), jnp.float32)
    W = jnp.zeros((32, 8), jnp.float32)
    labels = jnp.full((1, 4), -1, jnp.int32)
    out = blocked_lm_head_loss(x, W, labels, block_rows=4)
    assert float(out) == 0.0


# ------------------------------------------------------ quantized moments
def test_quant_roundtrip_accuracy():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(3000,)) * 0.01, jnp.float32)
    q = quant.quantize(x)
    back = quant.dequantize(q, x.shape)
    # blockwise absmax int8: worst-case error is absmax/254 per block
    err = np.abs(np.asarray(back) - np.asarray(x)).max()
    assert err <= float(jnp.max(jnp.abs(x))) / 127.0


def test_quant_zero_block_decodes_zero():
    x = jnp.zeros((4096,), jnp.float32)
    q = quant.quantize(x)
    assert np.asarray(quant.dequantize(q, x.shape)).max() == 0.0


def _quad_problem():
    rng = np.random.default_rng(3)
    target = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    params = {"w": jnp.zeros((64, 32), jnp.float32),
              "b": jnp.zeros((64,), jnp.float32)}

    def loss(p):
        return jnp.mean((p["w"] - target) ** 2) + jnp.mean(p["b"] ** 2)

    return params, loss


@pytest.mark.parametrize("state_dtype", ["bf16", "int8"])
def test_adam_reduced_state_converges(state_dtype):
    params, loss = _quad_problem()
    ref_opt, red_opt = Adam(), Adam(state_dtype=state_dtype)
    ref_state, red_state = ref_opt.init(params), red_opt.init(params)
    ref_p, red_p = params, params
    lr = jnp.float32(0.05)
    for _ in range(60):
        g_ref = jax.grad(loss)(ref_p)
        ref_p, ref_state, _ = ref_opt.apply(ref_p, g_ref, ref_state, lr)
        g_red = jax.grad(loss)(red_p)
        red_p, red_state, _ = red_opt.apply(red_p, g_red, red_state, lr)
    assert float(loss(red_p)) < 0.05 * float(loss(params))
    # trajectories stay close to fp32-state Adam (int8 mu wobbles a bit
    # more than bf16; both must track, not diverge)
    np.testing.assert_allclose(
        np.asarray(red_p["w"]), np.asarray(ref_p["w"]), atol=0.2
    )


def test_adam_state_dtype_memory_layout():
    params = {"w": jnp.zeros((4096, 8), jnp.float32)}
    s8 = Adam(state_dtype="int8").init(params)
    assert s8["mu"]["w"]["q"].dtype == jnp.int8
    assert s8["mu"]["w"]["q"].size == 4096 * 8
    assert s8["mu"]["w"]["scale"].size == 4096 * 8 // quant.BLOCK
    sb = Adam(state_dtype="bf16").init(params)
    assert sb["nu"]["w"].dtype == jnp.bfloat16


def test_lamb_reduced_state_converges():
    params, loss = _quad_problem()
    opt = Lamb(state_dtype="bf16")
    state = opt.init(params)
    p = params
    for _ in range(90):
        p, state, aux = opt.apply(p, jax.grad(loss)(p), state, jnp.float32(0.05))
    assert float(loss(p)) < 0.1 * float(loss(params))
    assert aux["lamb_coeffs"]


@pytest.mark.parametrize("state_pad_blocks", [1, 16])
@pytest.mark.parametrize("compensated", [False, True])
@pytest.mark.parametrize("state_dtype", ["fp32", "bf16", "int8"])
def test_chunked_leaf_update_matches_whole_leaf(
    state_dtype, compensated, state_pad_blocks, monkeypatch
):
    """Large stacked leaves update in place slice-by-slice (bounds HLO
    temps on 16GB chips); the math must match the whole-leaf path to
    float-associativity noise. The int8 leaf shape is BLOCK-aligned per
    slice so the quantized dynamic-slice branch is genuinely exercised
    (a misaligned shape silently falls back to whole-leaf).
    ``state_pad_blocks > 1`` adds a ZeRO-alignment padded tail to the
    quantized storage: the chunked loop's DUS writes must leave it
    bit-zero (a corrupt tail silently breaks dp-sharded elastic
    resume)."""
    from deepspeed_tpu.ops import optimizers as O
    from deepspeed_tpu.ops.quant import BLOCK

    rng = np.random.default_rng(0)
    # per leading-axis row: 2 * BLOCK elements -> per_slice % BLOCK == 0
    shape = (4, 2, BLOCK)
    dtype = jnp.bfloat16 if compensated else jnp.float32
    params = {"w": jnp.asarray(rng.normal(size=shape), dtype)}
    grads = {"w": jnp.asarray(rng.normal(size=shape), dtype)}

    # spy: the chunked path must genuinely engage (None = silent fallback)
    engaged = []
    orig = O._chunked_leaf_update

    def spy(*a, **k):
        out = orig(*a, **k)
        engaged.append(out is not None)
        return out

    monkeypatch.setattr(O, "_chunked_leaf_update", spy)
    opt = O.Adam(
        state_dtype=state_dtype, master_compensation=compensated,
        state_pad_blocks=state_pad_blocks,
        chunk_elements=BLOCK,  # force chunking
        flat_quant_update=False,  # the CHUNKED path is under test here
    )
    s0 = opt.init(params)
    p1, s1, _ = opt.apply(params, grads, s0, jnp.float32(1e-2))
    assert any(engaged), "chunked path silently fell back to whole-leaf"
    monkeypatch.setattr(O, "_chunked_leaf_update", orig)

    if state_dtype == "int8" and state_pad_blocks > 1:
        # the data tail past p.size (here 8 of 16 aligned blocks) is pure
        # ZeRO padding: a chunked step must keep its q codes AND scales
        # bit-zero (only mu quantizes under "int8"; nu stores bf16)
        n_data = params["w"].size
        mu = s1["mu"]["w"]
        assert mu["q"].size == state_pad_blocks * BLOCK
        assert not np.asarray(mu["q"][n_data:]).any()
        assert not np.asarray(mu["scale"][n_data // BLOCK:]).any()

    opt2 = O.Adam(
        state_dtype=state_dtype, master_compensation=compensated,
        state_pad_blocks=state_pad_blocks,
        chunk_elements=1 << 60,  # whole-leaf
    )
    p2, s2, _ = opt2.apply(params, grads, opt2.init(params), jnp.float32(1e-2))

    np.testing.assert_allclose(
        np.asarray(p1["w"], np.float32), np.asarray(p2["w"], np.float32),
        rtol=1e-5, atol=1e-6,
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(s1), jax.tree_util.tree_leaves(s2)
    ):
        if a.dtype == jnp.int8:
            # comp codes: fused-vs-loop rounding ties may differ by one
            # code step (= ulp/254 of the master) on a handful of elements
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1.0
            )
        else:
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=1e-5, atol=1e-6,
            )


# ------------------------------------------------- compensated masters
def test_master_compensation_roundtrip_bound():
    rng = np.random.default_rng(1)
    m = jnp.asarray(rng.normal(size=(4096,)), jnp.float32)
    p, code = quant.encode_master(m, jnp.bfloat16)
    assert p.dtype == jnp.bfloat16 and code.dtype == jnp.int8
    back = np.asarray(quant.decode_master(p, code))
    err = np.abs(back - np.asarray(m))
    ulp = np.abs(np.asarray(m)) * 2**-8
    # residual error after compensation <= ulp/254 (one code step / 2)
    assert (err / np.maximum(ulp, 1e-30)).max() < 1.0 / 200


def test_compensated_adam_tracks_fp32_master_trajectory():
    """bf16 params + int8 Kahan codes must reproduce the fp32-master
    update (same bf16 forward) — the property that lets GPT-2 1.5B drop
    the fp32 param bytes without giving up master precision."""
    rng = np.random.default_rng(0)
    target = jnp.asarray(rng.normal(size=(256, 64)), jnp.float32)

    def loss(p):
        return jnp.mean((p["w"].astype(jnp.float32) - target) ** 2)

    master = {"w": jnp.zeros((256, 64), jnp.float32)}
    o32 = Adam()
    s32 = o32.init(master)
    pbf = {"w": jnp.zeros((256, 64), jnp.bfloat16)}
    oc = Adam(master_compensation=True)
    sc = oc.init(pbf)
    assert sc["comp"]["w"].dtype == jnp.int8
    lr = jnp.float32(1e-3)  # updates below one bf16 ulp exercise the carry
    for _ in range(300):
        gm = jax.grad(loss)({"w": master["w"].astype(jnp.bfloat16)})
        master, s32, _ = o32.apply(master, gm, s32, lr)
        gb = jax.grad(loss)(pbf)
        pbf, sc, _ = oc.apply(pbf, gb, sc, lr)
    lm, lc = float(loss(master)), float(loss(pbf))
    assert abs(lc - lm) / max(lm, 1e-9) < 0.01, (lm, lc)
    # plain bf16 (no compensation) must be measurably worse
    ppl = {"w": jnp.zeros((256, 64), jnp.bfloat16)}
    opl = Adam()
    spl = opl.init(ppl)
    for _ in range(300):
        ppl, spl, _ = opl.apply(ppl, jax.grad(loss)(ppl), spl, lr)
    assert abs(float(loss(ppl)) - lm) > 10 * abs(lc - lm)


def test_compensation_survives_jit():
    """Regression: computing the rounding residue via an astype roundtrip
    is FOLDED AWAY by XLA's excess-precision simplification under jit —
    codes silently stay zero and compensation becomes a no-op exactly in
    production (compiled) steps. encode_master must round via
    lax.reduce_precision instead; jit and eager must agree."""
    rng = np.random.default_rng(0)
    m = jnp.asarray(rng.normal(size=(4096,)), jnp.float32)
    p_e, c_e = quant.encode_master(m, jnp.bfloat16)
    p_j, c_j = jax.jit(lambda x: quant.encode_master(x, jnp.bfloat16))(m)
    assert int(np.count_nonzero(np.asarray(c_j))) > 3000
    np.testing.assert_array_equal(np.asarray(c_e), np.asarray(c_j))
    np.testing.assert_array_equal(
        np.asarray(p_e, np.float32), np.asarray(p_j, np.float32)
    )
    back = jax.jit(quant.decode_master)(p_j, c_j)
    err = np.abs(np.asarray(back) - np.asarray(m))
    ulp = np.abs(np.asarray(m)) * 2**-8
    assert (err / np.maximum(ulp, 1e-30)).max() < 1.0 / 200


def test_compensated_engine_codes_become_nonzero():
    """End-to-end through the engine's COMPILED update: after a few steps
    the int8 Kahan codes must be populated (zero codes = the jit elision
    regression)."""
    import flax.linen as nn

    import deepspeed_tpu
    from deepspeed_tpu.parallel.mesh import build_mesh

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            h = nn.relu(nn.Dense(32)(x))
            logp = jax.nn.log_softmax(nn.Dense(4)(h))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

    rng = np.random.default_rng(0)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int32)
    model = M()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        mesh=build_mesh(data_parallel_size=8),
        config_params={
            "train_batch_size": 16,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "data_types": {"master_dtype": "compensated"},
            "steps_per_print": 10_000,
        },
    )
    for _ in range(6):
        loss = engine(X, Y)
        engine.backward(loss)
        engine.step()
    nonzero = sum(
        int(np.count_nonzero(np.asarray(l)))
        for l in jax.tree_util.tree_leaves(engine.optimizer_state["comp"])
    )
    total = sum(
        l.size for l in jax.tree_util.tree_leaves(engine.optimizer_state["comp"])
    )
    assert nonzero > 0.3 * total, (nonzero, total)


def test_compensated_engine_end_to_end(tmp_path):
    import flax.linen as nn

    import deepspeed_tpu
    from deepspeed_tpu.parallel.mesh import build_mesh

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            h = nn.relu(nn.Dense(32)(x))
            logp = jax.nn.log_softmax(nn.Dense(4)(h))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

    rng = np.random.default_rng(0)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int32) + 2 * (X[:, 1] > 0).astype(np.int32)
    model = M()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]

    def engine(seed=0):
        e, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            mesh=build_mesh(data_parallel_size=8),
            config_params={
                "train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "data_types": {"master_dtype": "compensated",
                               "optimizer_state_dtype": "int8"},
                "steps_per_print": 10_000,
            },
            rng_seed=seed,
        )
        return e

    e = engine()
    assert e.compensated_master and not e.master_in_opt
    for leaf in jax.tree_util.tree_leaves(e.params):
        assert leaf.dtype == e.compute_dtype  # no fp32 storage
    assert "comp" in e.optimizer_state

    losses = []
    for _ in range(12):
        loss = e(X, Y)
        e.backward(loss)
        e.step()
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses

    # exact same-mode checkpoint resume (comp codes ride the opt state)
    e.save_checkpoint(str(tmp_path), tag="t")
    cont = []
    for _ in range(6):
        loss = e(X, Y)
        e.backward(loss)
        e.step()
        cont.append(float(loss))
    fresh = engine(seed=7)
    fresh.load_checkpoint(str(tmp_path), tag="t")
    resumed = []
    for _ in range(6):
        loss = fresh(X, Y)
        fresh.backward(loss)
        fresh.step()
        resumed.append(float(loss))
    np.testing.assert_allclose(resumed, cont, rtol=1e-5)


# ------------------------------------------------------- engine plumbing
def test_engine_optimizer_state_dtype_config():
    import flax.linen as nn

    import deepspeed_tpu
    from deepspeed_tpu.parallel.mesh import build_mesh

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            logp = jax.nn.log_softmax(nn.Dense(4)(x))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

    rng = np.random.default_rng(0)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int32)
    model = M()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        mesh=build_mesh(data_parallel_size=8),
        config_params={
            "train_batch_size": 16,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "data_types": {"optimizer_state_dtype": "int8"},
            "steps_per_print": 10_000,
        },
    )
    mu = engine.optimizer_state["mu"]
    leaves = jax.tree_util.tree_leaves(mu)
    assert any(l.dtype == jnp.int8 for l in leaves)
    # one training window works end to end
    loss0 = engine(X, Y)
    engine.backward(loss0)
    engine.step()
    loss1 = engine(X, Y)
    engine.backward(loss1)
    engine.step()
    assert float(loss1) <= float(loss0)


def test_engine_int8_moments_shard_under_zero():
    """int8 moment storage and ZeRO sharding COMPOSE (round-3 verdict #4):
    under stage>=1 with dp>1 the quantized {'q','scale'} leaves keep int8
    storage AND shard over the data axis (flat layout, block count padded
    to dp) — per-chip moment bytes ~ total/dp on top of the 4x dtype
    saving. Training through the sharded quantized state must work."""
    import flax.linen as nn

    import deepspeed_tpu
    from deepspeed_tpu.config.constants import DATA_AXIS
    from deepspeed_tpu.parallel.mesh import build_mesh

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            h = nn.relu(nn.Dense(64)(x))
            logp = jax.nn.log_softmax(nn.Dense(4)(h))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

    rng = np.random.default_rng(0)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int32)
    model = M()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        mesh=build_mesh(data_parallel_size=8),
        config_params={
            "train_batch_size": 16,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "data_types": {"optimizer_state_dtype": "int8"},
            "steps_per_print": 10_000,
        },
    )
    inner = (
        engine.optimizer_state["inner"]
        if engine.master_in_opt else engine.optimizer_state
    )
    from deepspeed_tpu.ops.quant import BLOCK, is_quantized

    n_sharded = 0
    for leaf in jax.tree_util.tree_leaves(
        inner["mu"], is_leaf=is_quantized
    ):
        if not is_quantized(leaf):
            continue
        assert leaf["q"].dtype == jnp.int8
        assert leaf["scale"].shape[0] % 8 == 0  # padded to dp
        spec_q = leaf["q"].sharding.spec
        spec_s = leaf["scale"].sharding.spec
        assert spec_q == (DATA_AXIS,), spec_q
        assert spec_s == (DATA_AXIS,), spec_s
        # shard boundaries land on quant-block boundaries
        assert (leaf["q"].shape[0] // 8) % BLOCK == 0
        n_sharded += 1
    assert n_sharded > 0
    # training through the sharded quantized state converges
    losses = []
    for _ in range(12):
        loss = engine(X, Y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < 0.7 * losses[0], losses


def test_engine_rejects_reduced_state_for_fused_lamb():
    import flax.linen as nn

    import deepspeed_tpu
    from deepspeed_tpu.config.config import DeepSpeedConfigError

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return jnp.mean(nn.Dense(4)(x) ** 2)

    model = M()
    X = jnp.zeros((8, 4), jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0)}, X)["params"]
    with pytest.raises(DeepSpeedConfigError, match="FusedLamb"):
        deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config_params={
                "train_batch_size": 8,
                "optimizer": {"type": "FusedLamb", "params": {"lr": 1e-2}},
                "data_types": {"optimizer_state_dtype": "bf16"},
            },
        )


def test_engine_rejects_state_dtype_for_unsupported_optimizer():
    import flax.linen as nn

    import deepspeed_tpu
    from deepspeed_tpu.config.config import DeepSpeedConfigError

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return jnp.mean(nn.Dense(4)(x) ** 2)

    model = M()
    X = jnp.zeros((8, 4), jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0)}, X)["params"]
    with pytest.raises(DeepSpeedConfigError):
        deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config_params={
                "train_batch_size": 8,
                "optimizer": {"type": "SGD", "params": {"lr": 1e-2}},
                "data_types": {"optimizer_state_dtype": "bf16"},
            },
        )


def test_int8_zero_state_elastic_dp_resume(tmp_path):
    """Quantized ZeRO state must survive an elastic dp-resize resume: the
    pad multiple is dp-INDEPENDENT (max(256, dp)), so a dp4-saved
    checkpoint deserializes bit-for-bit into a dp8 engine's template
    (round-4 review finding: padding to dp itself baked the saving mesh
    into the stored shapes)."""
    import flax.linen as nn

    import deepspeed_tpu
    from deepspeed_tpu.parallel.mesh import build_mesh

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            h = nn.relu(nn.Dense(64)(x))
            logp = jax.nn.log_softmax(nn.Dense(4)(h))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

    rng = np.random.default_rng(0)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int32)
    model = M()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]

    def make(dp, mp):
        e, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            mesh=build_mesh(data_parallel_size=dp, model_parallel_size=mp),
            config_params={
                "train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "data_types": {"optimizer_state_dtype": "int8",
                               "master_dtype": "compensated"},
                "steps_per_print": 10_000,
            },
            rng_seed=0,
        )
        return e

    saver = make(dp=4, mp=2)
    for _ in range(6):
        loss = saver(X, Y)
        saver.backward(loss)
        saver.step()
    saver.save_checkpoint(str(tmp_path), tag="el")
    saver.eval()
    fp = float(saver(X, Y))

    loader = make(dp=8, mp=1)
    loader.load_checkpoint(str(tmp_path), tag="el")
    assert loader.global_steps == 6
    loader.eval()
    np.testing.assert_allclose(float(loader(X, Y)), fp, rtol=1e-5)
    # resumed training keeps working on the new layout
    loader.train()
    loss = loader(X, Y)
    loader.backward(loss)
    loader.step()
    assert np.isfinite(float(loss))


def test_int8_checkpoint_crosses_pad_policies(tmp_path):
    """A checkpoint saved with UNPADDED quantized state (stage 0 / dp1 —
    also the pre-padding on-disk format) must load into an engine whose
    template pads blocks for ZeRO sharding: load-time normalization
    resizes the zero tail (runtime/checkpointing._normalize_quant_padding)."""
    import flax.linen as nn

    import deepspeed_tpu
    from deepspeed_tpu.parallel.mesh import build_mesh

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            h = nn.relu(nn.Dense(64)(x))
            logp = jax.nn.log_softmax(nn.Dense(4)(h))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

    rng = np.random.default_rng(0)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int32)
    model = M()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]

    def make(stage, dp):
        e, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            mesh=build_mesh(
                devices=jax.devices()[:dp], data_parallel_size=dp
            ),
            config_params={
                "train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": stage},
                "data_types": {"optimizer_state_dtype": "int8",
                               "master_dtype": "compensated"},
                "steps_per_print": 10_000,
            },
            rng_seed=0,
        )
        return e

    saver = make(stage=0, dp=1)  # unpadded quantized leaves
    for _ in range(5):
        loss = saver(X, Y)
        saver.backward(loss)
        saver.step()
    saver.save_checkpoint(str(tmp_path), tag="pads")
    saver.eval()
    fp = float(saver(X, Y))

    loader = make(stage=1, dp=8)  # template pads blocks to 256
    from deepspeed_tpu.ops.quant import is_quantized

    tq = [l for l in jax.tree_util.tree_leaves(
        loader.optimizer_state["mu"], is_leaf=is_quantized) if is_quantized(l)]
    sq = [l for l in jax.tree_util.tree_leaves(
        saver.optimizer_state["mu"], is_leaf=is_quantized) if is_quantized(l)]
    assert tq[0]["scale"].shape != sq[0]["scale"].shape  # genuinely crossing pads
    loader.load_checkpoint(str(tmp_path), tag="pads")
    assert loader.global_steps == 5
    loader.eval()
    np.testing.assert_allclose(float(loader(X, Y)), fp, rtol=1e-5)
    loader.train()
    loss = loader(X, Y)
    loader.backward(loss)
    loader.step()
    assert np.isfinite(float(loss))

    # TRUNCATION direction: the padded dp8 checkpoint loads back into a
    # fresh unpadded stage-0 engine (merge-then-drop-zero-tail)
    loader.save_checkpoint(str(tmp_path), tag="padded")
    loader.eval()
    fp2 = float(loader(X, Y))
    back = make(stage=0, dp=1)
    back.load_checkpoint(str(tmp_path), tag="padded")
    assert back.global_steps == 6
    back.eval()
    np.testing.assert_allclose(float(back(X, Y)), fp2, rtol=1e-5)


@pytest.mark.parametrize("state_pad_blocks", [1, 16])
@pytest.mark.parametrize("compensated", [False, True])
def test_flat_quant_update_matches_whole_leaf(compensated, state_pad_blocks):
    """The padded-flat-domain int8 update (Adam.flat_quant_update — an
    OPT-IN path, default OFF: the round-5 bench platform's TPU compiler
    crashes on it at 1.5B scale; the chunked path stays the measured
    default) must match the shaped whole-leaf path to float noise, and
    keep the ZeRO padded tail bit-zero."""
    from deepspeed_tpu.ops import optimizers as O
    from deepspeed_tpu.ops.quant import BLOCK

    rng = np.random.default_rng(0)
    shape = (4, 2, BLOCK)
    dtype = jnp.bfloat16 if compensated else jnp.float32
    params = {"w": jnp.asarray(rng.normal(size=shape), dtype)}
    grads = {"w": jnp.asarray(rng.normal(size=shape), dtype)}

    flat = O.Adam(
        state_dtype="int8", master_compensation=compensated,
        state_pad_blocks=state_pad_blocks,
        chunk_elements=BLOCK,  # size threshold met -> flat path engages
        flat_quant_update=True,
    )
    whole = O.Adam(
        state_dtype="int8", master_compensation=compensated,
        state_pad_blocks=state_pad_blocks,
        chunk_elements=1 << 60,  # whole-leaf shaped path
        flat_quant_update=True,  # inert below the threshold
    )
    lr = jnp.float32(1e-2)
    p1, s1, _ = flat.apply(params, grads, flat.init(params), lr)
    p2, s2, _ = whole.apply(params, grads, whole.init(params), lr)
    np.testing.assert_allclose(
        np.asarray(p1["w"], np.float32), np.asarray(p2["w"], np.float32),
        rtol=1e-5, atol=1e-6,
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(s1), jax.tree_util.tree_leaves(s2)
    ):
        if a.dtype == jnp.int8:
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1.0
            )
        else:
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=1e-5, atol=1e-6,
            )
    if state_pad_blocks > 1:
        n_data = params["w"].size
        mu = s1["mu"]["w"]
        assert not np.asarray(mu["q"][n_data:]).any()
        assert not np.asarray(mu["scale"][n_data // BLOCK:]).any()


def test_flat_quant_update_gate_is_bitexact_noop():
    from deepspeed_tpu.ops import optimizers as O
    from deepspeed_tpu.ops.quant import BLOCK

    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(rng.normal(size=(4, 2, BLOCK)), jnp.bfloat16)}
    grads = {"w": jnp.asarray(rng.normal(size=(4, 2, BLOCK)), jnp.bfloat16)}
    opt = O.Adam(
        state_dtype="int8", master_compensation=True,
        chunk_elements=BLOCK, flat_quant_update=True,
    )
    s0 = opt.init(params)
    # one real step to produce nonzero state, then a gated-off step
    p1, s1, _ = opt.apply(params, grads, s0, jnp.float32(1e-2))
    p2, s2, _ = opt.apply(
        p1, grads, s1, jnp.float32(1e-2), gate=jnp.bool_(False)
    )
    np.testing.assert_array_equal(np.asarray(p1["w"]), np.asarray(p2["w"]))
    for a, b in zip(
        jax.tree_util.tree_leaves(
            {k: s1[k] for k in ("mu", "nu", "comp")}
        ),
        jax.tree_util.tree_leaves(
            {k: s2[k] for k in ("mu", "nu", "comp")}
        ),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
