"""Checkpoint save/load tests.

Coverage mirrors the reference's tests/unit/test_checkpointing.py:
save -> load -> compare module weights, optimizer state per ZeRO stage,
LR scheduler state, loss-scale state, client state; plus the elastic
dp-resize merge-and-reshard path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import build_mesh
from tests.unit.simple_model import SimpleModel, config_dict, init_model, random_dataset

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

INPUT_DIM = 16


def make_engine(cfg, seed=0, mesh=None):
    model = SimpleModel(hidden_dim=32)
    params = init_model(model, INPUT_DIM, seed=seed)
    engine, opt, _, sched = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg, mesh=mesh
    )
    return engine


def run_steps(engine, n=3, seed=0):
    bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
    x, y = random_dataset(bs * n, INPUT_DIM, seed=seed)
    for b in range(n):
        loss = engine(x[b * bs : (b + 1) * bs], y[b * bs : (b + 1) * bs])
        engine.backward(loss)
        engine.step()


def trees_equal(a, b, rtol=1e-6, atol=1e-7):
    for la, lb in zip(
        jax.tree_util.tree_leaves(jax.tree_util.tree_map(np.asarray, a)),
        jax.tree_util.tree_leaves(jax.tree_util.tree_map(np.asarray, b)),
    ):
        np.testing.assert_allclose(la, lb, rtol=rtol, atol=atol)


@pytest.mark.parametrize("stage", [0, 1, 2])
def test_checkpoint_roundtrip(tmp_path, stage):
    cfg = config_dict(batch_size=16, lr=1e-2, zero_stage=stage)
    cfg["scheduler"] = {
        "type": "WarmupLR",
        "params": {"warmup_max_lr": 1e-2, "warmup_num_steps": 10},
    }
    engine = make_engine(cfg, seed=1)
    run_steps(engine, n=3)
    engine.save_checkpoint(str(tmp_path), client_state={"epoch": 7})

    engine2 = make_engine(cfg, seed=2)  # different init
    path, client = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client["epoch"] == 7
    assert engine2.global_steps == engine.global_steps
    trees_equal(engine.params, engine2.params)
    trees_equal(engine.optimizer_state, engine2.optimizer_state)
    assert (
        engine2.lr_scheduler.last_batch_iteration
        == engine.lr_scheduler.last_batch_iteration
    )

    # resumed training proceeds identically from both engines
    run_steps(engine, n=2, seed=9)
    run_steps(engine2, n=2, seed=9)
    trees_equal(engine.params, engine2.params, rtol=1e-5, atol=1e-6)


def test_checkpoint_fp16_scaler_state(tmp_path):
    cfg = config_dict(batch_size=16, fp16=True, lr=1e-2)
    engine = make_engine(cfg)
    run_steps(engine, n=3)
    scale_before = float(engine.loss_scale_state.loss_scale)
    engine.save_checkpoint(str(tmp_path))
    engine2 = make_engine(cfg)
    engine2.load_checkpoint(str(tmp_path))
    assert float(engine2.loss_scale_state.loss_scale) == scale_before
    assert engine2.skipped_steps == engine.skipped_steps


def test_elastic_dp_resize(tmp_path):
    """Save at dp=8, load at dp=4 x mp=2: the reference's elastic
    merge-and-reshard (deepspeed_zero_optimizer.py:1483-1538)."""
    cfg = config_dict(batch_size=16, lr=1e-2, zero_stage=2)
    engine = make_engine(cfg, seed=1)
    assert engine.dp_world_size == 8
    run_steps(engine, n=3)
    engine.save_checkpoint(str(tmp_path))

    mesh42 = build_mesh(model_parallel_size=2)  # dp=4, mp=2 on 8 devices
    cfg2 = config_dict(batch_size=16, lr=1e-2, zero_stage=2)
    engine2 = make_engine(cfg2, seed=3, mesh=mesh42)
    assert engine2.dp_world_size == 4
    engine2.load_checkpoint(str(tmp_path))
    trees_equal(engine.params, engine2.params)
    trees_equal(engine.optimizer_state, engine2.optimizer_state)

    # and training still works at the new dp size
    run_steps(engine2, n=1)
    assert engine2.global_steps == engine.global_steps + 1


def test_load_missing_checkpoint(tmp_path):
    engine = make_engine(config_dict(batch_size=16, lr=1e-2))
    path, client = engine.load_checkpoint(str(tmp_path))
    assert path is None and client == {}


def test_latest_tag_tracking(tmp_path):
    engine = make_engine(config_dict(batch_size=16, lr=1e-2))
    run_steps(engine, n=1)
    engine.save_checkpoint(str(tmp_path), tag="tagA")
    run_steps(engine, n=1)
    engine.save_checkpoint(str(tmp_path), tag="tagB")
    engine2 = make_engine(config_dict(batch_size=16, lr=1e-2))
    engine2.load_checkpoint(str(tmp_path))  # should pick tagB via latest
    assert engine2.global_steps == engine.global_steps


# ---------------------------------------------------------------------------
# Multi-host write discipline (reference deepspeed_light.py:1282-1360)
# ---------------------------------------------------------------------------
def test_multihost_write_guard(tmp_path, monkeypatch):
    """Under n_processes > 1 only process 0 writes model states + latest;
    optimizer shard files are split round-robin across processes; the
    barrier runs before the tag is published."""
    from deepspeed_tpu.runtime import checkpointing as ckpt

    engine = make_engine(config_dict(batch_size=16, lr=1e-2, zero_stage=2))
    run_steps(engine, n=1)

    calls = []
    monkeypatch.setattr(ckpt, "_barrier", lambda name: calls.append(name))

    # --- pretend to be process 1 of 2 --------------------------------
    monkeypatch.setattr(ckpt.jax, "process_index", lambda: 1)
    monkeypatch.setattr(ckpt.jax, "process_count", lambda: 2)
    d1 = tmp_path / "p1"
    engine.save_checkpoint(str(d1), tag="t")
    files1 = sorted(p.name for p in (d1 / "t").glob("*"))
    assert not any("model_states" in f for f in files1), files1
    assert not (d1 / "latest").exists()
    # process 1 of 2 owns the odd dp shards only
    dp = engine.dp_world_size
    expected = {
        ckpt.OPTIM_FILE.format(dp=r, mp=0) for r in range(dp) if r % 2 == 1
    }
    assert set(files1) == expected, (files1, expected)
    assert calls == ["ckpt_save_t"]

    # --- process 0 of 2 ----------------------------------------------
    monkeypatch.setattr(ckpt.jax, "process_index", lambda: 0)
    d0 = tmp_path / "p0"
    engine.save_checkpoint(str(d0), tag="t")
    files0 = sorted(p.name for p in (d0 / "t").glob("*"))
    assert any("model_states" in f for f in files0), files0
    assert (d0 / "latest").read_text() == "t"
    even = {ckpt.OPTIM_FILE.format(dp=r, mp=0) for r in range(dp) if r % 2 == 0}
    # process 0 also writes the commit record (MANIFEST.json, after the
    # barrier, before publishing `latest` — resilience commit protocol)
    assert set(files0) == even | {
        ckpt.MODEL_FILE.format(mp=0), "MANIFEST.json",
    }, files0
