"""Real multi-process distributed tests — the reference's
``@distributed_test`` spawner analog (reference: tests/unit/common.py:14-100
forks N ranks and init_process_group's NCCL between them).

Here each rank is a REAL subprocess: the launcher's DS_TPU_* environment
drives ``runtime/dist.py``'s ``jax.distributed.initialize`` bootstrap
(exactly the path a pod takes), the ranks rendezvous over localhost, and a
global mesh spans both processes — crossing an actual process boundary,
which the in-process 8-virtual-device harness cannot.

Each rank runs on the CPU backend with one local device, so the global
mesh is 2 devices over 2 processes.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RANK_BODY = """
import os, sys
sys.path.insert(0, {repo!r})

import deepspeed_tpu  # auto-runs the DS_TPU_* jax.distributed bootstrap
import jax

assert deepspeed_tpu.runtime.dist.is_initialized(), "bootstrap did not run"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()
assert jax.local_device_count() == 1

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()), ("data",))

# a global array sharded over the two processes; psum-style reduction via
# jit: each rank contributes its own slice
rank = jax.process_index()
local = np.full((1, 4), float(rank + 1), np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data", None)), local, (2, 4)
)
total = jax.jit(
    lambda x: jnp.sum(x, axis=0), out_shardings=NamedSharding(mesh, P())
)(garr)
np.testing.assert_allclose(np.asarray(total), np.full((4,), 3.0))
print(f"RANK{{rank}} OK", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


ENGINE_BODY = """
import os, sys
sys.path.insert(0, {repo!r})

import deepspeed_tpu
import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

assert jax.process_count() == 2

from deepspeed_tpu.parallel.mesh import build_mesh

mesh = build_mesh(data_parallel_size=2)  # one device per process


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, y, train=True):
        h = nn.relu(nn.Dense(32)(x))
        logits = nn.Dense(4)(h)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))


rank = jax.process_index()
rng = np.random.default_rng(0)  # SAME global data on both ranks...
X = rng.normal(size=(8, 8)).astype(np.float32)
Y = (X[:, 0] > 0).astype(np.int32) + 2 * (X[:, 1] > 0).astype(np.int32)
# ...but each rank feeds only ITS half (DistributedSampler contract)
Xl, Yl = X[rank * 4:(rank + 1) * 4], Y[rank * 4:(rank + 1) * 4]

model = MLP()
params = model.init({{"params": jax.random.PRNGKey(0)}},
                    jnp.asarray(X), jnp.asarray(Y))["params"]
engine, _, _, _ = deepspeed_tpu.initialize(
    model=model, model_parameters=params, mesh=mesh,
    config_params={{
        "train_batch_size": 8,
        "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
        "zero_optimization": {{"stage": 2}},
        "steps_per_print": 10_000,
    }},
    rng_seed=0,
)
assert engine.dp_world_size == 2
losses = []
for _ in range(20):
    loss = engine(Xl, Yl)   # per-host slice in, global batch assembled
    engine.backward(loss)
    engine.step()
    losses.append(float(loss))
assert losses[-1] < 0.5 * losses[0], losses
print(f"RANK{{rank}} ENGINE OK first={{losses[0]:.4f}} last={{losses[-1]:.4f}}",
      flush=True)

# dataloader path: every host sees the same GLOBAL dataset; the loader
# gives each host its slice and _place stitches the global batch
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

loader = DeepSpeedDataLoader((X, Y), batch_size=8, mesh=mesh, shuffle=True)
engine.eval()
for bx, by in loader:
    assert bx.shape[0] == 8, bx.shape          # global rows
    assert not bx.is_fully_addressable          # spans both processes
    l_eval = engine(bx, by)
print(f"RANK{{rank}} LOADER OK eval={{float(l_eval):.6f}}", flush=True)
"""


def _run_ranks(tmp_path, body, tag):
    port = _free_port()
    script = tmp_path / f"rank_{tag}.py"
    script.write_text(textwrap.dedent(body.format(repo=REPO)))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        for var in list(env):
            if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
                env.pop(var)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["JAX_PLATFORMS"] = "cpu"
        env.update({
            "DS_TPU_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "DS_TPU_NUM_PROCESSES": "2",
            "DS_TPU_PROCESS_ID": str(rank),
        })
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"rank {rank} hung (rendezvous deadlock?)")
        outs.append(out)
    for rank, (proc, out) in enumerate(zip(procs, outs)):
        assert proc.returncode == 0, f"rank {rank} failed:\n{out}"
    return outs


def test_two_process_engine_training(tmp_path):
    """Full engine training across a REAL process boundary: 2 ranks, each
    feeding its own half of the global batch; ZeRO-2 shards optimizer
    state across the two hosts; the loss must drop and agree between
    ranks (it is a replicated global mean)."""
    outs = _run_ranks(tmp_path, ENGINE_BODY, "engine")
    lasts, evals = [], []
    for rank, out in enumerate(outs):
        line = [l for l in out.splitlines() if f"RANK{rank} ENGINE OK" in l]
        assert line, out
        lasts.append(line[0].split("last=")[1])
        lline = [l for l in out.splitlines() if f"RANK{rank} LOADER OK" in l]
        assert lline, out
        evals.append(lline[0].split("eval=")[1])
    assert lasts[0] == lasts[1], f"ranks disagree on the loss: {lasts}"
    assert evals[0] == evals[1], f"ranks disagree on the eval loss: {evals}"


def test_two_process_rendezvous_and_collective(tmp_path):
    outs = _run_ranks(tmp_path, RANK_BODY, "collective")
    for rank, out in enumerate(outs):
        assert f"RANK{rank} OK" in out, out
