"""Real multi-process distributed tests — the reference's
``@distributed_test`` spawner analog (reference: tests/unit/common.py:14-100
forks N ranks and init_process_group's NCCL between them).

Here each rank is a REAL subprocess: the launcher's DS_TPU_* environment
drives ``runtime/dist.py``'s ``jax.distributed.initialize`` bootstrap
(exactly the path a pod takes), the ranks rendezvous over localhost, and a
global mesh spans both processes — crossing an actual process boundary,
which the in-process 8-virtual-device harness cannot.

Each rank runs on the CPU backend with one local device, so the global
mesh is 2 devices over 2 processes.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RANK_BODY = """
import os, sys
sys.path.insert(0, {repo!r})

import deepspeed_tpu  # auto-runs the DS_TPU_* jax.distributed bootstrap
import jax

WORLD = int(os.environ["DS_TPU_NUM_PROCESSES"])
assert deepspeed_tpu.runtime.dist.is_initialized(), "bootstrap did not run"
assert jax.process_count() == WORLD, jax.process_count()
assert jax.device_count() == WORLD, jax.device_count()
assert jax.local_device_count() == 1

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()), ("data",))

# a global array sharded over the processes; psum-style reduction via
# jit: each rank contributes its own slice
rank = jax.process_index()
local = np.full((1, 4), float(rank + 1), np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data", None)), local, (WORLD, 4)
)
total = jax.jit(
    lambda x: jnp.sum(x, axis=0), out_shardings=NamedSharding(mesh, P())
)(garr)
expect = WORLD * (WORLD + 1) / 2.0
np.testing.assert_allclose(np.asarray(total), np.full((4,), expect))
print(f"RANK{{rank}} OK", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


ENGINE_BODY = """
import os, sys
sys.path.insert(0, {repo!r})

import deepspeed_tpu
import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

WORLD = int(os.environ["DS_TPU_NUM_PROCESSES"])
assert jax.process_count() == WORLD

from deepspeed_tpu.parallel.mesh import build_mesh

mesh = build_mesh(data_parallel_size=WORLD)  # one device per process


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, y, train=True):
        h = nn.relu(nn.Dense(32)(x))
        logits = nn.Dense(4)(h)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))


rank = jax.process_index()
rng = np.random.default_rng(0)  # SAME global data on all ranks...
X = rng.normal(size=(8, 8)).astype(np.float32)
Y = (X[:, 0] > 0).astype(np.int32) + 2 * (X[:, 1] > 0).astype(np.int32)
# ...but each rank feeds only ITS slice (DistributedSampler contract)
per = 8 // WORLD
Xl, Yl = X[rank * per:(rank + 1) * per], Y[rank * per:(rank + 1) * per]

model = MLP()
params = model.init({{"params": jax.random.PRNGKey(0)}},
                    jnp.asarray(X), jnp.asarray(Y))["params"]
engine, _, _, _ = deepspeed_tpu.initialize(
    model=model, model_parameters=params, mesh=mesh,
    config_params={{
        "train_batch_size": 8,
        "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
        "zero_optimization": {{"stage": 2}},
        "steps_per_print": 10_000,
    }},
    rng_seed=0,
)
assert engine.dp_world_size == WORLD
losses = []
for _ in range(16):
    loss = engine(Xl, Yl)   # per-host slice in, global batch assembled
    engine.backward(loss)
    engine.step()
    losses.append(float(loss))
# the fused train_batch() window must also cross the process boundary
for _ in range(4):
    loss = engine.train_batch([(Xl, Yl)])
    losses.append(float(loss))
assert losses[-1] < 0.5 * losses[0], losses
print(f"RANK{{rank}} ENGINE OK first={{losses[0]:.4f}} last={{losses[-1]:.4f}}",
      flush=True)

# dataloader path: every host sees the same GLOBAL dataset; the loader
# gives each host its slice and _place stitches the global batch
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

loader = DeepSpeedDataLoader((X, Y), batch_size=8, mesh=mesh, shuffle=True)
engine.eval()
for bx, by in loader:
    assert bx.shape[0] == 8, bx.shape          # global rows
    assert not bx.is_fully_addressable          # spans all processes
    l_eval = engine(bx, by)
print(f"RANK{{rank}} LOADER OK eval={{float(l_eval):.6f}}", flush=True)
"""


def _run_ranks(tmp_path, body, tag, world=2, extra_env=None, fmt=None):
    port = _free_port()
    script = tmp_path / f"rank_{tag}.py"
    script.write_text(textwrap.dedent(body.format(repo=REPO, **(fmt or {}))))
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        for var in list(env):
            if var.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
                env.pop(var)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["JAX_PLATFORMS"] = "cpu"
        env.update({
            "DS_TPU_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "DS_TPU_NUM_PROCESSES": str(world),
            "DS_TPU_PROCESS_ID": str(rank),
        })
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"rank {rank} hung (rendezvous deadlock?)")
        outs.append(out)
    for rank, (proc, out) in enumerate(zip(procs, outs)):
        assert proc.returncode == 0, f"rank {rank} failed:\n{out}"
    return outs


@pytest.mark.parametrize("world", [2, 4])
def test_multi_process_engine_training(tmp_path, world):
    """Full engine training across REAL process boundaries (world sizes 2
    and 4, the reference harness's world_size=[1,2,4] grid,
    tests/unit/common.py:14-100): each rank feeds its slice of the global
    batch; ZeRO-2 shards optimizer state across the hosts; unfused steps
    AND the fused train_batch() window run; the loss must drop and agree
    between ranks (it is a replicated global mean)."""
    outs = _run_ranks(tmp_path, ENGINE_BODY, f"engine{world}", world=world)
    lasts, evals = [], []
    for rank, out in enumerate(outs):
        line = [l for l in out.splitlines() if f"RANK{rank} ENGINE OK" in l]
        assert line, out
        lasts.append(line[0].split("last=")[1])
        lline = [l for l in out.splitlines() if f"RANK{rank} LOADER OK" in l]
        assert lline, out
        evals.append(lline[0].split("eval=")[1])
    assert len(set(lasts)) == 1, f"ranks disagree on the loss: {lasts}"
    assert len(set(evals)) == 1, f"ranks disagree on the eval loss: {evals}"


@pytest.mark.parametrize("world", [2, 4])
def test_multi_process_rendezvous_and_collective(tmp_path, world):
    outs = _run_ranks(tmp_path, RANK_BODY, f"collective{world}", world=world)
    for rank, out in enumerate(outs):
        assert f"RANK{rank} OK" in out, out


CKPT_BODY = """
import os, sys
sys.path.insert(0, {repo!r})

import deepspeed_tpu
import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

WORLD = int(os.environ["DS_TPU_NUM_PROCESSES"])
PHASE = os.environ["CKPT_PHASE"]          # "save" | "load"
CKPT_DIR = os.environ["CKPT_DIR"]
assert jax.process_count() == WORLD

from deepspeed_tpu.parallel.mesh import build_mesh

mesh = build_mesh(data_parallel_size=WORLD)


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, y, train=True):
        h = nn.relu(nn.Dense(32)(x))
        logp = jax.nn.log_softmax(nn.Dense(4)(h))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))


rank = jax.process_index()
rng = np.random.default_rng(0)
X = rng.normal(size=(8, 8)).astype(np.float32)
Y = (X[:, 0] > 0).astype(np.int32) + 2 * (X[:, 1] > 0).astype(np.int32)
per = 8 // WORLD
Xl, Yl = X[rank * per:(rank + 1) * per], Y[rank * per:(rank + 1) * per]

model = MLP()
params = model.init({{"params": jax.random.PRNGKey(0)}},
                    jnp.asarray(X), jnp.asarray(Y))["params"]
engine, _, _, _ = deepspeed_tpu.initialize(
    model=model, model_parameters=params, mesh=mesh,
    config_params={{
        "train_batch_size": 8,
        "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
        "bf16": {{"enabled": True}},
        "zero_optimization": {{"stage": 2}},
        "steps_per_print": 10_000,
    }},
    rng_seed=0,
)

if PHASE == "save":
    for _ in range(10):
        loss = engine(Xl, Yl)
        engine.backward(loss)
        engine.step()
    engine.save_checkpoint(CKPT_DIR, tag="elastic")
    # post-save eval loss on a FIXED batch (divisible by both world
    # sizes) is the cross-phase fingerprint
    engine.eval()
    fp = float(engine(X[:4], Y[:4]))
    print(f"RANK{{rank}} SAVE OK steps={{engine.global_steps}} fp={{fp:.6f}}",
          flush=True)
else:
    engine.load_checkpoint(CKPT_DIR, tag="elastic")
    engine.eval()
    fp = float(engine(X[:4], Y[:4]))
    print(f"RANK{{rank}} LOAD OK steps={{engine.global_steps}} fp={{fp:.6f}}",
          flush=True)
    # resumed training must keep working on the NEW world size
    engine.train()
    for _ in range(4):
        loss = engine(Xl, Yl)
        engine.backward(loss)
        engine.step()
    print(f"RANK{{rank}} RESUME OK loss={{float(loss):.4f}}", flush=True)
"""


def test_checkpoint_elastic_dp2_to_dp4(tmp_path):
    """Checkpoint save on a dp2 process mesh, elastic reload on dp4 — the
    reference's elastic DP-resize capability (merge all shards, reshard on
    the current mesh, runtime/checkpointing.py:254+) exercised across REAL
    process boundaries in BOTH directions. The restored model must produce
    the saver's post-save eval loss bit-for-bit on the new world size."""
    ckpt_dir = str(tmp_path / "ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)
    save_outs = _run_ranks(
        tmp_path, CKPT_BODY, "ckpt_save", world=2,
        extra_env={"CKPT_PHASE": "save", "CKPT_DIR": ckpt_dir},
    )
    fps = []
    for rank, out in enumerate(save_outs):
        line = [l for l in out.splitlines() if f"RANK{rank} SAVE OK" in l]
        assert line, out
        assert "steps=10" in line[0], line
        fps.append(line[0].split("fp=")[1])
    assert len(set(fps)) == 1

    load_outs = _run_ranks(
        tmp_path, CKPT_BODY, "ckpt_load", world=4,
        extra_env={"CKPT_PHASE": "load", "CKPT_DIR": ckpt_dir},
    )
    for rank, out in enumerate(load_outs):
        line = [l for l in out.splitlines() if f"RANK{rank} LOAD OK" in l]
        assert line, out
        assert "steps=10" in line[0], line  # counters restored
        # eval fingerprint on the SAME batch must match the saver's —
        # dp2-sharded state was merged and resharded onto dp4 losslessly.
        # (Tolerance, not bit-equality: dp2 and dp4 group the mean's
        # cross-device reduction differently, which may differ in the
        # last ulp.)
        got = float(line[0].split("fp=")[1])
        want = float(fps[0])
        assert abs(got - want) <= 1e-5 * max(abs(want), 1e-6), (line, fps)
        assert f"RANK{rank} RESUME OK" in out, out
