"""Host-memory spill tier (docs/inference.md "Host-memory spill tier"):
HostTier unit behavior (bitwise roundtrip, byte-budget LRU, checksum
drops, share-group refcounts), the BlockPool/AdapterPool spill seams
under threaded eviction-vs-acquire stress, and the engine-level pins —
D2H→H2D page promotion bitwise parity, peer warming across co-hosted
engines, preempt-park-resume exactness under lazy page growth, adapter
auto-load with generation restore, and ``host_tier.copy`` chaos
absorption (corrupt promotion re-prefills, never serves wrong pages)."""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.adapters import init_lora_params
from deepspeed_tpu.adapters.pool import AdapterPool, AdapterPoolFull
from deepspeed_tpu.inference import BlockPool, HostTier
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

VOCAB = 97


def _small_model(seed=0, **kw):
    cfg = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        dropout=0.0, use_flash=False, **kw,
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jnp.asarray(
        np.random.default_rng(seed).integers(0, VOCAB, (1, 8)), jnp.int32
    )
    params = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        ids0, ids0,
    )["params"]
    return cfg, model, params


def _prompt(n=8, seed=1):
    return [int(t) for t in np.random.default_rng(seed).integers(0, VOCAB, n)]


def _engine(model, params, inference=None, adapters=None, resilience=None):
    block = {"max_batch_slots": 4, "max_seq_len": 48, "prefill_len": 32,
             "kv_block_size": 8, "sampling": {"greedy": True}}
    block.update(inference or {})
    if block.get("kv_block_size") == 0:
        block.pop("kv_block_size")
    config = {"inference": block}
    if adapters is not None:
        ad = {"enabled": True, "rank": 2, "pool_slots": 4}
        ad.update(adapters)
        config["adapters"] = ad
    if resilience is not None:
        config["resilience"] = resilience
    return deepspeed_tpu.init_inference(
        model=model, model_parameters=params, config=config,
    )


def _tier_block(group, **kw):
    ht = {"enabled": True, "share_group": group}
    ht.update(kw)
    return ht


def _synth_adapter(params, seed, rank=2, scale=0.2):
    ada = init_lora_params(
        jax.tree_util.tree_map(np.asarray, params), rank,
        rng=jax.random.PRNGKey(seed),
    )
    return jax.tree_util.tree_map(
        lambda a: np.asarray(
            jax.random.normal(
                jax.random.fold_in(jax.random.PRNGKey(seed), a.size),
                a.shape,
            ) * scale,
            np.float32,
        ),
        ada,
    )


# ---------------------------------------------------------------------------
# HostTier: the tier itself (jax-free)
# ---------------------------------------------------------------------------
def test_tier_roundtrip_bitwise_with_meta_and_origin():
    tier = HostTier(max_bytes=1 << 20)
    k = np.random.default_rng(0).random((2, 8, 4, 8), np.float32)
    v = np.random.default_rng(1).random((2, 8, 4, 8), np.float32)
    assert tier.put("h1", (k, v), meta={"kind": "kv"}, origin="engine-a")
    assert tier.contains("h1") and tier.entries == 1
    assert tier.occupancy_bytes == k.nbytes + v.nbytes
    placed, meta, origin = tier.fetch("h1", requester="engine-b")
    np.testing.assert_array_equal(placed[0], k)
    np.testing.assert_array_equal(placed[1], v)
    assert meta == {"kind": "kv"} and origin == "engine-a"
    assert tier.promotions == 1 and tier.peer_fetches == 1
    # same-origin fetch is NOT a peer fetch
    tier.fetch("h1", requester="engine-a")
    assert tier.peer_fetches == 1
    tier.close()


def test_tier_byte_budget_evicts_lru_first_injectable_clock():
    clock = [0.0]
    tier = HostTier(max_bytes=3 * 1024, clock=lambda: clock[0])
    page = np.zeros(256, np.float32)  # 1 KiB each
    for i, key in enumerate(("a", "b", "c")):
        clock[0] = float(i)
        assert tier.put(key, (page,))
    clock[0] = 10.0
    tier.fetch("a")  # refresh a's recency: b is now the LRU victim
    clock[0] = 11.0
    assert tier.put("d", (page,))
    assert tier.entries == 3 and tier.evictions == 1
    assert not tier.contains("b")
    assert tier.contains("a") and tier.contains("c") and tier.contains("d")
    tier.close()


def test_tier_pinned_entry_survives_budget_pressure():
    tier = HostTier(max_bytes=1024)
    page = np.zeros(256, np.float32)
    assert tier.put("pinned", (page,))
    handle = tier.fetch_async("pinned")  # pin without consuming
    assert tier.put("next", (page,))  # over budget, but "pinned" is pinned
    assert tier.contains("pinned")
    handle.result()  # placement done: unpinned
    assert tier.put("more", (page,))
    assert not tier.contains("pinned")  # now evictable, and evicted
    tier.close()


def test_tier_oversize_entry_rejected_outright():
    tier = HostTier(max_bytes=64)
    assert not tier.put("big", (np.zeros(1024, np.float32),))
    assert tier.entries == 0 and tier.spills == 0
    tier.close()


def test_tier_corrupt_entry_drops_at_fetch_as_cold_miss():
    """The chaos-garble (and real bit-rot) contract: the digest is
    computed over the CLEAN payload, the stored copy is mangled, and the
    promotion-time verify drops the entry — a corrupt page can only ever
    read as a miss, never be served."""
    tier = HostTier(max_bytes=1 << 20)
    page = np.arange(64, dtype=np.float32)
    assert tier.put("bad", (page,), corrupt=True)
    assert tier.contains("bad")
    assert tier.fetch_async("bad") is None
    assert tier.checksum_drops == 1 and not tier.contains("bad")
    assert tier.fetch("bad") is None  # stays a miss
    tier.close()


def test_tier_shared_group_identity_and_refcount_retirement():
    a = HostTier.shared("t-group-x", max_bytes=1 << 16).retain()
    b = HostTier.shared("t-group-x").retain()
    assert a is b
    assert HostTier.shared("t-group-y") is not a
    a.put("k", (np.zeros(8, np.float32),))
    a.release()
    assert b.contains("k")  # one ref left: still open
    b.release()
    # last release retired the group: a NEW tier, no leaked entries
    fresh = HostTier.shared("t-group-x").retain()
    try:
        assert fresh is not a and not fresh.contains("k")
    finally:
        fresh.release()


def test_tier_snapshot_counts():
    tier = HostTier(max_bytes=1 << 20)
    tier.put("a", (np.zeros(16, np.float32),), origin="e1")
    tier.fetch("a", requester="e2")
    snap = tier.snapshot()
    assert snap["entries"] == 1 and snap["spills"] == 1
    assert snap["promotions"] == 1 and snap["peer_fetches"] == 1
    assert snap["occupancy_bytes"] == 64
    tier.close()


# ---------------------------------------------------------------------------
# BlockPool spill seam
# ---------------------------------------------------------------------------
def test_block_pool_spill_fn_fires_on_eviction_with_hash():
    spilled = []
    pool = BlockPool(4, block_size=4, spill_fn=lambda b, h: spilled.append((b, h)))
    prompt = list(range(9))  # 2 full pages + tail
    blocks = pool.alloc(3)
    pool.register_prefix(prompt, blocks)
    pool.release(blocks)
    assert pool.cached_blocks == 2 and not spilled  # parked, not evicted
    pool.alloc(4)  # pressure: both cached pages evict -> spill first
    assert [b for b, _ in spilled] == blocks[:2]
    assert all(isinstance(h, str) and h for _, h in spilled)
    assert pool.reclaimed == 2 and pool.spill_errors == 0


def test_block_pool_spill_fn_failure_never_blocks_eviction():
    def boom(b, h):
        raise OSError("D2H copy failed")
    pool = BlockPool(2, block_size=4, spill_fn=boom)
    blocks = pool.alloc(2)
    pool.register_prefix(list(range(9)), blocks)
    pool.release(blocks)
    got = pool.alloc(2)  # eviction proceeds despite the failing spill
    assert len(got) == 2 and pool.spill_errors == 2


def test_threaded_eviction_vs_acquire_stress():
    """The PR's concurrency pin: BlockPool eviction (with a spill
    callback writing into a shared HostTier) racing prefix acquires on
    other threads, and AdapterPool assign/acquire/release churn against
    the same tier — refcount exactness and tier-internal locking must
    hold with no exceptions and no lost pages."""
    clock = [0.0]
    tier = HostTier(max_bytes=1 << 22, clock=lambda: clock[0])
    pool = BlockPool(
        16, block_size=4,
        spill_fn=lambda b, h: tier.put(h, (np.full(8, b, np.float32),)),
    )
    apool = AdapterPool(3)
    pool_lock = threading.Lock()  # BlockPool is single-driver by contract
    errors = []

    def kv_churn(seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(150):
                prompt = [int(t) for t in rng.integers(0, 50, 9)]
                with pool_lock:
                    try:
                        blocks = pool.alloc(3)
                    except Exception:
                        continue  # transient exhaustion: racing churn
                    _plen, shared = pool.match_prefix(prompt)
                    pool.register_prefix(prompt, blocks)
                    pool.release(blocks)
                    if shared:
                        pool.release(shared)
        except Exception as exc:  # pragma: no cover - the assertion
            errors.append(exc)

    def adapter_churn(seed):
        rng = np.random.default_rng(seed)
        names = [f"t{j}" for j in range(5)]
        try:
            for i in range(200):
                name = names[int(rng.integers(0, len(names)))]
                op = int(rng.integers(0, 3))
                if op == 0:
                    try:
                        idx, evicted = apool.assign(name)
                        if evicted is not None:
                            tier.put(
                                f"adapter/{evicted}",
                                (np.zeros(16, np.float32),),
                            )
                    except AdapterPoolFull:
                        pass
                elif op == 1:
                    try:
                        apool.acquire(name)
                        apool.release(name)
                    except KeyError:
                        pass
                else:
                    tier.fetch(f"adapter/{name}", timeout=5.0)
        except Exception as exc:  # pragma: no cover - the assertion
            errors.append(exc)

    threads = (
        [threading.Thread(target=kv_churn, args=(s,)) for s in range(2)]
        + [threading.Thread(target=adapter_churn, args=(s,)) for s in range(2)]
    )
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors
    assert pool.used_blocks == 0  # every alloc was released
    for name in apool.loaded:
        assert apool.active_count(name) == 0
    assert tier.occupancy_bytes <= tier.max_bytes
    tier.close()


# ---------------------------------------------------------------------------
# engine-level pins
# ---------------------------------------------------------------------------
def test_kv_spill_promote_bitwise_roundtrip():
    """The tentpole's correctness pin: evicted prefix pages park D2H,
    a chain-hash hit promotes them H2D into fresh private pages, and
    decode over promoted pages is BITWISE identical to the first
    (cold-prefilled) serve."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, {
        "kv_pool_blocks": 6, "host_tier": _tier_block("rt-g"),
    })
    try:
        shared = _prompt(16, 7)
        out1 = engine.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        assert engine.block_pool.cached_blocks == 2
        rs = [engine.submit(_prompt(8, 20 + i), max_new_tokens=8)
              for i in range(3)]
        engine.scheduler.run_until_idle()
        assert all(len(r.result(0)) == 8 for r in rs)
        snap = engine.kv_snapshot()
        assert snap["host_tier_spills"] >= 2
        assert engine.host_tier.entries >= 2
        out2 = engine.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        snap2 = engine.kv_snapshot()
        assert snap2["host_tier_promotions"] >= 1
        assert out2 == out1
        # tier metrics surfaced through the router-facing load snapshot
        load = engine.load_snapshot()
        assert load["host_tier_occupancy_bytes"] > 0
    finally:
        engine.close()


def test_peer_promotion_warms_cohosted_engine():
    """Peer sharing: two engines in one share group (the node agent's
    in-process replicas); replica A's evicted template pages serve
    replica B's FIRST templated request as a peer-promoted hit, bitwise
    equal to A's output."""
    cfg, model, params = _small_model()
    a = _engine(model, params, {
        "kv_pool_blocks": 6, "host_tier": _tier_block("peer-g"),
    })
    b = _engine(model, params, {
        "kv_pool_blocks": 6, "host_tier": _tier_block("peer-g"),
    })
    try:
        assert a.host_tier is b.host_tier
        shared = _prompt(16, 7)
        out_a = a.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        rs = [a.submit(_prompt(8, 40 + i), max_new_tokens=8)
              for i in range(3)]
        a.scheduler.run_until_idle()
        assert all(len(r.result(0)) == 8 for r in rs)
        assert a.kv_snapshot()["host_tier_spills"] >= 2
        out_b = b.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        sb = b.kv_snapshot()
        assert sb["host_tier_peer_fetches"] >= 1
        assert sb["prefix_hits"] >= 1  # promoted pages count as a HIT
        assert out_b == out_a
    finally:
        a.close()
        b.close()
    # the last close retired the share group
    fresh = HostTier.shared("peer-g").retain()
    try:
        assert fresh.entries == 0
    finally:
        fresh.release()


def test_preempt_park_resume_bitwise_exactness():
    """Lazy page growth: admission reserves only the prompt's pages;
    decode-time growth preempts the most recently admitted request when
    the pool runs dry. The preempted request's pages park (spillable to
    host), it re-enters the deferred line, resumes suffix-only, and
    EVERY request completes bitwise-identical to an unpressured run."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, {
        "kv_pool_blocks": 4, "max_batch_slots": 2,
        "host_tier": _tier_block("lazy-g", lazy_alloc=True),
    })
    ref = _engine(model, params, {
        "kv_pool_blocks": 12, "max_batch_slots": 2,
    })
    try:
        prompts = [_prompt(8, 60), _prompt(8, 61)]
        # worst case is 3 pages each (6 > 4): the old reservation could
        # never co-admit these; lazy admission runs them concurrently
        # and preempts when growth exhausts the pool
        rs = [engine.submit(p, max_new_tokens=16) for p in prompts]
        engine.scheduler.run_until_idle()
        outs = [r.result(0) for r in rs]
        assert all(len(o) == 16 for o in outs)  # zero requests lost
        snap = engine.kv_snapshot()
        assert snap["host_tier_preemptions"] >= 1
        cold = [ref.generate([p], max_new_tokens=16)[0] for p in prompts]
        assert outs == cold
    finally:
        engine.close()
        ref.close()


def test_adapter_spill_and_auto_load_with_generation_restore():
    """S-LoRA host paging: an adapter evicted by pool pressure parks its
    rows in the tier; a later submit for the known-but-not-resident name
    auto-loads it (same weights, ORIGINAL generation — its salted prefix
    pages stay valid) and serves bitwise vs an always-resident engine."""
    cfg, model, params = _small_model()
    sa, sb, sc = (_synth_adapter(params, s) for s in (1, 2, 3))
    engine = _engine(
        model, params,
        {"prefill_len": 16, "host_tier": _tier_block("ad-g")},
        adapters={"pool_slots": 2},
    )
    ref = _engine(model, params, {"prefill_len": 16},
                  adapters={"pool_slots": 2})
    try:
        engine.load_adapter("a", adapter_state=sa)
        engine.load_adapter("b", adapter_state=sb)
        gen_b = engine.adapter_registry.generation_of("b")
        # serve one request against "a": it becomes the most recently
        # used, so loading "c" under pool pressure evicts idle "b"
        engine.generate([_prompt(6, 4)], max_new_tokens=2, adapter="a")
        engine.load_adapter("c", adapter_state=sc)
        assert "b" not in engine.adapter_registry.loaded
        assert engine.host_tier.contains("adapter/b")
        out = engine.generate([_prompt(6, 5)], max_new_tokens=6,
                              adapter="b")[0]
        assert "b" in engine.adapter_registry.loaded
        assert engine.adapter_registry.generation_of("b") == gen_b
        assert engine.kv_snapshot()["host_tier_promotions"] >= 1
        ref.load_adapter("b", adapter_state=sb)
        assert out == ref.generate([_prompt(6, 5)], max_new_tokens=6,
                                   adapter="b")[0]
        # explicit unload is intentional removal: the tier copy drops
        # too, so the name cannot silently resurrect
        engine.unload_adapter("c")
        assert not engine.host_tier.contains("adapter/c")
        with pytest.raises(ValueError, match="not loaded"):
            engine.generate([_prompt(6, 5)], max_new_tokens=2, adapter="c")
    finally:
        engine.close()
        ref.close()


def test_host_tier_copy_fault_oserror_drops_spill_cold_path_serves():
    """Chaos site ``host_tier.copy`` (oserror mode): the D2H spill is
    skipped — the page simply drops as without the tier — and serving
    continues correct; the fault is counted."""
    cfg, model, params = _small_model()
    engine = _engine(
        model, params,
        {"kv_pool_blocks": 6, "host_tier": _tier_block("f1-g")},
        resilience={"fault_injection": {
            "enabled": True,
            "faults": [{"site": "host_tier.copy", "times": 2,
                        "args": {"mode": "oserror"}}],
        }},
    )
    try:
        shared = _prompt(16, 7)
        out1 = engine.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        rs = [engine.submit(_prompt(8, 20 + i), max_new_tokens=8)
              for i in range(3)]
        engine.scheduler.run_until_idle()
        [r.result(0) for r in rs]
        snap = engine.kv_snapshot()
        assert snap["host_tier_copy_faults"] == 2
        assert snap["host_tier_spills"] == 0  # both spills skipped
        assert engine.host_tier.entries == 0
        # the template re-serves correct via the cold path
        out2 = engine.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        assert out2 == out1
    finally:
        engine.close()


def test_host_tier_copy_fault_garble_checksum_drop_reprefills():
    """Chaos site ``host_tier.copy`` (garble mode): the spilled payload
    is corrupted AFTER the digest — the promotion-time checksum drops
    the entry, the request re-prefills cold, and output stays bitwise
    correct. Corrupt pages are never served."""
    cfg, model, params = _small_model()
    engine = _engine(
        model, params,
        {"kv_pool_blocks": 6, "host_tier": _tier_block("f2-g")},
        resilience={"fault_injection": {
            "enabled": True,
            "faults": [{"site": "host_tier.copy", "times": 2,
                        "args": {"mode": "garble"}}],
        }},
    )
    try:
        shared = _prompt(16, 7)
        out1 = engine.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        rs = [engine.submit(_prompt(8, 20 + i), max_new_tokens=8)
              for i in range(3)]
        engine.scheduler.run_until_idle()
        [r.result(0) for r in rs]
        snap = engine.kv_snapshot()
        assert snap["host_tier_copy_faults"] == 2
        assert snap["host_tier_spills"] == 2  # stored, but garbled
        out2 = engine.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        assert out2 == out1  # re-prefilled, never served the garble
        assert engine.host_tier.checksum_drops >= 1
        assert engine.host_tier.entries <= 1  # corrupt entries dropped
    finally:
        engine.close()


def test_decode_pages_register_as_shareable_prefixes():
    """Decode-page chain hashing: full blocks COMPLETED DURING DECODE
    register at release, so a generated continuation is shareable — a
    re-submit of prompt+continuation prefix-hits instead of recomputing
    it."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, {"kv_pool_blocks": 8})
    try:
        prompt = _prompt(8, 3)  # 1 full page
        out = engine.generate([prompt], max_new_tokens=10)[0]
        # prompt (8) + committed-kv tokens: full blocks beyond the
        # prompt's single page came from DECODE
        assert engine.block_pool.cached_blocks >= 2
        snap0 = engine.metrics.snapshot()
        follow = (prompt + out)[:16] + _prompt(4, 44)
        engine.generate([follow], max_new_tokens=2)
        snap1 = engine.metrics.snapshot()
        assert snap1["infer/prefix_hits"] == snap0["infer/prefix_hits"] + 1
    finally:
        engine.close()


def test_config_validation_matrix():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    def build(ht, adapters=None, kv_block_size=8):
        inf = {"max_batch_slots": 2, "max_seq_len": 32, "prefill_len": 16,
               "host_tier": ht}
        if kv_block_size:
            inf["kv_block_size"] = kv_block_size
        cfg = {"train_micro_batch_size_per_gpu": 1, "inference": inf}
        if adapters:
            cfg["adapters"] = adapters
        return DeepSpeedConfig(None, param_dict=cfg)

    cfg = build({"enabled": True, "max_bytes": 1024, "lazy_alloc": True})
    assert cfg.inference_host_tier_enabled
    assert cfg.inference_host_tier_max_bytes == 1024
    assert cfg.inference_host_tier_lazy_alloc
    assert cfg.inference_host_tier_share_group == "node"
    with pytest.raises(DeepSpeedConfigError, match="unknown"):
        build({"enabled": True, "max_byte": 1024})
    with pytest.raises(DeepSpeedConfigError, match="max_bytes"):
        build({"enabled": True, "max_bytes": 0})
    with pytest.raises(DeepSpeedConfigError, match="share_group"):
        build({"enabled": True, "share_group": ""})
    with pytest.raises(DeepSpeedConfigError, match="nothing to spill"):
        build({"enabled": True}, kv_block_size=0)
    # adapters alone are a valid reason for the tier (contiguous cache)
    assert build(
        {"enabled": True}, kv_block_size=0,
        adapters={"enabled": True, "rank": 2},
    ).inference_host_tier_enabled
    with pytest.raises(DeepSpeedConfigError, match="lazy_alloc"):
        build({"enabled": False, "lazy_alloc": True})
