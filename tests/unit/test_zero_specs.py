"""ZeRO partition-spec derivation, including the path-vs-shape mapping fix.

The subtle case: two params with the SAME shape but DIFFERENT model-parallel
specs (common under TP — an attention out-proj [H, H] sharded on dim 0 vs a
square FF matrix [H, H] sharded on dim 1).  Optimizer moments must inherit
each param's own spec, keyed by tree path, never by shape (reference keeps
optimizer state strictly per-param: deepspeed_zero_optimizer.py:256-263).
"""

import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime import zero as zero_lib


def _params_same_shape():
    return {
        "attn_out": jnp.zeros((8, 8), jnp.float32),
        "ff_in": jnp.zeros((8, 8), jnp.float32),
        "bias": jnp.zeros((8,), jnp.float32),
    }


MODEL_SPECS = {
    "attn_out": P("model", None),
    "ff_in": P(None, "model"),
    "bias": P(),
}


def test_optstate_specs_map_by_path_not_shape():
    params = _params_same_shape()
    opt = optax.adam(1e-3)
    state = opt.init(params)
    # param specs as the engine would derive them at stage 1 with TP specs
    pspecs = zero_lib.zero_optstate_specs(
        params, dp_size=2, stage=1, model_specs=MODEL_SPECS
    )
    # the two same-shaped params must carry different specs already
    assert pspecs["attn_out"] != pspecs["ff_in"]
    ospecs = zero_lib.optstate_specs_like(state, pspecs, params)
    flat = jax.tree_util.tree_flatten_with_path(ospecs)[0]
    seen = {}
    for path, spec in flat:
        toks = tuple(getattr(k, "key", getattr(k, "idx", None)) for k in path)
        for name in ("attn_out", "ff_in", "bias"):
            if toks and toks[-1] == name:
                seen.setdefault(name, set()).add(spec)
    # every moment leaf for a param carries exactly that param's spec
    assert seen["attn_out"] == {pspecs["attn_out"]}
    assert seen["ff_in"] == {pspecs["ff_in"]}
    assert seen["bias"] == {pspecs["bias"]}


def test_optstate_scalar_leaves_replicated():
    params = _params_same_shape()
    state = optax.adam(1e-3).init(params)
    pspecs = zero_lib.zero_optstate_specs(params, dp_size=2, stage=1)
    ospecs = zero_lib.optstate_specs_like(state, pspecs, params)
    # adam's count is a scalar — must be replicated
    counts = [
        s
        for path, s in jax.tree_util.tree_flatten_with_path(ospecs)[0]
        if any("count" in str(k) for k in path)
    ]
    assert counts and all(s == P() for s in counts)


def test_optstate_shape_fallback_when_unambiguous():
    # a leaf whose path does not suffix-match any param (e.g. an optimizer
    # with renamed inner trees) still gets the spec when the shape is unique
    params = {"w": jnp.zeros((8, 4), jnp.float32)}
    pspecs = zero_lib.zero_optstate_specs(params, dp_size=2, stage=1)
    odd_state = {"momentum_buf": jnp.zeros((8, 4), jnp.float32)}
    ospecs = zero_lib.optstate_specs_like(odd_state, pspecs, params)
    assert ospecs["momentum_buf"] == pspecs["w"]


def test_optstate_ambiguous_shape_without_path_is_replicated():
    # same shape, different specs, and a path that matches neither param:
    # replication is the only safe answer
    params = _params_same_shape()
    pspecs = zero_lib.zero_optstate_specs(
        params, dp_size=2, stage=1, model_specs=MODEL_SPECS
    )
    odd_state = {"mystery": jnp.zeros((8, 8), jnp.float32)}
    ospecs = zero_lib.optstate_specs_like(odd_state, pspecs, params)
    assert ospecs["mystery"] == P()


@pytest.mark.parametrize("stage", [1, 2])
def test_engine_moments_follow_param_tp_specs(stage):
    """End-to-end: engine-derived moment shardings equal each param's own."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding

    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("data", "model"))
    params = _params_same_shape()
    pspecs = zero_lib.zero_optstate_specs(
        params, dp_size=2, stage=stage, model_specs=MODEL_SPECS
    )
    state = optax.adam(1e-3).init(params)
    ospecs = zero_lib.optstate_specs_like(state, pspecs, params)
    shardings = zero_lib.specs_to_shardings(ospecs, mesh)
    placed = jax.device_put(state, shardings)
    mu = placed[0].mu
    assert mu["attn_out"].sharding == NamedSharding(mesh, pspecs["attn_out"])
    assert mu["ff_in"].sharding == NamedSharding(mesh, pspecs["ff_in"])
    assert (
        mu["attn_out"].sharding.spec != mu["ff_in"].sharding.spec
    ), "same-shaped params must keep distinct moment layouts"
