"""wall_clock_breakdown coverage on BOTH train paths (VERDICT r04 #8).

The reference's always-on per-phase breakdown
(deepspeed/pt/deepspeed_light.py:709-719,886-931) splits fwd/bwd/step with
host timers. The unfused path here does the same; the fused train_batch()
window is one compiled program, so it reports whole-window wall clock +
samples/s in the step line and labels phases inside the jit with
``jax.named_scope`` for profiler traces.
"""

import logging

import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.engine import (
    BACKWARD_TIMER,
    FORWARD_TIMER,
    STEP_TIMER,
    TRAIN_BATCH_TIMER,
)
from deepspeed_tpu.utils.logging import logger
from tests.unit.simple_model import SimpleModel, config_dict, init_model, random_dataset

pytestmark = pytest.mark.slow

INPUT_DIM = 16


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.lines = []

    def emit(self, record):
        self.lines.append(record.getMessage())


@pytest.fixture()
def captured_log():
    h = _Capture()
    logger.addHandler(h)
    yield h.lines
    logger.removeHandler(h)


def _build(steps_per_print=2):
    cfg = config_dict(batch_size=16)
    cfg["wall_clock_breakdown"] = True
    cfg["steps_per_print"] = steps_per_print
    model = SimpleModel(hidden_dim=32)
    params = init_model(model, INPUT_DIM)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg
    )
    return engine


def test_unfused_path_has_phase_timers(captured_log):
    engine = _build()
    x, y = random_dataset(32, INPUT_DIM)
    for b in range(2):
        loss = engine(x[b * 16 : (b + 1) * 16], y[b * 16 : (b + 1) * 16])
        engine.backward(loss)
        engine.step()
    for name in (FORWARD_TIMER, BACKWARD_TIMER, STEP_TIMER):
        assert engine.timers.has_timer(name), name
    assert any("time (ms)" in l for l in captured_log), captured_log


def test_fused_path_reports_window_breakdown(captured_log):
    engine = _build()
    x, y = random_dataset(32, INPUT_DIM)
    for b in range(2):
        engine.train_batch([(x[b * 16 : (b + 1) * 16],
                             y[b * 16 : (b + 1) * 16])])
    assert engine.timers.has_timer(TRAIN_BATCH_TIMER)
    window_lines = [l for l in captured_log if "train_batch window" in l]
    assert window_lines, captured_log
    assert "samples/s" in window_lines[0]


def test_breakdown_off_keeps_async_path():
    cfg = config_dict(batch_size=16)
    model = SimpleModel(hidden_dim=32)
    params = init_model(model, INPUT_DIM)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg
    )
    x, y = random_dataset(16, INPUT_DIM)
    engine.train_batch([(x, y)])
    assert not engine.timers.has_timer(TRAIN_BATCH_TIMER)
