"""Mixture-of-Experts / expert parallelism (ops/moe.py + GPT-2 wiring).

Beyond-reference capability (v0.2.0 has no MoE; SURVEY §2.4 lists expert
parallelism as absent). Pins: top-k gating invariants (capacity, slot
uniqueness, aux loss), the GShard einsum layer's dense-equivalence at one
expert, expert-sharded training through the engine, and the multi-output
surfacing of the router loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.models.gpt2 import partition_specs
from deepspeed_tpu.ops.moe import MoEConfig, MoEMLP, top_k_gating
from deepspeed_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


def test_gating_respects_capacity_and_k():
    logits = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 16, 4)), jnp.float32
    )
    d, c, aux = top_k_gating(logits, k=2, capacity=3)
    # each token dispatched to at most k experts
    assert float(jnp.max(jnp.sum(d, axis=(2, 3)))) <= 2.0
    # per-(group, expert): at most `capacity` tokens
    assert float(jnp.max(jnp.sum(d, axis=(1, 3)))) <= 3.0
    # one token per (group, expert, slot)
    assert float(jnp.max(jnp.sum(d, axis=1))) <= 1.0
    # combine weights live exactly on dispatched slots
    assert float(jnp.max(c * (1.0 - d))) == 0.0
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_gating_uniform_logits_balances():
    """With uniform router logits the aux loss sits at its minimum (~1)."""
    logits = jnp.zeros((1, 64, 8), jnp.float32)
    _, _, aux = top_k_gating(logits, k=1, capacity=64)
    # E * mean_e(1/E * frac_e); ties all dispatch to expert 0, but the
    # gates term is uniform -> aux == E * sum(1/E * frac) == 1
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


def test_single_expert_equals_dense_mlp():
    import flax.linen as nn

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 16, 32)), jnp.float32)
    m = MoEMLP(
        hidden=32, intermediate=64,
        cfg=MoEConfig(n_experts=1, top_k=1, capacity_factor=16.0),
    )
    p = m.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    y, aux = m.apply({"params": p}, x)
    dense = nn.gelu(
        x @ p["expert_in_w"][0] + p["expert_in_b"][0], approximate=True
    )
    dense = dense @ p["expert_out_w"][0] + p["expert_out_b"][0]
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(dense), atol=1e-5
    )


def test_moe_layer_grads_reach_all_params():
    mesh = build_mesh(data_parallel_size=8)
    m = MoEMLP(
        hidden=32, intermediate=64,
        cfg=MoEConfig(n_experts=8, top_k=2, capacity_factor=2.0),
        mesh=mesh,
    )
    x = jnp.asarray(
        np.random.default_rng(2).normal(size=(8, 16, 32)), jnp.float32
    )
    p = m.init({"params": jax.random.PRNGKey(0)}, x)["params"]

    def loss(p, x):
        y, aux = m.apply({"params": p}, x)
        return jnp.mean(y ** 2) + aux

    with mesh:
        g = jax.jit(jax.grad(loss))(p, x)
    for k, v in g.items():
        assert float(jnp.linalg.norm(v)) > 0, f"no gradient reached {k}"


def test_gpt2_moe_trains_with_expert_parallelism():
    mesh = build_mesh(data_parallel_size=8)
    cfg = GPT2Config(
        vocab_size=512, n_positions=64, n_embd=128, n_layer=2, n_head=4,
        dropout=0.0, mesh=mesh, moe_experts=8, moe_capacity_factor=2.0,
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jnp.asarray(
        np.random.default_rng(0).integers(0, 512, (8, 64)), jnp.int32
    )
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, ids0, ids0, train=False
    )["params"]
    specs = partition_specs(params)
    # expert weights must carry the expert (data) axis on their E dim
    assert str(specs["transformer"]["h"]["moe"]["expert_in_w"]) == (
        "PartitionSpec(None, 'data', None, None)"
    )
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=mesh,
        param_specs=specs,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10_000,
        },
        rng_seed=0,
    )
    fixed = [
        jnp.asarray(
            np.random.default_rng(s % 2).integers(0, 512, (8, 64)), jnp.int32
        )
        for s in range(15)
    ]
    losses = []
    for ids in fixed:
        loss = engine(ids, ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < 0.9 * losses[0], losses
    # multi-output contract: (total, lm, aux) -> last_aux = (lm, aux)
    lm, aux = engine.last_aux
    assert np.isfinite(float(jnp.mean(lm)))
    assert float(jnp.mean(aux)) > 0
    # stored expert weights are actually expert-sharded
    w = engine.params["transformer"]["h"]["moe"]["expert_in_w"]
    assert "data" in str(w.sharding.spec), w.sharding.spec


def test_expert_sharding_does_not_change_numerics():
    """Expert parallelism is a layout, not a model change: the same MoE
    GPT-2 with the same init must produce the same loss trajectory on a
    single device and on an 8-way expert-sharded mesh."""

    def train(mesh, specs):
        cfg = GPT2Config(
            vocab_size=512, n_positions=64, n_embd=128, n_layer=2, n_head=4,
            dropout=0.0, mesh=mesh, moe_experts=8, moe_capacity_factor=2.0,
        )
        model = GPT2LMHeadModel(cfg)
        ids0 = jnp.asarray(
            np.random.default_rng(0).integers(0, 512, (8, 64)), jnp.int32
        )
        params = model.init(
            {"params": jax.random.PRNGKey(0)}, ids0, ids0, train=False
        )["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, mesh=mesh,
            param_specs=partition_specs(params) if specs else None,
            config_params={
                "train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10_000,
            },
            rng_seed=0,
        )
        losses = []
        for s in range(10):
            ids = jnp.asarray(
                np.random.default_rng(s % 2).integers(0, 512, (8, 64)),
                jnp.int32,
            )
            loss = engine(ids, ids)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        return np.asarray(losses)

    single = train(build_mesh(devices=jax.devices()[:1]), specs=False)
    sharded = train(build_mesh(data_parallel_size=8), specs=True)
    np.testing.assert_allclose(
        sharded, single, rtol=1e-4,
        err_msg="expert-sharded MoE diverged from the single-device run",
    )


def test_gpt2_moe_rejects_pipeline_combo():
    mesh = build_mesh(data_parallel_size=4, pipeline_parallel_size=2)
    cfg = GPT2Config(
        vocab_size=512, n_positions=64, n_embd=128, n_layer=4, n_head=4,
        mesh=mesh, moe_experts=4, pipeline_stages=2,
    )
    ids = jnp.zeros((8, 64), jnp.int32)
    with pytest.raises(ValueError, match="pp or ep"):
        GPT2LMHeadModel(cfg).init(
            {"params": jax.random.PRNGKey(0)}, ids, ids, train=False
        )
