"""Model-family smoke + integration tests: BERT and GPT-2 training through
the engine (the unit-scale analog of the reference's Megatron-GPT2 /
BingBert functional suites, tests/model/*)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import (
    BertConfig,
    BertForPreTraining,
    GPT2Config,
    GPT2LMHeadModel,
    partition_specs,
)
from jax.sharding import PartitionSpec as P

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


def tiny_gpt2():
    return GPT2Config(
        vocab_size=512, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        dropout=0.0,
    )


def tiny_bert():
    return BertConfig(
        vocab_size=512, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=128, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )


def test_gpt2_forward_loss_shape():
    cfg = tiny_gpt2()
    model = GPT2LMHeadModel(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, (2, 64)))
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids, ids,
    )["params"]
    loss = model.apply({"params": params}, ids, ids, train=False)
    assert loss.shape == ()
    assert float(loss) > 0


def test_gpt2_trains_through_engine():
    cfg = tiny_gpt2()
    model = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    # learnable synthetic data: next token = (token + 1) % 64
    start = rng.integers(0, 64, (256, 1))
    seq = (start + np.arange(64)[None, :]) % 64
    ids = jnp.asarray(seq, jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids[:2], ids[:2],
    )["params"]
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        training_data=(np.asarray(seq), np.asarray(seq)),
        config_params={
            "train_batch_size": 32,
            "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 1000,
        },
    )
    losses = []
    for epoch in range(3):
        for xb, yb in loader:
            loss = engine(xb, yb)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_bert_pretraining_loss_runs():
    cfg = tiny_bert()
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 512, (2, 64)), jnp.int32)
    mask = jnp.ones((2, 64), jnp.int32)
    mlm_labels = jnp.where(
        jnp.asarray(rng.random((2, 64)) < 0.15), ids, -1
    )
    nsp = jnp.asarray([0, 1], jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids, mask, None, mlm_labels, nsp,
    )["params"]
    loss = model.apply(
        {"params": params}, ids, mask, None, mlm_labels, nsp, train=False
    )
    assert float(loss) > 0


def test_bert_trains_through_engine():
    cfg = tiny_bert()
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    n = 64
    ids = rng.integers(0, 64, (n, 32)).astype(np.int32)
    mask = np.ones((n, 32), np.int32)
    mlm = np.where(rng.random((n, 32)) < 0.3, ids, -1).astype(np.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.asarray(ids[:2]), jnp.asarray(mask[:2]), None, jnp.asarray(mlm[:2]),
    )["params"]
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        training_data=(ids, mask, np.zeros_like(ids), mlm),
        config_params={
            "train_batch_size": 16,
            "optimizer": {"type": "Lamb", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "steps_per_print": 1000,
        },
    )
    losses = []
    for epoch in range(4):
        for batch in loader:
            loss = engine(*batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_gpt2_partition_specs_cover_params():
    cfg = tiny_gpt2()
    model = GPT2LMHeadModel(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids, ids,
    )["params"]
    specs = partition_specs(params)
    flat_p = jax.tree_util.tree_leaves(params)
    flat_s = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    assert len(flat_p) == len(flat_s)
    # the big projections must be model-sharded
    sharded = [s for s in flat_s if any(e == "model" for e in s)]
    assert len(sharded) >= 5
