"""Telemetry subsystem: registry semantics, exporter round-trips, the
engine's golden metric catalog, config-armed profiler windows, and the
step-heartbeat watchdog (docs/observability.md)."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.telemetry import (
    ENGINE_METRICS,
    JsonlExporter,
    MetricsRegistry,
    PrometheusTextfileExporter,
    StepHeartbeatWatchdog,
    SummaryWriterExporter,
    Telemetry,
    prometheus_name,
)
from deepspeed_tpu.utils.timers import ThroughputTimer


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------
def test_counter_semantics():
    reg = MetricsRegistry()
    c = reg.counter("train/steps", help="h")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    # get-or-create returns the same instrument
    assert reg.counter("train/steps") is c


def test_gauge_semantics():
    reg = MetricsRegistry()
    g = reg.gauge("mem/bytes")
    g.set(10.0)
    assert g.value == 10.0
    g.set(4.0)  # gauges may decrease
    assert g.value == 4.0
    g.inc(1.5)
    assert g.value == 5.5


def test_histogram_semantics():
    reg = MetricsRegistry()
    h = reg.histogram("t/ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 5.0, 50.0, 5000.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(5060.5)
    # per-bucket (non-cumulative) counts, +Inf last
    assert h.bucket_counts == (1, 2, 1, 1)
    with pytest.raises(ValueError):
        reg.histogram("bad", buckets=(10.0, 1.0))  # not ascending


def test_registry_kind_mismatch_raises():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_registry_snapshot_flattens_histograms():
    reg = MetricsRegistry()
    reg.counter("a").inc(2)
    reg.histogram("h", buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert snap["a"] == 2
    assert snap["h/count"] == 1
    assert snap["h/sum"] == 0.5


# ---------------------------------------------------------------------------
# exporter round-trips
# ---------------------------------------------------------------------------
def _populated_registry():
    reg = MetricsRegistry()
    reg.counter("train/steps", help="steps").inc(4)
    reg.gauge("train/loss", help="loss").set(1.25)
    h = reg.histogram("train/window_time_ms", buckets=(10.0, 100.0))
    h.observe(5.0)
    h.observe(50.0)
    h.observe(5000.0)
    return reg


def test_jsonl_exporter_reparse(tmp_path):
    reg = _populated_registry()
    exp = JsonlExporter(str(tmp_path))
    exp.export(reg.collect(), step=7)
    exp.close()
    # every line must be strict RFC JSON (parse_constant trips on bare
    # NaN/Infinity)
    lines = [
        json.loads(l, parse_constant=lambda s: pytest.fail(f"non-RFC: {s}"))
        for l in open(tmp_path / "metrics.jsonl").read().splitlines()
    ]
    by_tag = {l["tag"]: l for l in lines}
    assert by_tag["train/steps"]["value"] == 4
    assert by_tag["train/loss"]["value"] == 1.25
    assert by_tag["train/loss"]["step"] == 7
    hist = by_tag["train/window_time_ms"]
    assert hist["kind"] == "histogram"
    assert hist["count"] == 3
    assert hist["bucket_counts"] == [1, 1, 1]
    assert hist["thresholds"] == [10.0, 100.0]


def test_prometheus_textfile_format(tmp_path):
    reg = _populated_registry()
    path = str(tmp_path / "metrics.prom")
    exp = PrometheusTextfileExporter(path)
    exp.export(reg.collect(), step=7)
    text = open(path).read()
    assert "# TYPE train_steps counter" in text
    assert "train_steps 4.0" in text
    assert "# TYPE train_loss gauge" in text
    assert "train_loss 1.25" in text
    # histogram: cumulative buckets, +Inf catch-all equals _count
    assert '# TYPE train_window_time_ms histogram' in text
    assert 'train_window_time_ms_bucket{le="10.0"} 1' in text
    assert 'train_window_time_ms_bucket{le="100.0"} 2' in text
    assert 'train_window_time_ms_bucket{le="+Inf"} 3' in text
    assert "train_window_time_ms_count 3" in text
    # atomic write: no temp file left behind
    assert not os.path.exists(path + ".tmp")
    # re-export overwrites (textfile collector contract), never appends
    exp.export(reg.collect(), step=8)
    assert open(path).read().count("# TYPE train_steps counter") == 1


def test_prometheus_name_sanitization():
    assert prometheus_name("train/loss") == "train_loss"
    assert prometheus_name("9lives") == "_9lives"
    assert prometheus_name("a.b-c/d") == "a_b_c_d"


def test_summary_writer_exporter_fallback(tmp_path, monkeypatch):
    """Without torch, the tensorboard exporter writes the JSONL fallback —
    the pre-telemetry writer refitted as a registry exporter."""
    import builtins

    real_import = builtins.__import__

    def no_torch(name, *args, **kwargs):
        if name.startswith("torch"):
            raise ImportError(name)
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", no_torch)
    reg = _populated_registry()
    exp = SummaryWriterExporter(log_dir=str(tmp_path), job_name="job")
    exp.export(reg.collect(), step=2)
    exp.close()
    lines = [
        json.loads(l)
        for l in open(tmp_path / "job" / "events.jsonl").read().splitlines()
    ]
    tags = {l["tag"] for l in lines}
    # histograms surface as count/sum scalar streams
    assert {"train/steps", "train/loss", "train/window_time_ms/count",
            "train/window_time_ms/sum"} <= tags


# ---------------------------------------------------------------------------
# throughput-timer warmup fix (satellite)
# ---------------------------------------------------------------------------
def test_tput_timer_no_inf_before_warmup():
    lines = []
    t = ThroughputTimer(
        batch_size=4, num_workers=1, start_step=2, steps_per_output=1,
        monitor_memory=False, logging_fn=lines.append,
        fence_fn=lambda: None,
    )
    assert t.avg_samples_per_sec() == 0.0  # was float("-inf")
    # two warmup steps: no rate line may be emitted (and never a -inf one)
    for _ in range(2):
        t.start()
        t.stop()
    assert not any("SamplesPerSec" in l for l in lines)
    assert all("inf" not in l for l in lines)
    # past warmup the real rate appears
    for _ in range(3):
        t.start()
        t.stop()
    assert any("SamplesPerSec" in l for l in lines)
    assert t.avg_samples_per_sec() > 0


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------
def test_watchdog_stall_detection_fake_clock():
    now = [0.0]
    reports = []
    wd = StepHeartbeatWatchdog(
        timeout=30.0,
        poll_interval=1.0,
        clock=lambda: now[0],
        context_fn=lambda: {"device_memory": "fake", "last": 42},
        report_fn=lambda waited, step, ctx: reports.append((waited, step, ctx)),
    )
    # unarmed: a long quiet period before the first window is NOT a stall
    now[0] = 1000.0
    assert not wd.check()
    wd.beat(step=3)
    now[0] += 29.0
    assert not wd.check()  # inside the timeout
    now[0] += 2.0
    assert wd.check()  # 31s since beat -> stall fires
    assert not wd.check()  # one report per stall, not one per poll
    waited, step, ctx = reports[0]
    assert waited == pytest.approx(31.0)
    assert step == 3
    assert ctx["device_memory"] == "fake"
    # a recovery beat re-arms detection
    wd.beat(step=4)
    assert not wd.check()
    now[0] += 31.0
    assert wd.check()
    assert wd.stall_count == 2


def test_watchdog_default_report_is_rank_tagged():
    import logging as _logging

    from deepspeed_tpu.utils.logging import logger as ds_logger

    records = []

    class Capture(_logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = Capture(level=_logging.ERROR)
    ds_logger.addHandler(handler)  # the shared logger has propagate=False
    try:
        now = [0.0]
        wd = StepHeartbeatWatchdog(
            timeout=5.0, poll_interval=1.0, clock=lambda: now[0],
            context_fn=lambda: {"metrics": {"train/loss": 1.0}},
        )
        wd.beat(step=1)
        now[0] = 10.0
        assert wd.check()
    finally:
        ds_logger.removeHandler(handler)
    assert any(
        "STEP HEARTBEAT STALL" in r.getMessage()
        and "[Rank 0]" in r.getMessage()
        and r.levelno == _logging.ERROR
        for r in records
    )


def test_watchdog_liveness_beat_never_arms():
    """A step=None beat (eval forward) before the first training window
    must NOT arm the watchdog: a job that runs a baseline eval first is
    still owed the first-window compilation grace."""
    now = [0.0]
    wd = StepHeartbeatWatchdog(
        timeout=30.0, poll_interval=1.0, clock=lambda: now[0],
        report_fn=lambda *a: None,
    )
    wd.beat()  # eval-phase liveness before any training window
    now[0] += 1000.0  # first window compiles for far longer than timeout
    assert not wd.check()  # still unarmed: no false stall mid-compile
    wd.beat(step=1)  # first completed window arms it
    now[0] += 31.0
    assert wd.check()


def test_watchdog_pause_resume():
    """pause() suspends detection for phases with no step cadence (a
    checkpoint save can outlast the timeout); resume() restarts the stall
    clock so the paused phase never counts against it."""
    now = [0.0]
    reports = []
    wd = StepHeartbeatWatchdog(
        timeout=30.0, poll_interval=1.0, clock=lambda: now[0],
        report_fn=lambda waited, step, ctx: reports.append(step),
    )
    wd.beat(step=1)
    wd.pause()
    now[0] += 1000.0  # a save far longer than the timeout
    assert not wd.check()  # paused: no stall mid-save
    wd.resume()
    assert not wd.check()  # clock restarted at resume, not still at beat
    now[0] += 29.0
    assert not wd.check()
    now[0] += 2.0
    assert wd.check()  # detection is live again after resume
    assert reports == [1]
    # nesting: detection stays off until the outermost resume
    wd.beat(step=2)
    wd.pause()
    wd.pause()
    wd.resume()
    now[0] += 100.0
    assert not wd.check()
    wd.resume()
    now[0] += 31.0
    assert wd.check()


def test_telemetry_liveness_exempt_and_window_duration():
    """Telemetry.liveness_exempt pauses the watchdog for the block, and
    train/window_time_ms measures start->end duration, not the gap
    between successive window ends."""
    now = [0.0]
    wd = StepHeartbeatWatchdog(
        timeout=30.0, poll_interval=1.0, clock=lambda: now[0],
        report_fn=lambda *a: None,
    )
    t = Telemetry(enabled=True, watchdog=wd)
    wd.stop()  # drive the fake clock by hand, not from the poll thread
    t.on_window_end(global_steps=1)
    with t.liveness_exempt():
        now[0] += 1000.0
        assert not wd.check()
    assert not wd.check()  # clock restarted on exit
    # duration histogram: only windows bracketed by on_window_start count
    hist = t.registry.histogram("train/window_time_ms")
    assert hist.count == 0  # no on_window_start -> no bogus gap sample
    t.on_window_start()
    t.on_window_end(global_steps=2)
    assert hist.count == 1
    t.close()


def test_watchdog_thread_start_stop():
    wd = StepHeartbeatWatchdog(timeout=60.0, poll_interval=0.05)
    wd.start()
    assert wd._thread.is_alive()
    wd.start()  # idempotent
    wd.stop()
    assert wd._thread is None


def test_watchdog_rejects_bad_timeout():
    with pytest.raises(ValueError):
        StepHeartbeatWatchdog(timeout=0)
    # Event.wait(<=0) returns immediately -> the poll thread would
    # busy-spin a core; must be rejected up front
    with pytest.raises(ValueError):
        StepHeartbeatWatchdog(timeout=60.0, poll_interval=-1)


def test_watchdog_heartbeat_without_step():
    """A step=None beat (eval forward, checkpoint save) defers the stall
    but keeps the last-completed-window index in the report."""
    now = [0.0]
    reports = []
    wd = StepHeartbeatWatchdog(
        timeout=30.0, poll_interval=1.0, clock=lambda: now[0],
        report_fn=lambda waited, step, ctx: reports.append(step),
    )
    wd.beat(step=7)
    now[0] += 25.0
    wd.beat()  # liveness-only: eval phase in progress
    now[0] += 25.0
    assert not wd.check()  # 25s since last beat — no stall
    now[0] += 6.0
    assert wd.check()
    assert reports == [7]  # window index survived the None beats


def test_flush_exports_trailing_windows():
    """With interval > 1, windows past the last export boundary must be
    settled and exported by flush()/close(), not silently dropped."""
    class Capture:
        def __init__(self):
            self.steps = []

        def export(self, metrics, step):
            self.steps.append(step)

        def flush(self):
            pass

        def close(self):
            pass

    sink = Capture()
    t = Telemetry(enabled=True, interval=3, exporters=[sink])
    for step in range(1, 5):  # 4 windows: boundary at 3, one trailing
        t.on_window_start()
        t.on_window_end(loss=2.5, global_steps=step)
    assert sink.steps == [3]
    t.flush()
    assert sink.steps == [3, 4]  # trailing window settled at flush
    assert t.registry.snapshot()["train/loss"] == 2.5
    t.flush()
    assert sink.steps == [3, 4]  # nothing pending: no duplicate export
    t.close()


def test_batch_tokens_dtype_rule():
    """rows x dim-1 counts tokens only for 2-d integer leaves (LM ids);
    float features and images count tokens == samples."""
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    ids = np.zeros((8, 128), np.int32)
    assert DeepSpeedEngine._batch_tokens((ids,)) == (8 * 128, 8)
    feats = np.zeros((8, 512), np.float32)
    assert DeepSpeedEngine._batch_tokens((feats,)) == (8, 8)
    images = np.zeros((8, 32, 32, 3), np.float32)
    assert DeepSpeedEngine._batch_tokens((images,)) == (8, 8)
    assert DeepSpeedEngine._batch_tokens(()) == (0, 0)


def test_multiprocess_prometheus_path_keeps_prom_extension(
    tmp_path, monkeypatch
):
    """Rank suffix goes BEFORE '.prom': textfile collectors glob '*.prom',
    so 'metrics.prom.rank1' would never be scraped."""
    from deepspeed_tpu.telemetry import build_telemetry

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    cfg = _cfg({
        "enabled": True,
        "output_path": str(tmp_path),
        "exporters": ["prometheus"],
        "watchdog": {"enabled": False},
    })
    t = build_telemetry(cfg, rank=1)
    try:
        assert t.exporters[0].path.endswith(".rank1.prom")
    finally:
        t.close()


# ---------------------------------------------------------------------------
# config block validation
# ---------------------------------------------------------------------------
def _cfg(telemetry):
    return DeepSpeedConfig(
        None,
        param_dict={"train_batch_size": 8, "telemetry": telemetry},
        world_size=1,
    )


def test_config_defaults():
    cfg = _cfg({"enabled": True})
    assert cfg.telemetry_enabled
    assert cfg.telemetry_interval == 1
    assert cfg.telemetry_exporters == ["jsonl", "prometheus"]
    assert cfg.telemetry_profile_start_step == -1  # profiling off
    assert cfg.telemetry_watchdog_enabled
    assert cfg.telemetry_watchdog_timeout == 600.0
    # absent block: fully off, watchdog included
    off = DeepSpeedConfig(None, param_dict={"train_batch_size": 8}, world_size=1)
    assert not off.telemetry_enabled
    assert not off.telemetry_watchdog_enabled


def test_config_rejects_unknown_exporter():
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "exporters": ["jsonl", "statsd"]})


def test_config_rejects_non_list_exporters():
    # a bare string must not be list()ed into characters
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "exporters": "jsonl"})
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "exporters": 5})


def test_config_rejects_non_numeric_fields():
    # strings must raise a config error naming the field, not a raw
    # TypeError from a range comparison
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "profile": {"start_step": "20"}})
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True,
              "profile": {"start_step": 2, "num_steps": "2"}})
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "watchdog": {"timeout": "600"}})
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "watchdog": {"poll_interval": "5"}})


def test_config_rejects_bad_interval():
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "interval": 0})
    # bool passes isinstance(..., int): a user treating interval as a
    # flag must get the config error, not silent every-window export
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "interval": True})


def test_config_rejects_bad_profile_window():
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "profile": {"start_step": 2, "num_steps": 0}})


def test_config_rejects_bad_watchdog_timeout():
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "watchdog": {"timeout": 0}})


def test_config_rejects_bad_watchdog_poll_interval():
    with pytest.raises(DeepSpeedConfigError):
        _cfg({"enabled": True, "watchdog": {"poll_interval": -1}})


# ---------------------------------------------------------------------------
# engine integration: golden catalog, exporters, config-armed profiler
# ---------------------------------------------------------------------------
GOLDEN_SCALAR_NAMES = sorted(name for _, name, _ in ENGINE_METRICS)


def _small_engine(tmp_path, telemetry_extra=None, steps=3):
    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            pred = nn.Dense(1)(x)
            return jnp.mean((pred[:, 0] - y) ** 2)

    m = M()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    y = rng.standard_normal((8,)).astype(np.float32)
    params = m.init(jax.random.PRNGKey(0), x[:2], y[:2])["params"]
    telemetry = {
        "enabled": True,
        "output_path": str(tmp_path),
        "job_name": "job",
        "watchdog": {"timeout": 300.0},
    }
    telemetry.update(telemetry_extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m, model_parameters=params,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "steps_per_print": 1000,
            "telemetry": telemetry,
        },
    )
    for _ in range(steps):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.flush_monitor()
    return engine, (x, y)


def test_engine_golden_scalar_names(tmp_path):
    """Pins the engine's emitted metric catalog: a new stream must be added
    to ENGINE_METRICS (and docs/observability.md); a dropped one is a
    regression this test catches."""
    engine, _ = _small_engine(tmp_path)
    engine.telemetry.close()
    lines = [
        json.loads(l)
        for l in open(tmp_path / "job" / "metrics.jsonl").read().splitlines()
    ]
    assert sorted({l["tag"] for l in lines}) == GOLDEN_SCALAR_NAMES


def test_engine_exports_new_streams_to_both_sinks(tmp_path):
    """Acceptance smoke: grad-norm, skip counters, memory gauges and
    tokens/sec appear in BOTH the JSONL and the Prometheus textfile sinks
    with plausible values."""
    engine, _ = _small_engine(tmp_path, steps=4)
    engine.telemetry.close()
    job = tmp_path / "job"
    lines = [json.loads(l) for l in open(job / "metrics.jsonl").read().splitlines()]
    last = {}
    for l in lines:
        last[l["tag"]] = l
    assert last["train/grad_norm"]["value"] > 0
    assert last["train/global_steps"]["value"] == 4
    assert last["train/skipped_steps"]["value"] == 0
    assert last["train/micro_steps"]["value"] == 4
    assert last["train/loss"]["value"] > 0
    assert last["train/tokens_per_sec"]["value"] > 0
    assert last["jax/recompiles"]["value"] > 0
    prom = open(job / "metrics.prom").read()
    for stream in (
        "train_grad_norm", "train_skipped_steps", "device_bytes_in_use",
        "train_tokens_per_sec", "train_window_time_ms_bucket",
    ):
        assert stream in prom, f"{stream} missing from textfile"


def test_engine_config_armed_profiler_window(tmp_path):
    """A profile sub-block produces a trace for the configured window with
    no manual start_profile()/stop_profile() call."""
    engine, _ = _small_engine(
        tmp_path,
        telemetry_extra={"profile": {"start_step": 1, "num_steps": 2}},
        steps=4,
    )
    engine.telemetry.close()
    trace_dir = str(tmp_path / "job" / "profile")
    artifacts = glob.glob(trace_dir + "/**/*.pb", recursive=True) + glob.glob(
        trace_dir + "/**/*.json.gz", recursive=True
    )
    assert artifacts, os.listdir(trace_dir)
    # the window closed itself: no trace is still running
    assert not engine.telemetry.profiler.tracing


def test_engine_fused_train_batch_feeds_telemetry(tmp_path):
    """train_batch() (the fused window) goes through the same hooks."""
    engine, (x, y) = _small_engine(tmp_path, steps=1)
    for _ in range(2):
        engine.train_batch(iter([(x, y)]))
    engine.flush_monitor()
    engine.telemetry.close()
    lines = [
        json.loads(l)
        for l in open(tmp_path / "job" / "metrics.jsonl").read().splitlines()
    ]
    last = {}
    for l in lines:
        last[l["tag"]] = l
    assert last["train/global_steps"]["value"] == 3
    assert last["train/loss"]["value"] > 0


def test_engine_training_forward_beats_watchdog(tmp_path):
    """Micro-step progress is liveness: a deep accumulation window (or one
    slow-host micro-step) can legitimately outlast the watchdog timeout,
    so every training forward must defer the stall — not only
    on_window_end."""
    engine, (x, y) = _small_engine(tmp_path, steps=1)
    beats = []
    wd = engine.telemetry.watchdog
    orig = wd.beat
    wd.beat = lambda step=None: (beats.append(step), orig(step=step))
    loss = engine(x, y)  # forward only: window still open
    assert None in beats  # liveness-only beat — window index untouched
    engine.backward(loss)
    engine.step()
    engine.telemetry.close()


def test_engine_step_mirrors_export_as_gauges(tmp_path):
    """global/skipped/micro step mirrors are downward-revisable (deferred
    overflow reconciliation, in-process load_checkpoint), so the textfile
    must declare them TYPE gauge — a decreasing counter reads as a reset
    and blows up rate() on scrapers."""
    engine, _ = _small_engine(tmp_path, steps=2)
    engine.telemetry.close()
    prom = open(tmp_path / "job" / "metrics.prom").read()
    for name in ("train_global_steps", "train_skipped_steps",
                 "train_micro_steps"):
        assert f"# TYPE {name} gauge" in prom
    assert "# TYPE jax_recompiles counter" in prom


def test_dataloader_queue_depth_gauge():
    class StubTelemetry:
        def __init__(self):
            self.depths = []

        def set_dataloader_depth(self, depth):
            self.depths.append(depth)

    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    data = (np.arange(64, dtype=np.float32).reshape(16, 4),)
    stub = StubTelemetry()
    loader = DeepSpeedDataLoader(
        data, batch_size=4, mesh=None, prefetch=2, telemetry=stub
    )
    batches = list(loader)
    assert len(batches) == 4
    # one reading per handoff PLUS producer-side enqueue samples (the
    # epoch-boundary-refill fix: without the producer samples the gauge
    # sticks at the previous epoch's drained 0 while the queue refills)
    assert len(stub.depths) >= 4
    # producer samples report qsize+1 for the batch about to enqueue
    assert all(0 <= d <= 3 for d in stub.depths)


def test_telemetry_disabled_is_inert(tmp_path):
    """Without the config block every hook is a no-op: no files, no
    watchdog thread, no registry churn on the hot path."""
    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, y, train=True):
            return jnp.mean((nn.Dense(1)(x)[:, 0] - y) ** 2)

    m = M()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    y = rng.standard_normal((8,)).astype(np.float32)
    params = m.init(jax.random.PRNGKey(0), x[:2], y[:2])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m, model_parameters=params,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "steps_per_print": 1000,
        },
    )
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    assert not engine.telemetry.enabled
    assert engine.telemetry.watchdog is None
    assert engine.telemetry.exporters == []


# ---------------------------------------------------------------------------
# exporter degradation under fault (docs/observability.md "fleet-wide
# view"): the scrape pipe must bend, not break
# ---------------------------------------------------------------------------
def test_prometheus_textfile_unwritable_path_degrades(tmp_path):
    """An export target that becomes unwritable mid-run warns once and
    keeps the process alive — a full disk must not take down training."""
    path = tmp_path / "metrics.prom"
    reg = MetricsRegistry()
    reg.counter("a/b", help="h").inc()
    exp = PrometheusTextfileExporter(str(path))
    exp.export(reg.collect(), step=0)
    assert "a_b 1.0" in path.read_text()
    # the target turns into a directory: os.replace now raises OSError
    path.unlink()
    path.mkdir()
    exp.export(reg.collect(), step=1)  # warn_once path, no raise
    exp.export(reg.collect(), step=2)  # repeat failure stays silent
    assert path.is_dir()  # nothing clobbered the directory


def test_histogram_quantile_degenerate_sample_counts():
    """0 samples -> 0.0 (not NaN); 1 sample interpolates inside its own
    bucket; +Inf-only clamps to the last finite edge."""
    from deepspeed_tpu.telemetry.registry import histogram_quantile

    reg = MetricsRegistry()
    h = reg.histogram("t/ms", buckets=(1.0, 10.0, 100.0))
    assert histogram_quantile(h, 0.5) == 0.0
    assert histogram_quantile(h, 0.99) == 0.0
    h.observe(5.0)
    q = histogram_quantile(h, 0.99)
    assert 1.0 <= q <= 10.0
    h_inf = reg.histogram("t_inf/ms", buckets=(1.0, 10.0, 100.0))
    h_inf.observe(1e9)  # lands in the +Inf bucket
    assert histogram_quantile(h_inf, 0.99) == 100.0


def test_snapshot_concurrent_with_remove_prefix():
    """A scrape (snapshot / wire_snapshot) racing a replica retirement
    (remove_prefix) must never throw — the hub scrapes on its own
    thread while the autoscaler retires gauges on another."""
    import threading
    import time

    from deepspeed_tpu.telemetry.registry import wire_snapshot

    reg = MetricsRegistry()
    reg.counter("fleet/requests_completed").inc()
    h = reg.histogram("fleet/ttft_ms", buckets=(1.0, 10.0))
    h.observe(2.0)
    stop = threading.Event()
    failures = []

    def retire_loop():
        i = 0
        try:
            while not stop.is_set():
                for j in range(8):
                    reg.gauge(f"fleet/replica{i}/g{j}").set(1.0)
                reg.remove_prefix(f"fleet/replica{i}/")
                i += 1
        except Exception as e:  # pragma: no cover - the failure signal
            failures.append(e)

    t = threading.Thread(target=retire_loop)
    t.start()
    try:
        deadline = time.monotonic() + 0.5
        while time.monotonic() < deadline:
            snap = reg.snapshot()
            # the stable series survive every interleaving
            assert snap["fleet/requests_completed"] == 1.0
            assert snap["fleet/ttft_ms/count"] == 1
            entries = wire_snapshot(reg)
            assert any(e["name"] == "fleet/ttft_ms" for e in entries)
    finally:
        stop.set()
        t.join(5.0)
    assert not failures, failures


def test_render_prometheus_name_collision_keeps_first():
    """prometheus_name() is lossy: two distinct registry names mapping
    to one prom name must not interleave into a corrupt series — the
    first claims the name, the rest drop into the suppressed-error
    counter instead of silently merging."""
    from deepspeed_tpu.telemetry import render_prometheus
    from deepspeed_tpu.telemetry.registry import diagnostics_registry

    before = (
        diagnostics_registry()
        .counter("internal/suppressed_errors/telemetry.prom_name_collision")
        .value
    )
    entries = [
        {"name": "a/b", "kind": "counter", "help": "", "value": 1.0},
        {"name": "a.b", "kind": "counter", "help": "", "value": 2.0},
        {"name": "a/b", "kind": "counter", "help": "", "value": 3.0,
         "labels": {"node": "n0"}},
    ]
    text = render_prometheus(entries)
    lines = [ln for ln in text.splitlines() if ln.startswith("a_b")]
    # the claimed name keeps exporting (unlabeled + labeled sample);
    # the colliding distinct name is gone
    assert lines == ["a_b 1.0", 'a_b{node="n0"} 3.0'], lines
    after = (
        diagnostics_registry()
        .counter("internal/suppressed_errors/telemetry.prom_name_collision")
        .value
    )
    assert after == before + 1
