"""Pipeline parallelism (parallel/pipeline.py + GPT-2 integration).

Beyond-reference capability (the reference v0.2.0 has no pipeline engine,
SURVEY §2.4): an SPMD GPipe schedule over the mesh's ``pipe`` axis —
shard_map manual over pipe only, ppermute stage hops, autodiff'd backward.
These tests pin (a) the generic schedule against a sequential oracle,
(b) GPT-2 pipelined-vs-scanned exact parity (same param tree!), and
(c) end-to-end engine training with ZeRO-2 on a pipe x data mesh.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.models.gpt2 import partition_specs
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.parallel.pipeline import gpipe_spmd

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


def _toy_setup(n_stages=2, layers_per_stage=3, n_micro=4, mb=2, s=8, h=16):
    rng = np.random.default_rng(0)
    L = n_stages * layers_per_stage
    W = jnp.asarray(rng.normal(size=(L, h, h)) * 0.2, jnp.float32)
    X = jnp.asarray(rng.normal(size=(n_micro, mb, s, h)), jnp.float32)
    return W, X


def _toy_stage_fn(layers_per_stage):
    def stage_fn(local_w, x, t, extras):
        def one(x, w):
            return jnp.tanh(x @ w), None

        y, _ = jax.lax.scan(one, x, local_w)
        return y

    return stage_fn


def _toy_sequential(W, X):
    def one(x, w):
        return jnp.tanh(x @ w), None

    y, _ = jax.lax.scan(one, X.reshape(-1, *X.shape[2:]), W)
    return y.reshape(X.shape)


def test_gpipe_matches_sequential_fwd_and_grad():
    mesh = build_mesh(data_parallel_size=4, pipeline_parallel_size=2)
    W, X = _toy_setup()
    Wp = W.reshape(2, 3, *W.shape[1:])
    stage_fn = _toy_stage_fn(3)

    out = jax.jit(
        lambda w, x: gpipe_spmd(stage_fn, w, x, mesh)
    )(Wp, X)
    ref = _toy_sequential(W, X)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def loss_pipe(w):
        return jnp.sum(gpipe_spmd(stage_fn, w, X, mesh) ** 2)

    def loss_ref(w):
        return jnp.sum(_toy_sequential(w.reshape(-1, *w.shape[2:]), X) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(Wp)
    g_ref = jax.grad(loss_ref)(Wp)
    np.testing.assert_allclose(
        np.asarray(g_pipe), np.asarray(g_ref), atol=1e-5
    )


def test_gpipe_bubble_ticks_cannot_poison_gradients():
    """Robustness smoke test: an amplifying (exp-based) stage map must
    give finite outputs AND grads through fill/drain. Note what this does
    and does not pin: the bubble-input zeroing in pipeline.py makes bubble
    compute input-independent (every bubble tick evaluates stage_fn at
    zeros, never at stale data-dependent activations), but because valid
    outputs are unaffected by design, no output-level test can detect its
    removal — the value-parity tests above pin the valid path, and this
    test guards the finite-gradient property the masking exists to
    protect."""
    mesh = build_mesh(data_parallel_size=4, pipeline_parallel_size=2)
    W, X = _toy_setup()
    Wp = W.reshape(2, 3, *W.shape[1:])

    def stage_fn(local_w, x, t, extras):
        def one(x, w):
            # exp amplifies any unbounded junk to inf within a few hops;
            # on VALID (bounded) inputs it stays finite
            return jnp.exp(jnp.clip(x @ w, -50.0, 50.0)) * 1e-2, None

        y, _ = jax.lax.scan(one, x, local_w)
        return y

    def loss(w):
        return jnp.sum(gpipe_spmd(stage_fn, w, X, mesh) ** 2)

    val = jax.jit(loss)(Wp)
    g = jax.jit(jax.grad(loss))(Wp)
    assert np.isfinite(float(val))
    assert all(np.all(np.isfinite(np.asarray(l))) for l in jax.tree_util.tree_leaves(g))


def test_gpipe_last_stage_fn_keeps_activations_local():
    """last_stage_fn: per-microbatch scalars computed ON the final stage
    must equal the reference head-outside-pipeline computation — only [M]
    floats cross the pipe axis instead of [M, mb, s, h] activations."""
    mesh = build_mesh(data_parallel_size=4, pipeline_parallel_size=2)
    W, X = _toy_setup()
    Wp = W.reshape(2, 3, *W.shape[1:])
    stage_fn = _toy_stage_fn(3)

    def head(y, mb_idx, extras):
        return jnp.mean(y * y) + 0.5 * mb_idx.astype(jnp.float32)

    losses = jax.jit(
        lambda w, x: gpipe_spmd(
            stage_fn, w, x, mesh, last_stage_fn=head
        )
    )(Wp, X)
    ref_out = _toy_sequential(W, X)
    ref = jnp.asarray(
        [jnp.mean(ref_out[i] ** 2) + 0.5 * i for i in range(X.shape[0])]
    )
    assert losses.shape == (X.shape[0],)
    np.testing.assert_allclose(np.asarray(losses), np.asarray(ref), atol=1e-6)

    # and it differentiates (the training path)
    def loss(w):
        return jnp.sum(
            gpipe_spmd(stage_fn, w, X, mesh, last_stage_fn=head)
        )

    g = jax.jit(jax.grad(loss))(Wp)

    def loss_ref(w):
        out = _toy_sequential(w.reshape(-1, *w.shape[2:]), X)
        return jnp.sum(
            jnp.asarray([jnp.mean(out[i] ** 2) for i in range(X.shape[0])])
        )

    g_ref = jax.grad(loss_ref)(Wp)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-5)


def test_gpipe_single_stage_degenerates_to_scan():
    mesh = build_mesh(data_parallel_size=8)
    W, X = _toy_setup(n_stages=1, layers_per_stage=4)
    out = gpipe_spmd(_toy_stage_fn(4), W[None], X, mesh)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_toy_sequential(W, X)), atol=1e-6
    )


# ---------------------------------------------------------------------------
# GPT-2 integration
# ---------------------------------------------------------------------------
BASE = dict(
    vocab_size=512, n_positions=64, n_embd=128, n_layer=4, n_head=4,
    dropout=0.0,
)


def _ids(batch=8, seq=64, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 512, (batch, seq)), jnp.int32
    )


def test_gpt2_pipeline_matches_scanned_stack():
    """Pipelined and scanned stacks share one param tree and one output."""
    mesh = build_mesh(data_parallel_size=4, pipeline_parallel_size=2)
    cfg_pp = GPT2Config(
        **BASE, mesh=mesh, pipeline_stages=2, pipeline_microbatches=4
    )
    m_pp = GPT2LMHeadModel(cfg_pp)
    m_seq = GPT2LMHeadModel(GPT2Config(**BASE))
    ids = _ids()
    params = m_pp.init(
        {"params": jax.random.PRNGKey(0)}, ids, ids, train=False
    )["params"]
    p_seq = m_seq.init(
        {"params": jax.random.PRNGKey(0)}, ids, ids, train=False
    )["params"]
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(
        p_seq
    ), "pipelined param tree must interchange with the scanned stack"

    loss_seq = m_seq.apply({"params": params}, ids, ids, train=False)
    loss_pp = jax.jit(
        lambda p, i: m_pp.apply({"params": p}, i, i, train=False)
    )(params, ids)
    np.testing.assert_allclose(
        float(loss_pp), float(loss_seq), rtol=1e-5
    )

    g_seq = jax.grad(
        lambda p: m_seq.apply({"params": p}, ids, ids, train=False)
    )(params)
    g_pp = jax.jit(
        jax.grad(lambda p: m_pp.apply({"params": p}, ids, ids, train=False))
    )(params)
    err = max(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(
                lambda a, b: float(jnp.max(jnp.abs(a - b))), g_seq, g_pp
            )
        )
    )
    assert err < 1e-5, f"pipeline grads diverge from scanned stack: {err}"


def test_gpt2_pipeline_dropout_runs_and_is_deterministic():
    mesh = build_mesh(data_parallel_size=4, pipeline_parallel_size=2)
    cfg = GPT2Config(
        **{**BASE, "dropout": 0.1}, mesh=mesh, pipeline_stages=2,
        pipeline_microbatches=4,
    )
    m = GPT2LMHeadModel(cfg)
    ids = _ids()
    params = m.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids, ids,
    )["params"]
    f = jax.jit(
        lambda p, i, k: m.apply(
            {"params": p}, i, i, train=True, rngs={"dropout": k}
        )
    )
    l1 = f(params, ids, jax.random.PRNGKey(7))
    l2 = f(params, ids, jax.random.PRNGKey(7))
    l3 = f(params, ids, jax.random.PRNGKey(8))
    assert float(l1) == float(l2), "same dropout key must reproduce the loss"
    assert float(l1) != float(l3), "different dropout keys must differ"
    assert np.isfinite(float(l1))


def test_gpt2_pipeline_engine_zero2_trains():
    """Full engine step on a pipe=2 x data=4 mesh with ZeRO-2: the pipeline
    composes with grad/opt-state sharding and the loss goes down."""
    mesh = build_mesh(data_parallel_size=4, pipeline_parallel_size=2)
    cfg = GPT2Config(
        **BASE, mesh=mesh, pipeline_stages=2, pipeline_microbatches=4
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = _ids()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, ids0, ids0, train=False
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        mesh=mesh,
        param_specs=partition_specs(params, pipeline=True),
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10_000,
        },
        rng_seed=0,
    )
    fixed = [_ids(seed=s % 2) for s in range(12)]
    losses = []
    for ids in fixed:
        loss = engine(ids, ids)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert engine.global_steps == 12
    assert losses[-1] < 0.9 * losses[0], losses

    # stage weights must actually be stored pipe-sharded: the stacked qkv
    # kernel's leading (layers) dim splits over the pipe axis
    qkv = engine.params["transformer"]["h"]["attn_qkvw"]
    spec = qkv.sharding.spec
    assert spec and spec[0] == "pipe", spec


def test_gpt2_pipeline_validation_errors():
    mesh = build_mesh(data_parallel_size=4, pipeline_parallel_size=2)
    ids = _ids()
    # n_layer not divisible by stages
    bad = GPT2Config(
        **{**BASE, "n_layer": 3}, mesh=mesh, pipeline_stages=2
    )
    with pytest.raises(ValueError, match="divide"):
        GPT2LMHeadModel(bad).init(
            {"params": jax.random.PRNGKey(0)}, ids, ids, train=False
        )
    # mesh pipe axis size mismatch
    mesh1 = build_mesh(data_parallel_size=8)
    bad2 = GPT2Config(**BASE, mesh=mesh1, pipeline_stages=2)
    with pytest.raises(ValueError, match="pipe"):
        GPT2LMHeadModel(bad2).init(
            {"params": jax.random.PRNGKey(0)}, ids, ids, train=False
        )
    # batch not divisible by microbatches
    bad3 = GPT2Config(
        **BASE, mesh=mesh, pipeline_stages=2, pipeline_microbatches=3
    )
    with pytest.raises(ValueError, match="microbatch"):
        GPT2LMHeadModel(bad3).init(
            {"params": jax.random.PRNGKey(0)}, ids, ids, train=False
        )
    # pp x sp would silently replicate attention across sequence ranks
    mesh_sp = build_mesh(
        data_parallel_size=2, sequence_parallel_size=2,
        pipeline_parallel_size=2,
    )
    bad4 = GPT2Config(
        **BASE, mesh=mesh_sp, pipeline_stages=2, pipeline_microbatches=4
    )
    with pytest.raises(ValueError, match="sequence"):
        GPT2LMHeadModel(bad4).init(
            {"params": jax.random.PRNGKey(0)}, ids, ids, train=False
        )
