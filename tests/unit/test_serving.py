"""Fleet serving tier tests (deepspeed_tpu/serving/, docs/serving.md):
placement determinism, prefix affinity, token-bucket admission, drain
steering, rolling-restart exactly-once + bitwise parity, failed-replica
eviction/re-route, and the worker RPC protocol."""

import queue
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference import RequestRejected
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec
from deepspeed_tpu.serving import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    FleetOverloaded,
    FleetRouter,
    LeastLoaded,
    PrefixAffinity,
    RateLimited,
    ReplicaRPCError,
    RoundRobin,
    SubprocessReplica,
    TokenBucket,
)
from deepspeed_tpu.serving.replica import ReplicaBase
from deepspeed_tpu.serving.router import _histogram_quantile
from deepspeed_tpu.serving.worker import WorkerServer

VOCAB = 97


# ---------------------------------------------------------------------------
# stub replicas: the router's contract without engines (fast paths)
# ---------------------------------------------------------------------------
_IDLE_SNAP = {
    "queue_depth": 0, "queue_capacity": 8, "active_slots": 0,
    "free_slots": 2, "num_slots": 2, "health": 0,
    "mean_prefill_ms": 1.0, "mean_decode_ms": 1.0, "requests_shed": 0.0,
    "restarts_used": 0, "requests_completed": 0, "tokens_generated": 0,
    "driving": True, "stopped": False,
    "driver_failed": False, "alive": True, "failed": False,
}


class StubHandle:
    def __init__(self, prompt_tokens):
        self.prompt_tokens = list(prompt_tokens)
        self.tokens = []
        self.finish_reason = None
        self.first_token_at = None
        self._done = threading.Event()

    @property
    def done(self):
        return self._done.is_set()

    def _finish(self, tokens, reason):
        self.tokens = list(tokens)
        self.finish_reason = reason
        self.first_token_at = time.monotonic()
        self._done.set()


class StubReplica(ReplicaBase):
    """Scripted replica: canned snapshot, optional auto-finish or
    rejection, explicit failure injection."""

    def __init__(self, replica_id, snapshot=None, autofinish=None,
                 reject_with=None, heal_on_restart=False,
                 restart_autofinish=None):
        super().__init__(replica_id)
        self.snap = dict(_IDLE_SNAP, **(snapshot or {}))
        self.autofinish = autofinish  # tokens to finish with, or None
        self.reject_with = reject_with
        self.heal_on_restart = heal_on_restart
        self.restart_autofinish = restart_autofinish
        self.handles = []
        self.submit_calls = 0
        self.submit_kwargs = []
        self.brownouts = []
        self.failed = False
        self.drained = False
        self.shutdowns = 0
        self.restarts = 0

    def start(self):
        return self

    def submit(self, prompt_tokens, **kwargs):
        self.submit_calls += 1
        self.submit_kwargs.append(dict(kwargs))
        if self.reject_with is not None:
            raise self.reject_with
        handle = StubHandle(prompt_tokens)
        self.handles.append(handle)
        if self.autofinish is not None:
            handle._finish(self.autofinish, "max_new_tokens")
        return handle

    def load_snapshot(self):
        snap = dict(self.snap)
        snap["failed"] = self.failed
        snap["alive"] = snap["alive"] and not self.failed
        return snap

    def set_brownout(self, on):
        self.brownouts.append(bool(on))

    def drain(self):
        self.drained = True

    def restart(self):
        # a REAL replica restart fail-finishes anything still in flight
        # (fresh engine / fresh worker) — the monitor re-routes those
        for handle in self.handles:
            if not handle.done:
                handle._finish([], "error")
        self.restarts += 1
        self.failed = False
        if self.heal_on_restart:
            self.snap["active_slots"] = 0
            self.snap["unresponsive"] = False
        if self.restart_autofinish is not None:
            self.autofinish = self.restart_autofinish
        return self

    def shutdown(self):
        self.shutdowns += 1
        # a dead replica's engine/worker fail-finishes whatever it held
        for handle in self.handles:
            if not handle.done:
                handle._finish([], "error")


def _stub_router(replicas, **kw):
    kw.setdefault("monitor_interval", 0.001)
    return FleetRouter(replicas, **kw).start()


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------
def test_least_loaded_placement_deterministic():
    """Given FIXED load snapshots the policy's choice is a pure function:
    min(queue_depth + active_slots), ties to the earliest candidate."""
    policy = LeastLoaded()
    candidates = [
        ("0", dict(_IDLE_SNAP, queue_depth=3, active_slots=1)),
        ("1", dict(_IDLE_SNAP, queue_depth=0, active_slots=2)),
        ("2", dict(_IDLE_SNAP, queue_depth=1, active_slots=0)),
    ]
    for _ in range(5):
        assert policy.choose(candidates, [1, 2, 3]) == "2"
    # tie (load 2 vs load 2): earliest candidate wins
    tied = [
        ("a", dict(_IDLE_SNAP, queue_depth=1, active_slots=1)),
        ("b", dict(_IDLE_SNAP, queue_depth=0, active_slots=2)),
    ]
    assert LeastLoaded().choose(tied, []) == "a"


def test_round_robin_cycles_candidates():
    policy = RoundRobin()
    candidates = [("0", dict(_IDLE_SNAP)), ("1", dict(_IDLE_SNAP))]
    picks = [policy.choose(candidates, []) for _ in range(4)]
    assert picks == ["0", "1", "0", "1"]


def test_prefix_affinity_hits_and_forgets():
    """Identical prompt prefixes stick to the first-serving replica even
    when load says otherwise; forget() re-pins after an eviction."""
    policy = PrefixAffinity(prefix_tokens=4)
    heavy0 = [
        ("0", dict(_IDLE_SNAP, queue_depth=9)),
        ("1", dict(_IDLE_SNAP, queue_depth=0)),
    ]
    prefix = [7, 7, 7, 7]
    first = policy.choose(heavy0, prefix + [1])
    assert first == "1" and policy.last_hit is False  # least-loaded pick
    # same prefix, different tail, replica 1 now the HEAVY one: sticky
    heavy1 = [
        ("0", dict(_IDLE_SNAP, queue_depth=0)),
        ("1", dict(_IDLE_SNAP, queue_depth=9)),
    ]
    assert policy.choose(heavy1, prefix + [2]) == "1"
    assert policy.last_hit is True
    # a DIFFERENT prefix follows load as usual
    assert policy.choose(heavy1, [5, 5, 5, 5, 3]) == "0"
    assert policy.last_hit is False
    policy.forget("1")
    assert policy.choose(heavy1, prefix + [3]) == "0"
    assert policy.last_hit is False


def test_prefix_affinity_skips_sticky_replica_out_of_kv_pages():
    """A sticky replica whose snapshot reports an exhausted KV page pool
    is skipped for the placement (it would only bounce the request off
    its typed 'capacity' rejection) and the affinity entry re-pins."""
    policy = PrefixAffinity(prefix_tokens=4)
    prefix = [7, 7, 7, 7]
    both = [
        ("0", dict(_IDLE_SNAP, queue_depth=9, kv_blocks_free=8)),
        ("1", dict(_IDLE_SNAP, queue_depth=0, kv_blocks_free=8)),
    ]
    assert policy.choose(both, prefix + [1]) == "1"  # pins to 1
    starved = [
        ("0", dict(_IDLE_SNAP, queue_depth=0, kv_blocks_free=8)),
        ("1", dict(_IDLE_SNAP, queue_depth=9, kv_blocks_free=0)),
    ]
    # sticky replica 1 is out of pages: fall through to least-loaded
    assert policy.choose(starved, prefix + [2]) == "0"
    assert policy.last_hit is False
    # the entry moved with the traffic: replica 0 is the new sticky
    assert policy.choose(starved, prefix + [3]) == "0"
    assert policy.last_hit is True
    # snapshots WITHOUT the field (contiguous replicas) keep stickiness
    legacy = PrefixAffinity(prefix_tokens=4)
    assert legacy.choose(both, prefix + [1]) == "1"
    heavy1 = [
        ("0", dict(_IDLE_SNAP, queue_depth=0)),
        ("1", dict(_IDLE_SNAP, queue_depth=9)),
    ]
    assert legacy.choose(heavy1, prefix + [2]) == "1"
    assert legacy.last_hit is True


def test_router_mirrors_replica_prefix_cache_gauges():
    """Paged replicas' prefix_hit_rate / kv_blocks_free land on the
    per-replica fleet gauges and aggregate into fleet/prefix_hit_rate."""
    a = StubReplica("0", snapshot={
        "prefix_hits": 3, "prefix_misses": 1, "prefix_hit_rate": 0.75,
        "kv_blocks_free": 5, "kv_blocks_total": 8, "kv_blocks_used": 3,
    }, autofinish=[1])
    b = StubReplica("1", autofinish=[2])  # contiguous: no kv fields
    router = _stub_router([a, b])
    try:
        router.refresh_telemetry()
        snap = router.metrics.snapshot()
        assert snap["fleet/replica0/prefix_hit_rate"] == 0.75
        assert snap["fleet/replica0/kv_blocks_free"] == 5
        assert "fleet/replica1/prefix_hit_rate" not in snap
        assert snap["fleet/prefix_hit_rate"] == 0.75
    finally:
        router.shutdown()


def test_router_prefix_affinity_counts_hits():
    a = StubReplica("0", autofinish=[1])
    b = StubReplica("1", autofinish=[2])
    router = _stub_router([a, b], placement="prefix_affinity",
                          affinity_prefix_tokens=4)
    try:
        prefix = [9, 9, 9, 9]
        r1 = router.submit(prefix + [1], max_new_tokens=1)
        r2 = router.submit(prefix + [2], max_new_tokens=1)
        r1.result(2.0), r2.result(2.0)
        assert r1.replica_id == r2.replica_id
        assert router.metrics.snapshot()["fleet/affinity_hits"] == 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# admission: rate limits + priority shedding
# ---------------------------------------------------------------------------
def test_token_bucket_burst_and_refill():
    clock = [0.0]
    bucket = TokenBucket(rate=2.0, burst=2, clock=lambda: clock[0])
    assert bucket.try_acquire() and bucket.try_acquire()
    assert not bucket.try_acquire()  # burst spent, no time passed
    clock[0] += 0.5  # refills one token at 2/s
    assert bucket.try_acquire()
    assert not bucket.try_acquire()
    clock[0] += 10.0  # refill clamps at burst
    assert bucket.try_acquire() and bucket.try_acquire()
    assert not bucket.try_acquire()


def test_rate_limit_rejects_per_tenant_with_reason_code():
    clock = [0.0]
    a = StubReplica("0", autofinish=[1])
    router = _stub_router(
        [a], rate_limit=(1.0, 1), clock=lambda: clock[0],
        per_tenant_limits={"gold": {"requests_per_sec": 100.0, "burst": 3}},
    )
    try:
        router.submit([1, 2], tenant="free", max_new_tokens=1)
        with pytest.raises(RateLimited) as exc:
            router.submit([1, 2], tenant="free", max_new_tokens=1)
        assert exc.value.reason == "rate_limit"
        assert isinstance(exc.value, RequestRejected)  # one except clause
        # an over-limit tenant never touches a replica queue
        assert len(a.handles) == 1
        # other tenants have their own bucket
        for _ in range(3):
            router.submit([1, 2], tenant="gold", max_new_tokens=1)
        snap = router.metrics.snapshot()
        assert snap["fleet/requests_rate_limited"] == 1
        assert snap["fleet/requests_rejected"] == 1
        assert snap["fleet/requests_routed"] == 4
        # the bucket refills with the (injected) clock
        clock[0] += 1.1
        router.submit([1, 2], tenant="free", max_new_tokens=1)
    finally:
        router.shutdown()


def test_fleet_pressure_sheds_priority_classes_only():
    full = StubReplica(
        "0", snapshot={"queue_depth": 7, "queue_capacity": 8},
        autofinish=[1],
    )
    router = _stub_router([full], shed_queue_ratio=0.75)
    try:
        with pytest.raises(FleetOverloaded) as exc:
            router.submit([1], priority=1, max_new_tokens=1)
        assert exc.value.reason == "overload"
        router.submit([1], priority=0, max_new_tokens=1)  # never shed here
    finally:
        router.shutdown()


def test_draining_fleet_rejects_with_reason():
    router = _stub_router([StubReplica("0", autofinish=[1])])
    try:
        router.drain_fleet()
        with pytest.raises(RequestRejected) as exc:
            router.submit([1], max_new_tokens=1)
        assert exc.value.reason == "draining"
    finally:
        router.shutdown()


def test_unmeetable_deadline_rejected_at_router_door():
    """A deadline below even the fastest candidate's observed prefill is
    rejected at the ROUTER (reason "deadline") — it never burns a
    replica queue slot on a guaranteed miss."""
    slow = StubReplica("0", snapshot={"mean_prefill_ms": 50.0},
                       autofinish=[1])
    router = _stub_router([slow])
    try:
        with pytest.raises(RequestRejected) as exc:
            router.submit([1, 2], max_new_tokens=1, deadline_secs=0.01)
        assert exc.value.reason == "deadline"
        assert len(slow.handles) == 0
        # a meetable deadline passes the gate and places normally
        req = router.submit([1, 2], max_new_tokens=1, deadline_secs=5.0)
        assert req.result(2.0) == [1]
    finally:
        router.shutdown()


def test_affinity_hit_not_counted_when_sticky_replica_rejects():
    """The sticky replica rejecting at its door is NOT an affinity hit:
    the request actually lands elsewhere via fallback."""
    a = StubReplica("0", autofinish=[1])
    b = StubReplica("1", autofinish=[2])
    router = _stub_router([a, b], placement="prefix_affinity",
                          affinity_prefix_tokens=4)
    try:
        prefix = [3, 3, 3, 3]
        first = router.submit(prefix + [1], max_new_tokens=1)
        first.result(2.0)
        sticky = router._replicas[first.replica_id]
        other = b if sticky is a else a
        sticky.reject_with = RequestRejected("full", reason="overload")
        second = router.submit(prefix + [2], max_new_tokens=1)
        assert second.result(2.0) == (other.autofinish)
        assert second.replica_id == other.replica_id
        assert router.metrics.snapshot()["fleet/affinity_hits"] == 0
    finally:
        router.shutdown()


def test_all_replicas_rejecting_is_fleet_overloaded():
    rej = RequestRejected("queue full", reason="overload")
    router = _stub_router([
        StubReplica("0", reject_with=rej),
        StubReplica("1", reject_with=rej),
    ])
    try:
        with pytest.raises(FleetOverloaded):
            router.submit([1], max_new_tokens=1)
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# failure handling: eviction + re-route
# ---------------------------------------------------------------------------
def test_evicted_replica_requests_reroute_exactly_once():
    """A replica that dies under its requests is evicted; each of its
    requests is re-placed on a survivor and finishes exactly once."""
    flaky = StubReplica("0")          # least loaded: takes the request
    backup = StubReplica("1", snapshot={"queue_depth": 5}, autofinish=[42])
    router = _stub_router([flaky, backup], max_reroutes=2)
    try:
        req = router.submit([1, 2, 3], max_new_tokens=1)
        assert req.replica_id == "0"
        # the replica crashes past its restart budget: its scheduler
        # fail-finishes the in-flight request, the snapshot reports failed
        flaky.failed = True
        flaky.handles[0]._finish([], "error")
        assert req.result(5.0) == [42]
        assert req.replica_id == "1"
        assert req.reroutes == 1
        assert req.finish_reason == "max_new_tokens"
        deadline = time.monotonic() + 5.0
        while ("0" not in router.evicted_ids
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert router.evicted_ids == {"0"}
        assert flaky.shutdowns == 1
        router.refresh_telemetry()
        snap = router.metrics.snapshot()
        assert snap["fleet/replicas_evicted"] == 1
        assert snap["fleet/requests_rerouted"] == 1
        assert snap["fleet/requests_completed"] == 1
        assert snap["fleet/replicas_total"] == 1
    finally:
        router.shutdown()


def test_reroute_charges_elapsed_deadline_time():
    """A re-routed request carries its REMAINING end-to-end deadline to
    the new replica (the clock does not restart), and one that expired
    while its replica died finishes "deadline" instead of getting a
    fresh full-budget generation elsewhere."""
    flaky = StubReplica("0")
    backup = StubReplica("1", snapshot={"queue_depth": 5}, autofinish=[7])
    router = _stub_router([flaky, backup], max_reroutes=2)
    try:
        req = router.submit([1, 2], max_new_tokens=1, deadline_secs=30.0)
        flaky.failed = True
        flaky.handles[0]._finish([], "error")
        assert req.result(5.0) == [7]
        carried = backup.handles[0]
        # the backup saw a reduced budget, not the original 30s
        assert req.kwargs["deadline_secs"] < 30.0
        assert carried.prompt_tokens == [1, 2]

        # expired-while-dying: terminal "deadline", no re-placement
        router2 = _stub_router(
            [StubReplica("a"), StubReplica("b", autofinish=[9])],
            max_reroutes=2,
        )
        try:
            req2 = router2.submit([3], max_new_tokens=1,
                                  deadline_secs=0.01)
            replica_a = router2._replicas["a"]
            time.sleep(0.05)  # deadline passes while the replica dies
            replica_a.failed = True
            for handle in replica_a.handles:
                handle._finish([], "error")
            deadline = time.monotonic() + 5.0
            while not req2.done and time.monotonic() < deadline:
                time.sleep(0.005)
            assert req2.finish_reason == "deadline"
            assert req2.result(0) == []  # partial-answer contract
            assert router2._replicas["b"].handles == []  # never re-placed
        finally:
            router2.shutdown()
    finally:
        router.shutdown()


def test_reroute_budget_exhausted_fails_loudly():
    dead_a = StubReplica("0")
    dead_b = StubReplica("1")
    router = _stub_router([dead_a, dead_b], max_reroutes=1)
    try:
        req = router.submit([1], max_new_tokens=1)
        for replica in (dead_a, dead_b):
            replica.failed = True
            for handle in replica.handles:
                if not handle.done:
                    handle._finish([], "error")
        # the re-routed copy lands on the OTHER dead replica and dies too;
        # budget 1 means the router must now fail the fleet request
        deadline = time.monotonic() + 5.0
        while not req.done and time.monotonic() < deadline:
            for replica in (dead_a, dead_b):
                for handle in replica.handles:
                    if not handle.done:
                        handle._finish([], "error")
            time.sleep(0.005)
        assert req.done
        assert req.finish_reason == "error"
        with pytest.raises(RuntimeError, match="re-route"):
            req.result(0)
    finally:
        router.shutdown()


def test_histogram_quantile_interpolates():
    from deepspeed_tpu.telemetry.registry import Histogram

    hist = Histogram("t", buckets=(10.0, 20.0, 40.0))
    assert _histogram_quantile(hist, 0.5) == 0.0  # empty
    for v in (5, 5, 15, 15, 35, 35, 35, 35):
        hist.observe(v)
    p50 = _histogram_quantile(hist, 0.5)
    p99 = _histogram_quantile(hist, 0.99)
    assert 10.0 <= p50 <= 20.0
    assert 20.0 < p99 <= 40.0


# ---------------------------------------------------------------------------
# worker RPC protocol (in-process: no spawn, no jax)
# ---------------------------------------------------------------------------
class _ChanIn:
    """Blocking line source driving WorkerServer.run like a real pipe."""

    def __init__(self):
        self._q = queue.Queue()

    def send(self, line):
        self._q.put(line + "\n")

    def close(self):
        self._q.put(None)

    def __iter__(self):
        while True:
            line = self._q.get()
            if line is None:
                return
            yield line


class _ChanOut:
    """Collects protocol lines; tests wait on arrival."""

    def __init__(self):
        self.lines = []
        self._cond = threading.Condition()

    def write(self, text):
        with self._cond:
            self.lines.append(text.strip())
            self._cond.notify_all()

    def flush(self):
        pass

    def wait_for(self, predicate, timeout=5.0):
        import json

        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                for raw in self.lines:
                    msg = json.loads(raw)
                    if predicate(msg):
                        return msg
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"no matching line in {self.lines}")
                self._cond.wait(remaining)


class _FakeWorkerEngine:
    """The InferenceEngine surface WorkerServer drives, scripted."""

    def __init__(self):
        self.scheduler = self
        self.drained = False
        self.closed = False

    def serve_forever(self):
        pass

    def submit(self, prompt, max_new_tokens=32, **kwargs):
        if prompt == ["reject"]:
            raise RequestRejected("full", reason="overload")
        if not prompt:
            raise ValueError("empty prompt")
        handle = StubHandle(prompt)
        handle._finish([t + 1 for t in prompt][:max_new_tokens],
                       "max_new_tokens")
        return handle

    def load_snapshot(self):
        return dict(_IDLE_SNAP)

    def drain(self):
        self.drained = True

    def close(self):
        self.closed = True


def test_worker_server_protocol_roundtrip():
    import json

    stdin, stdout = _ChanIn(), _ChanOut()
    engine = _FakeWorkerEngine()
    server = WorkerServer(stdin, stdout, lambda spec: engine,
                          poll_interval=0.001)
    thread = threading.Thread(target=server.run, daemon=True)
    thread.start()
    stdin.send(json.dumps({"op": "init", "spec": {}}))
    stdout.wait_for(lambda m: m.get("event") == "ready")
    stdin.send(json.dumps({
        "op": "submit", "id": 1, "prompt": [10, 20], "max_new_tokens": 2,
    }))
    stdout.wait_for(
        lambda m: m.get("event") == "reply" and m.get("id") == 1
        and "error" not in m
    )
    fin = stdout.wait_for(
        lambda m: m.get("event") == "finished" and m.get("id") == 1
    )
    assert fin["tokens"] == [11, 21]
    assert fin["reason"] == "max_new_tokens"
    # a rejected submit carries the machine-readable reason through
    stdin.send(json.dumps(
        {"op": "submit", "id": 2, "prompt": ["reject"]}
    ))
    rej = stdout.wait_for(
        lambda m: m.get("event") == "reply" and m.get("id") == 2
    )
    assert rej["reason"] == "overload" and rej["error"]
    stdin.send(json.dumps({"op": "snapshot", "id": 3}))
    snap = stdout.wait_for(
        lambda m: m.get("event") == "reply" and m.get("id") == 3
    )
    assert snap["snapshot"]["queue_depth"] == 0
    stdin.send(json.dumps({"op": "drain"}))
    stdin.send(json.dumps({"op": "shutdown"}))
    thread.join(5.0)
    assert not thread.is_alive()
    assert engine.drained and engine.closed


# ---------------------------------------------------------------------------
# real engines: drain steering, rolling restart, parity
# ---------------------------------------------------------------------------
def _small_model(seed=0):
    cfg = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        dropout=0.0, use_flash=False,
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jnp.asarray(
        np.random.default_rng(seed).integers(0, VOCAB, (1, 8)), jnp.int32
    )
    params = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        ids0, ids0,
    )["params"]
    return cfg, model, params


_ENGINE_BLOCK = {
    "max_batch_slots": 2, "max_seq_len": 48, "prefill_len": 16,
    "sampling": {"greedy": True},
}


def _factory(model, params):
    def build():
        return deepspeed_tpu.init_inference(
            model=model, model_parameters=params,
            config={"inference": dict(_ENGINE_BLOCK)},
        )

    return build


def _prompts(n, seed=1):
    rng = np.random.default_rng(seed)
    return [
        [int(t) for t in rng.integers(0, VOCAB, k)]
        for k in rng.integers(5, 12, n)
    ]


def test_fleet_drain_steers_traffic_while_inflight_finishes():
    cfg, model, params = _small_model()
    router = deepspeed_tpu.init_fleet(
        engine_factory=_factory(model, params),
        config={"serving": {"replicas": 2}},
    )
    try:
        long_req = router.submit(_prompts(1)[0], max_new_tokens=24)
        target = long_req.replica_id
        other = next(r for r in router.replica_ids if r != target)
        router.drain(target)
        after = [router.submit(p, max_new_tokens=4) for p in _prompts(3, 7)]
        for req in after:
            req.result(60.0)
            assert req.replica_id == other  # steered away from the drain
        assert long_req.result(60.0)  # in-flight work still finished
        assert long_req.replica_id == target
        assert long_req.reroutes == 0
    finally:
        router.shutdown()


def test_rolling_restart_exactly_once_and_bitwise_parity():
    """The acceptance pin: a rolling restart across 2 replicas under
    concurrent traffic finishes every submitted request exactly once
    (none lost, none duplicated), keeps routable capacity at/above the
    configured floor, and greedy outputs stay bitwise-identical to a
    single-replica run of the same prompts."""
    cfg, model, params = _small_model()
    prompts = _prompts(4, seed=3)

    single = _factory(model, params)()
    reference = single.generate(prompts, max_new_tokens=8)
    single.close()

    router = deepspeed_tpu.init_fleet(
        engine_factory=_factory(model, params),
        config={"serving": {"replicas": 2, "capacity_floor": 0.5}},
    )
    floor_breached = []
    available = router.metrics.gauge("fleet/replicas_available")
    try:
        results = {}
        errors = []

        def pump(i):
            try:
                req = router.submit(prompts[i % 4], max_new_tokens=8)
                results.setdefault(i, []).append(req.result(120.0))
            except Exception as e:  # pragma: no cover - failure detail
                errors.append((i, e))

        threads = [
            threading.Thread(target=pump, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()

        watching = threading.Event()

        def watch_floor():
            while not watching.is_set():
                if available.value < 1.0:
                    floor_breached.append(available.value)
                time.sleep(0.002)

        watcher = threading.Thread(target=watch_floor, daemon=True)
        watcher.start()
        router.rolling_restart(wait_timeout=60.0)
        for t in threads:
            t.join(120.0)
        watching.set()
        watcher.join(5.0)

        assert not errors, errors
        assert len(results) == 8  # every submission answered...
        for i, answers in results.items():
            assert len(answers) == 1  # ...exactly once
            assert answers[0] == reference[i % 4]  # ...bitwise greedy
        assert sum(router.routed_counts.values()) >= 8
        snap = router.metrics.snapshot()
        assert snap["fleet/replica_restarts"] == 2
        assert snap["fleet/requests_completed"] == 8
        assert snap["fleet/ttft_ms/count"] == 8
        # capacity floor held for the whole restart (1 of 2 replicas)
        assert not floor_breached, floor_breached
    finally:
        router.shutdown()


def test_rolling_restart_refuses_impossible_floor():
    router = _stub_router([StubReplica("0", autofinish=[1])],
                          capacity_floor=0.9)
    try:
        with pytest.raises(RuntimeError, match="capacity floor"):
            router.rolling_restart()
    finally:
        router.shutdown()


def test_subprocess_replica_end_to_end_greedy_parity():
    """One worker subprocess serving the tiniest GPT-2: submissions cross
    the pipe, answers match an in-process engine of the same seed
    bitwise, and shutdown reaps the process."""
    from deepspeed_tpu.serving import SubprocessReplica

    model_kw = {
        "vocab_size": 64, "n_positions": 32, "n_embd": 16, "n_layer": 1,
        "n_head": 2, "use_flash": False,
    }
    engine_block = {
        "max_batch_slots": 2, "max_seq_len": 24, "prefill_len": 8,
        "sampling": {"greedy": True},
    }
    spec = {
        "model": model_kw, "init_seed": 0,
        "config": {"inference": engine_block},
    }
    rng = np.random.default_rng(5)
    prompts = [[int(t) for t in rng.integers(0, 64, 6)] for _ in range(2)]

    from deepspeed_tpu.serving.worker import build_engine_from_spec

    local = build_engine_from_spec(spec)
    reference = local.generate(prompts, max_new_tokens=5)
    local.close()

    replica = SubprocessReplica("sub0", spec, start_timeout=240.0)
    replica.start()
    try:
        snap = replica.load_snapshot()
        assert snap["alive"] and not snap["failed"]
        handles = [
            replica.submit(p, max_new_tokens=5) for p in prompts
        ]
        outs = [h.result(120.0) for h in handles]
        assert outs == reference
        assert all(h.finish_reason == "max_new_tokens" for h in handles)
    finally:
        replica.shutdown()
    assert not replica.alive and not replica.failed


# ---------------------------------------------------------------------------
# circuit breakers (serving/breaker.py, docs/serving.md "Circuit breakers")
# ---------------------------------------------------------------------------
def test_circuit_breaker_state_machine():
    """Closed -> open after N CONSECUTIVE failures, exponentially
    backed-off windows with exactly one half-open probe each, success
    closes, probe failure re-opens with a doubled window."""
    clock = [0.0]
    br = CircuitBreaker(failure_threshold=2, backoff_secs=1.0,
                        backoff_max_secs=8.0, clock=lambda: clock[0],
                        seed=3)
    assert br.state == BREAKER_CLOSED and br.routable()
    br.record_failure()
    assert br.state == BREAKER_CLOSED  # 1 < threshold
    br.record_success()
    br.record_failure()
    assert br.state == BREAKER_CLOSED  # success reset the streak
    br.record_failure()
    br.record_failure()
    assert br.state == BREAKER_OPEN
    assert not br.routable() and not br.allow_request()
    # window 1: base 1.0s (+ <=10% jitter)
    assert 1.0 <= br.open_window_remaining <= 1.1
    clock[0] += 1.2
    assert br.routable()
    assert br.allow_request()           # THE probe ticket
    assert br.state == BREAKER_HALF_OPEN
    assert not br.allow_request()       # one probe per window, exactly
    assert not br.routable()
    br.record_failure()                 # probe failed: re-open, doubled
    assert br.state == BREAKER_OPEN
    assert 2.0 <= br.open_window_remaining <= 2.2
    clock[0] += 2.3
    assert br.allow_request()
    br.record_success()                 # probe answered: rejoin
    assert br.state == BREAKER_CLOSED and br.routable()
    assert br.consecutive_failures == 0


def test_circuit_breaker_backoff_caps_and_jitter_deterministic():
    clock = [0.0]

    def windows(seed):
        clock[0] = 0.0
        br = CircuitBreaker(failure_threshold=1, backoff_secs=1.0,
                            backoff_max_secs=4.0,
                            clock=lambda: clock[0], seed=seed)
        out = []
        for _ in range(5):
            br.record_failure()
            out.append(br.open_window_remaining)
            clock[0] += br.open_window_remaining + 0.01
            assert br.allow_request()
        return out

    first = windows(seed=9)
    assert first == windows(seed=9)  # same seed => same jitter sequence
    # the exponential caps at backoff_max (jitter rides on top)
    assert first[-1] <= 4.0 * 1.1
    assert first[0] < first[1] < first[2]


def test_router_breaker_opens_skips_probes_and_rejoins():
    """The acceptance pin: a replica failing N consecutive RPCs is
    skipped by placement while open, receives exactly one half-open
    probe per backoff window, and rejoins with its state intact (no
    restart, no eviction, no affinity forget) on probe success."""
    clock = [0.0]
    flaky = StubReplica("0", reject_with=ReplicaRPCError("pipe torn"))
    healthy = StubReplica("1", autofinish=[5])
    router = _stub_router(
        [flaky, healthy], clock=lambda: clock[0],
        breaker_failure_threshold=2, breaker_backoff_secs=1.0,
    )
    try:
        # least-loaded ties break to replica 0: every submit tries the
        # flaky one first while its breaker is closed
        assert router.submit([1], max_new_tokens=1).result(5.0) == [5]
        assert router.breaker_state("0") == BREAKER_CLOSED
        assert router.submit([1], max_new_tokens=1).result(5.0) == [5]
        assert router.breaker_state("0") == BREAKER_OPEN
        calls_when_opened = flaky.submit_calls
        # open: dropped from the candidate set entirely
        for _ in range(3):
            assert router.submit([1], max_new_tokens=1).result(5.0) == [5]
        assert flaky.submit_calls == calls_when_opened
        assert [rid for rid, _ in router._candidates()] == ["1"]
        # window elapses: exactly ONE probe goes through, fails, re-opens
        clock[0] += 1.2
        assert router.submit([1], max_new_tokens=1).result(5.0) == [5]
        assert flaky.submit_calls == calls_when_opened + 1
        assert router.breaker_state("0") == BREAKER_OPEN
        assert router.submit([1], max_new_tokens=1).result(5.0) == [5]
        assert flaky.submit_calls == calls_when_opened + 1  # window shut
        # replica heals; next window's probe succeeds and it rejoins
        flaky.reject_with = None
        flaky.autofinish = [7]
        clock[0] += 3.0
        req = router.submit([1], max_new_tokens=1)
        assert req.result(5.0) == [7] and req.replica_id == "0"
        assert router.breaker_state("0") == BREAKER_CLOSED
        # rejoined with state INTACT: the breaker never restarted or
        # evicted the replica, so pool/affinity state survived untouched
        assert flaky.restarts == 0 and flaky.shutdowns == 0
        assert router.evicted_ids == set()
        snap = router.metrics.snapshot()
        assert snap["fleet/breaker_opens"] == 2
        assert snap["fleet/breaker_probes"] == 2
        router.refresh_telemetry()
        snap = router.metrics.snapshot()
        assert snap["fleet/replica0/circuit_state"] == BREAKER_CLOSED
    finally:
        router.shutdown()


@pytest.mark.parametrize("placement", [
    "least_loaded", "round_robin", "prefix_affinity", "adapter_affinity",
])
def test_open_breaker_excluded_from_every_placement_policy(placement):
    flaky = StubReplica("0", autofinish=[1])
    healthy = StubReplica("1", autofinish=[2])
    router = _stub_router([flaky, healthy], placement=placement,
                          breaker_failure_threshold=1,
                          breaker_backoff_secs=60.0)
    try:
        router._note_breaker_failure("0", RuntimeError("rpc"))
        assert router.breaker_state("0") == BREAKER_OPEN
        for i in range(4):
            req = router.submit([9, 9, 9, 9, i], max_new_tokens=1)
            assert req.result(5.0) == [2]
            assert req.replica_id == "1"
        assert flaky.submit_calls == 0
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# zombie detection (docs/serving.md "Zombie detection")
# ---------------------------------------------------------------------------
def test_zombie_replica_detected_restarted_and_request_rerouted():
    """A replica with active slots and frozen completion counters is
    drained-then-restarted after zombie_secs; its in-flight request
    fail-finishes with the restart and re-routes exactly once."""
    zombie = StubReplica("0", snapshot={"active_slots": 1},
                         heal_on_restart=True, restart_autofinish=[99])
    backup = StubReplica("1", snapshot={"queue_depth": 9}, autofinish=[3])
    router = _stub_router([zombie, backup], zombie_secs=0.05,
                          monitor_interval=0.005)
    try:
        req = router.submit([1, 2], max_new_tokens=1)
        assert req.replica_id == "0"  # lands on the (sticking) zombie
        assert req.result(10.0) == [99]
        assert req.reroutes == 1
        assert zombie.restarts == 1
        assert zombie.drained  # drained-then-restarted, not killed cold
        snap = router.metrics.snapshot()
        assert snap["fleet/zombie_restarts"] == 1
        assert snap["fleet/replica_restarts"] == 1
        assert router.evicted_ids == set()  # restart sufficed
    finally:
        router.shutdown()


def test_zombie_past_restart_budget_is_evicted():
    zombie = StubReplica("0", snapshot={"active_slots": 1})  # never heals
    backup = StubReplica("1", snapshot={"queue_depth": 9}, autofinish=[3])
    router = _stub_router([zombie, backup], zombie_secs=0.04,
                          zombie_restart_budget=1, monitor_interval=0.005)
    try:
        req = router.submit([1], max_new_tokens=1)
        assert req.result(10.0) == [3]  # survives via re-route
        deadline = time.monotonic() + 10.0
        while router.evicted_ids != {"0"} and time.monotonic() < deadline:
            time.sleep(0.01)
        assert router.evicted_ids == {"0"}
        snap = router.metrics.snapshot()
        assert snap["fleet/zombie_restarts"] == 1  # budget 1, then evict
        assert zombie.restarts == 1
        assert snap["fleet/replicas_evicted"] == 1
    finally:
        router.shutdown()


def test_unresponsive_replica_counts_as_zombie():
    """A live-but-unresponsive worker (snapshot RPCs failing with the
    process alive) is zombie food even with no visible active slots —
    frozen is frozen."""
    hung = StubReplica("0", snapshot={"unresponsive": True, "alive": False},
                       heal_on_restart=True)
    router = _stub_router([hung], zombie_secs=0.04, monitor_interval=0.005)
    try:
        deadline = time.monotonic() + 10.0
        while hung.restarts == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hung.restarts == 1
        assert router.metrics.snapshot()["fleet/zombie_restarts"] == 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# brownout degradation (docs/serving.md "Brownout degradation")
# ---------------------------------------------------------------------------
def test_brownout_clamps_sheddable_requests_between_thresholds():
    """The acceptance pin: between brownout_queue_ratio and the shed
    ratio, priority > 0 requests COMPLETE with max_new_tokens clamped to
    the floor instead of raising FleetOverloaded; above the shed ratio
    the existing rejection is unchanged; leaving the band restores full
    budgets."""
    full = StubReplica("0", snapshot={"queue_depth": 4}, autofinish=[1])
    router = _stub_router(
        [full], shed_queue_ratio=0.75, brownout_queue_ratio=0.5,
        brownout_max_new_tokens=4,
    )
    try:
        # fill 4/8 = 0.5: inside the brownout band [0.5, 0.75)
        req = router.submit([1], priority=1, max_new_tokens=32)
        assert req.result(5.0) == [1]  # completes, NOT FleetOverloaded
        assert full.submit_kwargs[-1]["max_new_tokens"] == 4
        assert router.brownout
        snap = router.metrics.snapshot()
        assert snap["fleet/brownout"] == 1.0
        assert snap["fleet/requests_browned_out"] == 1
        assert full.brownouts[-1] is True  # replicas heard the toggle
        # priority 0 keeps its full budget even in the band
        router.submit([1], priority=0, max_new_tokens=32).result(5.0)
        assert full.submit_kwargs[-1]["max_new_tokens"] == 32
        # above the shed ratio: rejection behavior unchanged
        full.snap["queue_depth"] = 7
        with pytest.raises(FleetOverloaded):
            router.submit([1], priority=1, max_new_tokens=32)
        router.submit([1], priority=0, max_new_tokens=32).result(5.0)
        # queue drains: the monitor's refresh EXITS the brownout window
        full.snap["queue_depth"] = 0
        router.refresh_telemetry()
        assert not router.brownout
        assert router.metrics.snapshot()["fleet/brownout"] == 0.0
        assert full.brownouts[-1] is False
        router.submit([1], priority=1, max_new_tokens=32).result(5.0)
        assert full.submit_kwargs[-1]["max_new_tokens"] == 32
    finally:
        router.shutdown()


def test_brownout_requires_config_and_small_requests_uncounted():
    """Without brownout_queue_ratio the band never engages; requests
    already under the floor are admitted untouched and uncounted."""
    full = StubReplica("0", snapshot={"queue_depth": 4}, autofinish=[1])
    router = _stub_router([full], shed_queue_ratio=0.75)
    try:
        router.submit([1], priority=1, max_new_tokens=32).result(5.0)
        assert full.submit_kwargs[-1]["max_new_tokens"] == 32
        assert not router.brownout
        assert router.metrics.snapshot()["fleet/brownout"] == 0.0
    finally:
        router.shutdown()
    full2 = StubReplica("0", snapshot={"queue_depth": 4}, autofinish=[1])
    router2 = _stub_router([full2], shed_queue_ratio=0.75,
                           brownout_queue_ratio=0.5,
                           brownout_max_new_tokens=8)
    try:
        router2.submit([1], priority=1, max_new_tokens=2).result(5.0)
        assert full2.submit_kwargs[-1]["max_new_tokens"] == 2
        assert router2.metrics.snapshot()[
            "fleet/requests_browned_out"] == 0
    finally:
        router2.shutdown()


# ---------------------------------------------------------------------------
# serving-seam fault sites (resilience/faults.py, docs/resilience.md):
# the chaos matrix — every site injected against a live 2-replica fleet
# finishes all submitted requests exactly once, or fail-finishes typed.
# ---------------------------------------------------------------------------
class _FakeEngine:
    """The InferenceEngine surface InProcessReplica drives, scripted and
    jax-free: deterministic answers from the prompt so exactly-once
    re-routing is assertable bitwise."""

    class _Sched:
        def __init__(self):
            self._stop = threading.Event()
            self.driver_failed = False

        def drain(self):
            pass

    def __init__(self):
        self.scheduler = self._Sched()

    def serve_forever(self):
        pass

    def submit(self, prompt, max_new_tokens=32, **kwargs):
        handle = StubHandle(prompt)
        base = int(prompt[-1]) if prompt else 0
        handle._finish(
            [(base + i + 1) % 1000 for i in range(int(max_new_tokens))],
            "max_new_tokens",
        )
        return handle

    def load_snapshot(self):
        return dict(_IDLE_SNAP)

    def close(self):
        self.scheduler._stop.set()


def _expected_answer(prompt, max_new):
    base = int(prompt[-1])
    return [(base + i + 1) % 1000 for i in range(max_new)]


def test_chaos_router_place_fault_absorbed_by_fallback():
    """A raising placement policy (chaos site router.place) must cost a
    fallback choice, never the submission."""
    from deepspeed_tpu.serving import InProcessReplica
    from deepspeed_tpu.telemetry.registry import diagnostics_registry

    injector = FaultInjector(
        [FaultSpec("router.place", times=2, seed=0)], seed=0
    )
    replicas = [InProcessReplica(str(i), _FakeEngine) for i in range(2)]
    router = FleetRouter(replicas, monitor_interval=0.001,
                         fault_injector=injector).start()
    try:
        before = diagnostics_registry().snapshot().get(
            "internal/suppressed_errors/serving.router_place", 0
        )
        reqs = [router.submit([10 + i], max_new_tokens=3) for i in range(4)]
        for i, req in enumerate(reqs):
            assert req.result(10.0) == _expected_answer([10 + i], 3)
            assert req.finish_reason == "max_new_tokens"
        assert injector.injected["router.place"] == 2
        after = diagnostics_registry().snapshot()[
            "internal/suppressed_errors/serving.router_place"
        ]
        assert after - before == 2  # absorbed, counted, never silent
    finally:
        router.shutdown()


def test_chaos_snapshot_stale_fault_survived():
    """Stale load snapshots skew placement but must never lose or
    duplicate a request."""
    from deepspeed_tpu.serving import InProcessReplica

    injector = FaultInjector(
        [FaultSpec("snapshot.stale", times=3, seed=0)], seed=0
    )
    replicas = [
        InProcessReplica(str(i), _FakeEngine, fault_injector=injector)
        for i in range(2)
    ]
    router = FleetRouter(replicas, monitor_interval=0.001).start()
    try:
        reqs = [router.submit([20 + i], max_new_tokens=3) for i in range(6)]
        for i, req in enumerate(reqs):
            assert req.result(10.0) == _expected_answer([20 + i], 3)
        deadline = time.monotonic() + 5.0
        while (
            injector.injected.get("snapshot.stale", 0) < 3
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)  # the monitor's snapshot polls finish it off
        assert injector.injected["snapshot.stale"] == 3
    finally:
        router.shutdown()


def test_snapshot_stale_fault_freezes_previous_values():
    """The site's contract at the replica seam: an armed traversal
    returns the PREVIOUS call's values, bit for bit."""
    injector = FaultInjector(
        [FaultSpec("snapshot.stale", times=2, seed=0)], seed=0
    )

    class Probe(ReplicaBase):
        def __init__(self):
            super().__init__("p", fault_injector=injector)
            self.n = 0

        def _snapshot_now(self):
            self.n += 1
            return dict(_IDLE_SNAP, queue_depth=self.n)

    probe = Probe()
    assert probe.load_snapshot()["queue_depth"] == 1  # nothing cached yet
    assert probe.load_snapshot()["queue_depth"] == 1  # frozen (fault 1)
    assert probe.load_snapshot()["queue_depth"] == 1  # frozen (fault 2)
    assert probe.load_snapshot()["queue_depth"] == 2  # spec exhausted
    assert probe.n == 2


def test_chaos_replica_flap_restart_retried_then_rejoins():
    """replica.flap: the first restart attempt crashes; the router's
    retry loop absorbs it and the replica rejoins."""
    from deepspeed_tpu.serving import InProcessReplica

    # traversals 1-2 are the two initial start()s; the restart is 3
    injector = FaultInjector(
        [FaultSpec("replica.flap", after=2, times=1, seed=0)], seed=0
    )
    replicas = [
        InProcessReplica(str(i), _FakeEngine, fault_injector=injector)
        for i in range(2)
    ]
    router = FleetRouter(replicas, monitor_interval=0.001).start()
    try:
        assert router.restart_replica("0") is True
        assert injector.injected["replica.flap"] == 1
        req = router.submit([30], max_new_tokens=2)
        assert req.result(10.0) == _expected_answer([30], 2)
        assert router.evicted_ids == set()
        assert router.metrics.snapshot()["fleet/replica_restarts"] == 1
    finally:
        router.shutdown()


def test_chaos_replica_flap_exhausted_restarts_evicts():
    """A replica that crashes on EVERY restart attempt is condemned and
    evicted instead of parking in an unroutable limbo."""
    from deepspeed_tpu.serving import InProcessReplica

    injector = FaultInjector(
        [FaultSpec("replica.flap", after=2, times=0, seed=0)], seed=0
    )
    replicas = [
        InProcessReplica(str(i), _FakeEngine, fault_injector=injector)
        for i in range(2)
    ]
    router = FleetRouter(replicas, monitor_interval=0.001).start()
    try:
        assert router.restart_replica("0") is False
        deadline = time.monotonic() + 10.0
        while router.evicted_ids != {"0"} and time.monotonic() < deadline:
            time.sleep(0.01)
        assert router.evicted_ids == {"0"}
        # the survivor keeps serving
        req = router.submit([40], max_new_tokens=2)
        assert req.result(10.0) == _expected_answer([40], 2)
        assert req.replica_id == "1"
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# rpc.* sites + RPC hardening over REAL worker subprocesses (the stub
# engine keeps them jax-free and fast; serving/worker.py StubWorkerEngine)
# ---------------------------------------------------------------------------
def _stub_worker_replica(rid, *, faults=None, config=None, stub=None,
                         rpc_timeout=0.5, rpc_retries=1):
    spec = {"stub": dict(stub or {})}
    if config is not None:
        spec["config"] = config
    return SubprocessReplica(
        rid, spec, start_timeout=90.0, rpc_timeout=rpc_timeout,
        rpc_retries=rpc_retries, rpc_backoff_secs=0.01,
        fault_injector=faults,
    )


@pytest.mark.parametrize("site,mode", [
    ("rpc.send", "drop"),
    ("rpc.recv", "corrupt"),
    ("replica.hang", None),
])
def test_chaos_matrix_rpc_sites_exactly_once(site, mode):
    """The pipe-seam chaos matrix against a live 2-replica subprocess
    fleet: the armed fault costs the flaky replica a breaker trip, and
    every submission still finishes exactly once with the bitwise
    expected answer (absorbed by fall-through placement)."""
    faults0 = None
    config0 = None
    # deterministic traversal targeting (telemetry refresh is pushed out
    # of the way below, so the pipe traffic is exactly: init, the
    # start() refresh snapshot, then per submit a candidates snapshot
    # followed by the submit op itself):
    if site == "replica.hang":
        # worker-side injector (rides the spec config into the worker
        # process); its op-loop counting starts AFTER init, so the first
        # submit is traversal 3 (refresh snap, candidates snap, submit)
        config0 = {"resilience": {"fault_injection": {
            "enabled": True,
            "faults": [{"site": "replica.hang", "after": 2, "times": 1,
                        "args": {"duration_ms": 900}}],
        }}}
    else:
        # parent-side injector: init/ready (1), refresh snap (2),
        # candidates snap (3), first submit op/ack (4)
        faults0 = FaultInjector(
            [FaultSpec(site, after=3, times=1, args={"mode": mode},
                       seed=0)],
            seed=0,
        )
    # a small stub delay keeps the finished event strictly AFTER the
    # submit ack on the pipe, so the armed traversal is the ack
    r0 = _stub_worker_replica("0", faults=faults0, config=config0,
                              stub={"delay_secs": 0.05})
    r1 = _stub_worker_replica("1", stub={"delay_secs": 0.05})
    router = FleetRouter(
        [r0, r1], monitor_interval=0.005, telemetry_refresh_secs=3600.0,
        breaker_failure_threshold=1, breaker_backoff_secs=0.25,
    ).start()
    try:
        reqs = [router.submit([10 + i], max_new_tokens=3) for i in range(4)]
        for i, req in enumerate(reqs):
            assert req.result(60.0) == _expected_answer([10 + i], 3)
            assert req.finish_reason == "max_new_tokens"
        if faults0 is not None:
            assert faults0.injected[site] == 1  # pinned per (seed, site)
        # the transport failure fed the breaker, not a re-route
        snap = router.metrics.snapshot()
        assert snap["fleet/breaker_opens"] >= 1
        assert snap["fleet/requests_rerouted"] == 0
    finally:
        router.shutdown()


def test_reply_after_timeout_is_dropped_not_matched_later():
    """Satellite pin: a reply landing AFTER its waiter timed out (an
    injected rpc.recv delay) is discarded by the reader — it neither
    leaks in _replies nor gets matched to a later rpc_id."""
    injector = FaultInjector(
        [FaultSpec("rpc.recv", after=1, times=1,
                   args={"mode": "delay", "delay_ms": 700}, seed=0)],
        seed=0,
    )
    replica = _stub_worker_replica(
        "late", faults=injector, rpc_timeout=0.2, rpc_retries=0
    )
    replica.start()
    try:
        with pytest.raises(ReplicaRPCError):
            replica.submit([1, 2], max_new_tokens=2)  # ack arrives late
        time.sleep(1.2)  # let the delayed ack land (and be dropped)
        with replica._reply_cond:
            assert replica._replies == {}
            assert replica._expected == set()
        # the transport is healthy again and later rpc_ids are untouched
        snap = replica.load_snapshot()
        assert snap["alive"] and not snap.get("unresponsive")
        handle = replica.submit([3], max_new_tokens=2)
        assert handle.result(30.0) == _expected_answer([3], 2)
    finally:
        replica.shutdown()


def test_rpc_retry_absorbs_transient_control_op_failure():
    """Idempotent control ops (snapshot) retry with backoff through a
    transient transport fault; the retry is counted, the caller never
    sees it."""
    injector = FaultInjector(
        [FaultSpec("rpc.recv", after=1, times=1,
                   args={"mode": "delay", "delay_ms": 400}, seed=0)],
        seed=0,
    )
    replica = _stub_worker_replica(
        "retry", faults=injector, rpc_timeout=0.2, rpc_retries=2
    )
    replica.start()
    try:
        snap = replica.load_snapshot()  # first attempt eats the delay
        assert snap["alive"] and not snap.get("unresponsive")
        assert replica.rpc_retries_used >= 1
    finally:
        replica.shutdown()


def test_hung_worker_reads_unresponsive_not_failed():
    """A worker whose op loop stalls past the retry budget is classified
    UNRESPONSIVE (alive process, no answers) — not failed: it must not
    be mistaken for a corpse and evicted over one long pause."""
    config = {"resilience": {"fault_injection": {
        "enabled": True,
        # worker op-loop counting starts after init: the first snapshot
        # op below is traversal 1
        "faults": [{"site": "replica.hang", "times": 1,
                    "args": {"duration_ms": 700}}],
    }}}
    replica = _stub_worker_replica(
        "hung", config=config, rpc_timeout=0.1, rpc_retries=0
    )
    replica.start()
    try:
        snap = replica.load_snapshot()  # snapshot op triggers the stall
        assert snap.get("unresponsive") is True
        assert snap["failed"] is False and snap["alive"] is False
        time.sleep(1.0)  # the stall passes; the worker answers again
        snap = replica.load_snapshot()
        assert snap["alive"] and not snap.get("unresponsive")
    finally:
        replica.shutdown()


def test_zombie_subprocess_hang_engine_restarted_and_rerouted():
    """End to end over real processes: a worker whose ENGINE wedges
    (accepts work, never finishes it) is zombie-detected from its frozen
    completion counters, drained-then-restarted, and its request
    re-routes to the survivor."""
    r0 = _stub_worker_replica("0", stub={"hang": True})
    r1 = _stub_worker_replica("1")
    router = FleetRouter(
        [r0, r1], monitor_interval=0.01, zombie_secs=0.4,
        zombie_restart_budget=2, placement="round_robin",
    ).start()
    try:
        req = router.submit([50], max_new_tokens=2)  # round-robin: r0
        assert req.replica_id == "0"
        assert req.result(120.0) == _expected_answer([50], 2)
        assert req.replica_id == "1" and req.reroutes == 1
        snap = router.metrics.snapshot()
        assert snap["fleet/zombie_restarts"] == 1
        assert router.evicted_ids == set()
    finally:
        router.shutdown()
