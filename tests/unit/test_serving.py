"""Fleet serving tier tests (deepspeed_tpu/serving/, docs/serving.md):
placement determinism, prefix affinity, token-bucket admission, drain
steering, rolling-restart exactly-once + bitwise parity, failed-replica
eviction/re-route, and the worker RPC protocol."""

import queue
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference import RequestRejected
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.serving import (
    FleetOverloaded,
    FleetRouter,
    LeastLoaded,
    PrefixAffinity,
    RateLimited,
    RoundRobin,
    TokenBucket,
)
from deepspeed_tpu.serving.replica import ReplicaBase
from deepspeed_tpu.serving.router import _histogram_quantile
from deepspeed_tpu.serving.worker import WorkerServer

VOCAB = 97


# ---------------------------------------------------------------------------
# stub replicas: the router's contract without engines (fast paths)
# ---------------------------------------------------------------------------
_IDLE_SNAP = {
    "queue_depth": 0, "queue_capacity": 8, "active_slots": 0,
    "free_slots": 2, "num_slots": 2, "health": 0,
    "mean_prefill_ms": 1.0, "mean_decode_ms": 1.0, "requests_shed": 0.0,
    "restarts_used": 0, "driving": True, "stopped": False,
    "driver_failed": False, "alive": True, "failed": False,
}


class StubHandle:
    def __init__(self, prompt_tokens):
        self.prompt_tokens = list(prompt_tokens)
        self.tokens = []
        self.finish_reason = None
        self.first_token_at = None
        self._done = threading.Event()

    @property
    def done(self):
        return self._done.is_set()

    def _finish(self, tokens, reason):
        self.tokens = list(tokens)
        self.finish_reason = reason
        self.first_token_at = time.monotonic()
        self._done.set()


class StubReplica(ReplicaBase):
    """Scripted replica: canned snapshot, optional auto-finish or
    rejection, explicit failure injection."""

    def __init__(self, replica_id, snapshot=None, autofinish=None,
                 reject_with=None):
        super().__init__(replica_id)
        self.snap = dict(_IDLE_SNAP, **(snapshot or {}))
        self.autofinish = autofinish  # tokens to finish with, or None
        self.reject_with = reject_with
        self.handles = []
        self.failed = False
        self.drained = False
        self.shutdowns = 0
        self.restarts = 0

    def start(self):
        return self

    def submit(self, prompt_tokens, **kwargs):
        if self.reject_with is not None:
            raise self.reject_with
        handle = StubHandle(prompt_tokens)
        self.handles.append(handle)
        if self.autofinish is not None:
            handle._finish(self.autofinish, "max_new_tokens")
        return handle

    def load_snapshot(self):
        snap = dict(self.snap)
        snap["failed"] = self.failed
        snap["alive"] = snap["alive"] and not self.failed
        return snap

    def drain(self):
        self.drained = True

    def restart(self):
        self.restarts += 1
        self.failed = False
        return self

    def shutdown(self):
        self.shutdowns += 1


def _stub_router(replicas, **kw):
    kw.setdefault("monitor_interval", 0.001)
    return FleetRouter(replicas, **kw).start()


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------
def test_least_loaded_placement_deterministic():
    """Given FIXED load snapshots the policy's choice is a pure function:
    min(queue_depth + active_slots), ties to the earliest candidate."""
    policy = LeastLoaded()
    candidates = [
        ("0", dict(_IDLE_SNAP, queue_depth=3, active_slots=1)),
        ("1", dict(_IDLE_SNAP, queue_depth=0, active_slots=2)),
        ("2", dict(_IDLE_SNAP, queue_depth=1, active_slots=0)),
    ]
    for _ in range(5):
        assert policy.choose(candidates, [1, 2, 3]) == "2"
    # tie (load 2 vs load 2): earliest candidate wins
    tied = [
        ("a", dict(_IDLE_SNAP, queue_depth=1, active_slots=1)),
        ("b", dict(_IDLE_SNAP, queue_depth=0, active_slots=2)),
    ]
    assert LeastLoaded().choose(tied, []) == "a"


def test_round_robin_cycles_candidates():
    policy = RoundRobin()
    candidates = [("0", dict(_IDLE_SNAP)), ("1", dict(_IDLE_SNAP))]
    picks = [policy.choose(candidates, []) for _ in range(4)]
    assert picks == ["0", "1", "0", "1"]


def test_prefix_affinity_hits_and_forgets():
    """Identical prompt prefixes stick to the first-serving replica even
    when load says otherwise; forget() re-pins after an eviction."""
    policy = PrefixAffinity(prefix_tokens=4)
    heavy0 = [
        ("0", dict(_IDLE_SNAP, queue_depth=9)),
        ("1", dict(_IDLE_SNAP, queue_depth=0)),
    ]
    prefix = [7, 7, 7, 7]
    first = policy.choose(heavy0, prefix + [1])
    assert first == "1" and policy.last_hit is False  # least-loaded pick
    # same prefix, different tail, replica 1 now the HEAVY one: sticky
    heavy1 = [
        ("0", dict(_IDLE_SNAP, queue_depth=0)),
        ("1", dict(_IDLE_SNAP, queue_depth=9)),
    ]
    assert policy.choose(heavy1, prefix + [2]) == "1"
    assert policy.last_hit is True
    # a DIFFERENT prefix follows load as usual
    assert policy.choose(heavy1, [5, 5, 5, 5, 3]) == "0"
    assert policy.last_hit is False
    policy.forget("1")
    assert policy.choose(heavy1, prefix + [3]) == "0"
    assert policy.last_hit is False


def test_prefix_affinity_skips_sticky_replica_out_of_kv_pages():
    """A sticky replica whose snapshot reports an exhausted KV page pool
    is skipped for the placement (it would only bounce the request off
    its typed 'capacity' rejection) and the affinity entry re-pins."""
    policy = PrefixAffinity(prefix_tokens=4)
    prefix = [7, 7, 7, 7]
    both = [
        ("0", dict(_IDLE_SNAP, queue_depth=9, kv_blocks_free=8)),
        ("1", dict(_IDLE_SNAP, queue_depth=0, kv_blocks_free=8)),
    ]
    assert policy.choose(both, prefix + [1]) == "1"  # pins to 1
    starved = [
        ("0", dict(_IDLE_SNAP, queue_depth=0, kv_blocks_free=8)),
        ("1", dict(_IDLE_SNAP, queue_depth=9, kv_blocks_free=0)),
    ]
    # sticky replica 1 is out of pages: fall through to least-loaded
    assert policy.choose(starved, prefix + [2]) == "0"
    assert policy.last_hit is False
    # the entry moved with the traffic: replica 0 is the new sticky
    assert policy.choose(starved, prefix + [3]) == "0"
    assert policy.last_hit is True
    # snapshots WITHOUT the field (contiguous replicas) keep stickiness
    legacy = PrefixAffinity(prefix_tokens=4)
    assert legacy.choose(both, prefix + [1]) == "1"
    heavy1 = [
        ("0", dict(_IDLE_SNAP, queue_depth=0)),
        ("1", dict(_IDLE_SNAP, queue_depth=9)),
    ]
    assert legacy.choose(heavy1, prefix + [2]) == "1"
    assert legacy.last_hit is True


def test_router_mirrors_replica_prefix_cache_gauges():
    """Paged replicas' prefix_hit_rate / kv_blocks_free land on the
    per-replica fleet gauges and aggregate into fleet/prefix_hit_rate."""
    a = StubReplica("0", snapshot={
        "prefix_hits": 3, "prefix_misses": 1, "prefix_hit_rate": 0.75,
        "kv_blocks_free": 5, "kv_blocks_total": 8, "kv_blocks_used": 3,
    }, autofinish=[1])
    b = StubReplica("1", autofinish=[2])  # contiguous: no kv fields
    router = _stub_router([a, b])
    try:
        router.refresh_telemetry()
        snap = router.metrics.snapshot()
        assert snap["fleet/replica0/prefix_hit_rate"] == 0.75
        assert snap["fleet/replica0/kv_blocks_free"] == 5
        assert "fleet/replica1/prefix_hit_rate" not in snap
        assert snap["fleet/prefix_hit_rate"] == 0.75
    finally:
        router.shutdown()


def test_router_prefix_affinity_counts_hits():
    a = StubReplica("0", autofinish=[1])
    b = StubReplica("1", autofinish=[2])
    router = _stub_router([a, b], placement="prefix_affinity",
                          affinity_prefix_tokens=4)
    try:
        prefix = [9, 9, 9, 9]
        r1 = router.submit(prefix + [1], max_new_tokens=1)
        r2 = router.submit(prefix + [2], max_new_tokens=1)
        r1.result(2.0), r2.result(2.0)
        assert r1.replica_id == r2.replica_id
        assert router.metrics.snapshot()["fleet/affinity_hits"] == 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# admission: rate limits + priority shedding
# ---------------------------------------------------------------------------
def test_token_bucket_burst_and_refill():
    clock = [0.0]
    bucket = TokenBucket(rate=2.0, burst=2, clock=lambda: clock[0])
    assert bucket.try_acquire() and bucket.try_acquire()
    assert not bucket.try_acquire()  # burst spent, no time passed
    clock[0] += 0.5  # refills one token at 2/s
    assert bucket.try_acquire()
    assert not bucket.try_acquire()
    clock[0] += 10.0  # refill clamps at burst
    assert bucket.try_acquire() and bucket.try_acquire()
    assert not bucket.try_acquire()


def test_rate_limit_rejects_per_tenant_with_reason_code():
    clock = [0.0]
    a = StubReplica("0", autofinish=[1])
    router = _stub_router(
        [a], rate_limit=(1.0, 1), clock=lambda: clock[0],
        per_tenant_limits={"gold": {"requests_per_sec": 100.0, "burst": 3}},
    )
    try:
        router.submit([1, 2], tenant="free", max_new_tokens=1)
        with pytest.raises(RateLimited) as exc:
            router.submit([1, 2], tenant="free", max_new_tokens=1)
        assert exc.value.reason == "rate_limit"
        assert isinstance(exc.value, RequestRejected)  # one except clause
        # an over-limit tenant never touches a replica queue
        assert len(a.handles) == 1
        # other tenants have their own bucket
        for _ in range(3):
            router.submit([1, 2], tenant="gold", max_new_tokens=1)
        snap = router.metrics.snapshot()
        assert snap["fleet/requests_rate_limited"] == 1
        assert snap["fleet/requests_rejected"] == 1
        assert snap["fleet/requests_routed"] == 4
        # the bucket refills with the (injected) clock
        clock[0] += 1.1
        router.submit([1, 2], tenant="free", max_new_tokens=1)
    finally:
        router.shutdown()


def test_fleet_pressure_sheds_priority_classes_only():
    full = StubReplica(
        "0", snapshot={"queue_depth": 7, "queue_capacity": 8},
        autofinish=[1],
    )
    router = _stub_router([full], shed_queue_ratio=0.75)
    try:
        with pytest.raises(FleetOverloaded) as exc:
            router.submit([1], priority=1, max_new_tokens=1)
        assert exc.value.reason == "overload"
        router.submit([1], priority=0, max_new_tokens=1)  # never shed here
    finally:
        router.shutdown()


def test_draining_fleet_rejects_with_reason():
    router = _stub_router([StubReplica("0", autofinish=[1])])
    try:
        router.drain_fleet()
        with pytest.raises(RequestRejected) as exc:
            router.submit([1], max_new_tokens=1)
        assert exc.value.reason == "draining"
    finally:
        router.shutdown()


def test_unmeetable_deadline_rejected_at_router_door():
    """A deadline below even the fastest candidate's observed prefill is
    rejected at the ROUTER (reason "deadline") — it never burns a
    replica queue slot on a guaranteed miss."""
    slow = StubReplica("0", snapshot={"mean_prefill_ms": 50.0},
                       autofinish=[1])
    router = _stub_router([slow])
    try:
        with pytest.raises(RequestRejected) as exc:
            router.submit([1, 2], max_new_tokens=1, deadline_secs=0.01)
        assert exc.value.reason == "deadline"
        assert len(slow.handles) == 0
        # a meetable deadline passes the gate and places normally
        req = router.submit([1, 2], max_new_tokens=1, deadline_secs=5.0)
        assert req.result(2.0) == [1]
    finally:
        router.shutdown()


def test_affinity_hit_not_counted_when_sticky_replica_rejects():
    """The sticky replica rejecting at its door is NOT an affinity hit:
    the request actually lands elsewhere via fallback."""
    a = StubReplica("0", autofinish=[1])
    b = StubReplica("1", autofinish=[2])
    router = _stub_router([a, b], placement="prefix_affinity",
                          affinity_prefix_tokens=4)
    try:
        prefix = [3, 3, 3, 3]
        first = router.submit(prefix + [1], max_new_tokens=1)
        first.result(2.0)
        sticky = router._replicas[first.replica_id]
        other = b if sticky is a else a
        sticky.reject_with = RequestRejected("full", reason="overload")
        second = router.submit(prefix + [2], max_new_tokens=1)
        assert second.result(2.0) == (other.autofinish)
        assert second.replica_id == other.replica_id
        assert router.metrics.snapshot()["fleet/affinity_hits"] == 0
    finally:
        router.shutdown()


def test_all_replicas_rejecting_is_fleet_overloaded():
    rej = RequestRejected("queue full", reason="overload")
    router = _stub_router([
        StubReplica("0", reject_with=rej),
        StubReplica("1", reject_with=rej),
    ])
    try:
        with pytest.raises(FleetOverloaded):
            router.submit([1], max_new_tokens=1)
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# failure handling: eviction + re-route
# ---------------------------------------------------------------------------
def test_evicted_replica_requests_reroute_exactly_once():
    """A replica that dies under its requests is evicted; each of its
    requests is re-placed on a survivor and finishes exactly once."""
    flaky = StubReplica("0")          # least loaded: takes the request
    backup = StubReplica("1", snapshot={"queue_depth": 5}, autofinish=[42])
    router = _stub_router([flaky, backup], max_reroutes=2)
    try:
        req = router.submit([1, 2, 3], max_new_tokens=1)
        assert req.replica_id == "0"
        # the replica crashes past its restart budget: its scheduler
        # fail-finishes the in-flight request, the snapshot reports failed
        flaky.failed = True
        flaky.handles[0]._finish([], "error")
        assert req.result(5.0) == [42]
        assert req.replica_id == "1"
        assert req.reroutes == 1
        assert req.finish_reason == "max_new_tokens"
        deadline = time.monotonic() + 5.0
        while ("0" not in router.evicted_ids
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert router.evicted_ids == {"0"}
        assert flaky.shutdowns == 1
        router.refresh_telemetry()
        snap = router.metrics.snapshot()
        assert snap["fleet/replicas_evicted"] == 1
        assert snap["fleet/requests_rerouted"] == 1
        assert snap["fleet/requests_completed"] == 1
        assert snap["fleet/replicas_total"] == 1
    finally:
        router.shutdown()


def test_reroute_charges_elapsed_deadline_time():
    """A re-routed request carries its REMAINING end-to-end deadline to
    the new replica (the clock does not restart), and one that expired
    while its replica died finishes "deadline" instead of getting a
    fresh full-budget generation elsewhere."""
    flaky = StubReplica("0")
    backup = StubReplica("1", snapshot={"queue_depth": 5}, autofinish=[7])
    router = _stub_router([flaky, backup], max_reroutes=2)
    try:
        req = router.submit([1, 2], max_new_tokens=1, deadline_secs=30.0)
        flaky.failed = True
        flaky.handles[0]._finish([], "error")
        assert req.result(5.0) == [7]
        carried = backup.handles[0]
        # the backup saw a reduced budget, not the original 30s
        assert req.kwargs["deadline_secs"] < 30.0
        assert carried.prompt_tokens == [1, 2]

        # expired-while-dying: terminal "deadline", no re-placement
        router2 = _stub_router(
            [StubReplica("a"), StubReplica("b", autofinish=[9])],
            max_reroutes=2,
        )
        try:
            req2 = router2.submit([3], max_new_tokens=1,
                                  deadline_secs=0.01)
            replica_a = router2._replicas["a"]
            time.sleep(0.05)  # deadline passes while the replica dies
            replica_a.failed = True
            for handle in replica_a.handles:
                handle._finish([], "error")
            deadline = time.monotonic() + 5.0
            while not req2.done and time.monotonic() < deadline:
                time.sleep(0.005)
            assert req2.finish_reason == "deadline"
            assert req2.result(0) == []  # partial-answer contract
            assert router2._replicas["b"].handles == []  # never re-placed
        finally:
            router2.shutdown()
    finally:
        router.shutdown()


def test_reroute_budget_exhausted_fails_loudly():
    dead_a = StubReplica("0")
    dead_b = StubReplica("1")
    router = _stub_router([dead_a, dead_b], max_reroutes=1)
    try:
        req = router.submit([1], max_new_tokens=1)
        for replica in (dead_a, dead_b):
            replica.failed = True
            for handle in replica.handles:
                if not handle.done:
                    handle._finish([], "error")
        # the re-routed copy lands on the OTHER dead replica and dies too;
        # budget 1 means the router must now fail the fleet request
        deadline = time.monotonic() + 5.0
        while not req.done and time.monotonic() < deadline:
            for replica in (dead_a, dead_b):
                for handle in replica.handles:
                    if not handle.done:
                        handle._finish([], "error")
            time.sleep(0.005)
        assert req.done
        assert req.finish_reason == "error"
        with pytest.raises(RuntimeError, match="re-route"):
            req.result(0)
    finally:
        router.shutdown()


def test_histogram_quantile_interpolates():
    from deepspeed_tpu.telemetry.registry import Histogram

    hist = Histogram("t", buckets=(10.0, 20.0, 40.0))
    assert _histogram_quantile(hist, 0.5) == 0.0  # empty
    for v in (5, 5, 15, 15, 35, 35, 35, 35):
        hist.observe(v)
    p50 = _histogram_quantile(hist, 0.5)
    p99 = _histogram_quantile(hist, 0.99)
    assert 10.0 <= p50 <= 20.0
    assert 20.0 < p99 <= 40.0


# ---------------------------------------------------------------------------
# worker RPC protocol (in-process: no spawn, no jax)
# ---------------------------------------------------------------------------
class _ChanIn:
    """Blocking line source driving WorkerServer.run like a real pipe."""

    def __init__(self):
        self._q = queue.Queue()

    def send(self, line):
        self._q.put(line + "\n")

    def close(self):
        self._q.put(None)

    def __iter__(self):
        while True:
            line = self._q.get()
            if line is None:
                return
            yield line


class _ChanOut:
    """Collects protocol lines; tests wait on arrival."""

    def __init__(self):
        self.lines = []
        self._cond = threading.Condition()

    def write(self, text):
        with self._cond:
            self.lines.append(text.strip())
            self._cond.notify_all()

    def flush(self):
        pass

    def wait_for(self, predicate, timeout=5.0):
        import json

        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                for raw in self.lines:
                    msg = json.loads(raw)
                    if predicate(msg):
                        return msg
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"no matching line in {self.lines}")
                self._cond.wait(remaining)


class _FakeWorkerEngine:
    """The InferenceEngine surface WorkerServer drives, scripted."""

    def __init__(self):
        self.scheduler = self
        self.drained = False
        self.closed = False

    def serve_forever(self):
        pass

    def submit(self, prompt, max_new_tokens=32, **kwargs):
        if prompt == ["reject"]:
            raise RequestRejected("full", reason="overload")
        if not prompt:
            raise ValueError("empty prompt")
        handle = StubHandle(prompt)
        handle._finish([t + 1 for t in prompt][:max_new_tokens],
                       "max_new_tokens")
        return handle

    def load_snapshot(self):
        return dict(_IDLE_SNAP)

    def drain(self):
        self.drained = True

    def close(self):
        self.closed = True


def test_worker_server_protocol_roundtrip():
    import json

    stdin, stdout = _ChanIn(), _ChanOut()
    engine = _FakeWorkerEngine()
    server = WorkerServer(stdin, stdout, lambda spec: engine,
                          poll_interval=0.001)
    thread = threading.Thread(target=server.run, daemon=True)
    thread.start()
    stdin.send(json.dumps({"op": "init", "spec": {}}))
    stdout.wait_for(lambda m: m.get("event") == "ready")
    stdin.send(json.dumps({
        "op": "submit", "id": 1, "prompt": [10, 20], "max_new_tokens": 2,
    }))
    stdout.wait_for(
        lambda m: m.get("event") == "reply" and m.get("id") == 1
        and "error" not in m
    )
    fin = stdout.wait_for(
        lambda m: m.get("event") == "finished" and m.get("id") == 1
    )
    assert fin["tokens"] == [11, 21]
    assert fin["reason"] == "max_new_tokens"
    # a rejected submit carries the machine-readable reason through
    stdin.send(json.dumps(
        {"op": "submit", "id": 2, "prompt": ["reject"]}
    ))
    rej = stdout.wait_for(
        lambda m: m.get("event") == "reply" and m.get("id") == 2
    )
    assert rej["reason"] == "overload" and rej["error"]
    stdin.send(json.dumps({"op": "snapshot", "id": 3}))
    snap = stdout.wait_for(
        lambda m: m.get("event") == "reply" and m.get("id") == 3
    )
    assert snap["snapshot"]["queue_depth"] == 0
    stdin.send(json.dumps({"op": "drain"}))
    stdin.send(json.dumps({"op": "shutdown"}))
    thread.join(5.0)
    assert not thread.is_alive()
    assert engine.drained and engine.closed


# ---------------------------------------------------------------------------
# real engines: drain steering, rolling restart, parity
# ---------------------------------------------------------------------------
def _small_model(seed=0):
    cfg = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        dropout=0.0, use_flash=False,
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jnp.asarray(
        np.random.default_rng(seed).integers(0, VOCAB, (1, 8)), jnp.int32
    )
    params = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        ids0, ids0,
    )["params"]
    return cfg, model, params


_ENGINE_BLOCK = {
    "max_batch_slots": 2, "max_seq_len": 48, "prefill_len": 16,
    "sampling": {"greedy": True},
}


def _factory(model, params):
    def build():
        return deepspeed_tpu.init_inference(
            model=model, model_parameters=params,
            config={"inference": dict(_ENGINE_BLOCK)},
        )

    return build


def _prompts(n, seed=1):
    rng = np.random.default_rng(seed)
    return [
        [int(t) for t in rng.integers(0, VOCAB, k)]
        for k in rng.integers(5, 12, n)
    ]


def test_fleet_drain_steers_traffic_while_inflight_finishes():
    cfg, model, params = _small_model()
    router = deepspeed_tpu.init_fleet(
        engine_factory=_factory(model, params),
        config={"serving": {"replicas": 2}},
    )
    try:
        long_req = router.submit(_prompts(1)[0], max_new_tokens=24)
        target = long_req.replica_id
        other = next(r for r in router.replica_ids if r != target)
        router.drain(target)
        after = [router.submit(p, max_new_tokens=4) for p in _prompts(3, 7)]
        for req in after:
            req.result(60.0)
            assert req.replica_id == other  # steered away from the drain
        assert long_req.result(60.0)  # in-flight work still finished
        assert long_req.replica_id == target
        assert long_req.reroutes == 0
    finally:
        router.shutdown()


def test_rolling_restart_exactly_once_and_bitwise_parity():
    """The acceptance pin: a rolling restart across 2 replicas under
    concurrent traffic finishes every submitted request exactly once
    (none lost, none duplicated), keeps routable capacity at/above the
    configured floor, and greedy outputs stay bitwise-identical to a
    single-replica run of the same prompts."""
    cfg, model, params = _small_model()
    prompts = _prompts(4, seed=3)

    single = _factory(model, params)()
    reference = single.generate(prompts, max_new_tokens=8)
    single.close()

    router = deepspeed_tpu.init_fleet(
        engine_factory=_factory(model, params),
        config={"serving": {"replicas": 2, "capacity_floor": 0.5}},
    )
    floor_breached = []
    available = router.metrics.gauge("fleet/replicas_available")
    try:
        results = {}
        errors = []

        def pump(i):
            try:
                req = router.submit(prompts[i % 4], max_new_tokens=8)
                results.setdefault(i, []).append(req.result(120.0))
            except Exception as e:  # pragma: no cover - failure detail
                errors.append((i, e))

        threads = [
            threading.Thread(target=pump, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()

        watching = threading.Event()

        def watch_floor():
            while not watching.is_set():
                if available.value < 1.0:
                    floor_breached.append(available.value)
                time.sleep(0.002)

        watcher = threading.Thread(target=watch_floor, daemon=True)
        watcher.start()
        router.rolling_restart(wait_timeout=60.0)
        for t in threads:
            t.join(120.0)
        watching.set()
        watcher.join(5.0)

        assert not errors, errors
        assert len(results) == 8  # every submission answered...
        for i, answers in results.items():
            assert len(answers) == 1  # ...exactly once
            assert answers[0] == reference[i % 4]  # ...bitwise greedy
        assert sum(router.routed_counts.values()) >= 8
        snap = router.metrics.snapshot()
        assert snap["fleet/replica_restarts"] == 2
        assert snap["fleet/requests_completed"] == 8
        assert snap["fleet/ttft_ms/count"] == 8
        # capacity floor held for the whole restart (1 of 2 replicas)
        assert not floor_breached, floor_breached
    finally:
        router.shutdown()


def test_rolling_restart_refuses_impossible_floor():
    router = _stub_router([StubReplica("0", autofinish=[1])],
                          capacity_floor=0.9)
    try:
        with pytest.raises(RuntimeError, match="capacity floor"):
            router.rolling_restart()
    finally:
        router.shutdown()


def test_subprocess_replica_end_to_end_greedy_parity():
    """One worker subprocess serving the tiniest GPT-2: submissions cross
    the pipe, answers match an in-process engine of the same seed
    bitwise, and shutdown reaps the process."""
    from deepspeed_tpu.serving import SubprocessReplica

    model_kw = {
        "vocab_size": 64, "n_positions": 32, "n_embd": 16, "n_layer": 1,
        "n_head": 2, "use_flash": False,
    }
    engine_block = {
        "max_batch_slots": 2, "max_seq_len": 24, "prefill_len": 8,
        "sampling": {"greedy": True},
    }
    spec = {
        "model": model_kw, "init_seed": 0,
        "config": {"inference": engine_block},
    }
    rng = np.random.default_rng(5)
    prompts = [[int(t) for t in rng.integers(0, 64, 6)] for _ in range(2)]

    from deepspeed_tpu.serving.worker import build_engine_from_spec

    local = build_engine_from_spec(spec)
    reference = local.generate(prompts, max_new_tokens=5)
    local.close()

    replica = SubprocessReplica("sub0", spec, start_timeout=240.0)
    replica.start()
    try:
        snap = replica.load_snapshot()
        assert snap["alive"] and not snap["failed"]
        handles = [
            replica.submit(p, max_new_tokens=5) for p in prompts
        ]
        outs = [h.result(120.0) for h in handles]
        assert outs == reference
        assert all(h.finish_reason == "max_new_tokens" for h in handles)
    finally:
        replica.shutdown()
    assert not replica.alive and not replica.failed
