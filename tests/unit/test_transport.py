"""Socket replica transport tests (deepspeed_tpu/serving/transport.py +
node.py, docs/serving.md "Networked fleet"): the frame codec's
corruption detection, the replica RPC end to end over a REAL loopback
listener, idempotent-RPC retry and late-reply discard mirroring the
pipe-based pins, reconnect-with-resume under injected resets, lease /
failover semantics, the protocol-version handshake on both transports,
and the graceful-EOF satellite for the subprocess backend.

Everything here is jax-free: the node hosts worker.py's StubWorkerEngine
(answers are a pure function of the prompt, so exactly-once is
assertable bitwise) and listens on an ephemeral loopback port."""

import time

import pytest

from deepspeed_tpu.inference.scheduler import RequestRejected
from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec
from deepspeed_tpu.serving import (
    FleetRouter,
    ReplicaProtocolError,
    ReplicaRPCError,
    SocketReplica,
    SubprocessReplica,
)
from deepspeed_tpu.serving.node import NodeServer
from deepspeed_tpu.serving.transport import (
    FrameError,
    corrupt_frame,
    decode_frame,
    encode_frame,
)
from deepspeed_tpu.telemetry.registry import suppressed_errors_snapshot


def _expected_answer(prompt, max_new):
    """StubWorkerEngine's deterministic answer (worker.py)."""
    base = prompt[-1] if prompt else 0
    return [(base + i + 1) % 1000 for i in range(max_new)]


def _node(replicas=("r0",), *, delay=0.02, hang=False, config=None,
          node_id="n0", lease_secs=5.0, resume_grace_secs=5.0):
    spec = {
        "node_id": node_id,
        "replicas": {
            name: {"stub": {"delay_secs": delay, "hang": hang}}
            for name in replicas
        },
        "lease_secs": lease_secs,
        "resume_grace_secs": resume_grace_secs,
    }
    if config is not None:
        spec["config"] = config
    return NodeServer(spec)


def _replica(node, name="r0", *, rid=None, faults=None, rpc_timeout=2.0,
             rpc_retries=1, reconnect_attempts=3, **kw):
    host, port = node.address
    return SocketReplica(
        rid or f"{node.node_id}:{name}", (host, port), remote_name=name,
        rpc_timeout=rpc_timeout, rpc_retries=rpc_retries,
        rpc_backoff_secs=0.01, reconnect_backoff_secs=0.02,
        reconnect_attempts=reconnect_attempts, fault_injector=faults, **kw,
    )


# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------
def test_frame_codec_roundtrip_and_bare_json():
    msg = {"op": "submit", "id": 3, "prompt": [1, 2], "kwargs": {}}
    assert decode_frame(encode_frame(msg)) == msg
    # the pipe protocol's bare newline-JSON frames stay valid
    assert decode_frame(b'{"event": "ready"}\n') == {"event": "ready"}


@pytest.mark.parametrize("line", [
    b"",                                # empty
    b"12 {\"a\": 1}",                   # declared 12, payload is 8 bytes
    b"notjson at all",                  # neither form
    b"999999999999 {}",                 # length past the ceiling
    b"7 [1,2,3]",                       # JSON but not an object
])
def test_frame_codec_rejects_torn_and_garbled(line):
    with pytest.raises(FrameError):
        decode_frame(line)


def test_corrupt_frame_mutation_is_undecodable_single_line():
    data = corrupt_frame(encode_frame({"op": "snapshot", "id": 1}))
    assert data.endswith(b"\n") and data.count(b"\n") == 1
    with pytest.raises(FrameError):
        decode_frame(data)


# ---------------------------------------------------------------------------
# end to end over a real loopback listener
# ---------------------------------------------------------------------------
def test_socket_replica_end_to_end_stub():
    node = _node(("r0", "r1"))
    node.start()
    replica = _replica(node, "r0")
    try:
        replica.start()
        assert replica.node_id == "n0"
        snap = replica.load_snapshot()
        assert snap["alive"] and not snap["failed"]
        reqs = [replica.submit([10 + i], max_new_tokens=3)
                for i in range(4)]
        for i, req in enumerate(reqs):
            assert req.result(30.0) == _expected_answer([10 + i], 3)
            assert req.finish_reason == "max_new_tokens"
            assert req.first_token_at is not None
        replica.drain()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if replica.load_snapshot().get("stopped"):
                break
            time.sleep(0.01)
        with pytest.raises(RequestRejected) as exc_info:
            # the drained stub rejects at its door: the typed reason
            # rides the REPLY (a healthy answer, not a transport error)
            replica.submit([1], max_new_tokens=2)
        assert not isinstance(exc_info.value, ReplicaRPCError)
    finally:
        replica.shutdown()
        node.shutdown()


def test_socket_submit_rejection_reason_crosses_the_wire():
    node = _node()
    node.start()
    replica = _replica(node)
    try:
        replica.start()
        replica.drain()
        time.sleep(0.05)
        with pytest.raises(RequestRejected) as exc_info:
            replica.submit([5], max_new_tokens=2)
        assert exc_info.value.reason == "draining"
        assert not isinstance(exc_info.value, ReplicaRPCError)
    finally:
        replica.shutdown()
        node.shutdown()


def test_deadline_rides_the_frame_header():
    """_frame_submit lifts deadline_secs out of the kwargs into dl_ms;
    the node re-derives the engine deadline from the header — the wire
    carries the budget, not an opaque kwarg."""
    seen = {}

    def recording_builder(spec):
        from deepspeed_tpu.serving.worker import build_engine_from_spec

        engine = build_engine_from_spec(spec)
        orig = engine.submit

        def submit(prompt, **kw):
            seen.update(kw)
            kw.pop("deadline_secs", None)  # the stub takes no deadline
            return orig(prompt, **kw)

        engine.submit = submit
        return engine

    node = NodeServer(
        {"node_id": "n0", "replicas": {"r0": {"stub": {}}}},
        engine_builder=recording_builder,
    )
    node.start()
    replica = _replica(node)
    try:
        replica.start()
        req = replica.submit([3], max_new_tokens=2, deadline_secs=30.0)
        assert req.result(10.0) == _expected_answer([3], 2)
        assert "deadline_secs" in seen
        # the node saw the re-derived remaining budget, not the raw kwarg
        assert 0 < seen["deadline_secs"] <= 30.0
    finally:
        replica.shutdown()
        node.shutdown()


# ---------------------------------------------------------------------------
# chaos sites over the real socket (the pipe pins' socket mirrors)
# ---------------------------------------------------------------------------
def test_idempotent_rpc_retry_absorbs_corrupt_frame():
    """frame.corrupt garbles one snapshot op on the wire: the node
    counts-and-drops it, the client's reply timeout fires, and the
    idempotent retry re-asks — the caller never notices."""
    # client _send traversals: hello is raw, so the first snapshot op is
    # traversal 1
    faults = FaultInjector(
        [FaultSpec("frame.corrupt", times=1, seed=0)], seed=0
    )
    node = _node()
    node.start()
    replica = _replica(node, faults=faults, rpc_timeout=0.3, rpc_retries=2)
    try:
        replica.start()
        snap = replica.load_snapshot()
        assert snap["alive"] and not snap.get("unresponsive")
        assert replica.rpc_retries_used >= 1
        assert faults.injected["frame.corrupt"] == 1
    finally:
        replica.shutdown()
        node.shutdown()


def test_partitioned_frame_lost_not_duplicated():
    """net.partition black-holes one submit frame (the connection looks
    alive): the submit times out with a typed transport error — and the
    op provably never reached the node, so a router falling through to
    another replica cannot double-generate."""
    faults = FaultInjector(
        [FaultSpec("net.partition", times=1, seed=0)], seed=0
    )
    node = _node(delay=0.0)
    node.start()
    replica = _replica(node, faults=faults, rpc_timeout=0.3, rpc_retries=0)
    try:
        replica.start()
        with pytest.raises(ReplicaRPCError):
            replica.submit([5], max_new_tokens=2)  # the ack never comes
        assert faults.injected["net.partition"] == 1
        # nothing leaked: no reply waiters, no outstanding request, and
        # the node never admitted anything (the frame died on the wire)
        with replica._reply_cond:
            assert replica._replies == {} and replica._expected == set()
        assert replica._outstanding == {}
        assert node.engines["r0"].load_snapshot()["active_slots"] == 0
        # the transport is fine; the next submit sails through
        req = replica.submit([7], max_new_tokens=2)
        assert req.result(10.0) == _expected_answer([7], 2)
    finally:
        replica.shutdown()
        node.shutdown()


def test_late_reply_after_timeout_discarded_over_socket():
    """The pipe-based late-reply pin against a real listener: a node-side
    op stall (replica.hang) delays the snapshot ack past the client
    timeout; the landing reply is dropped by the reader — it neither
    leaks in _replies nor matches a later rpc_id."""
    node = _node(config={"resilience": {"fault_injection": {
        "enabled": True,
        # node op traversals: the first snapshot op below is 1
        "faults": [{"site": "replica.hang", "times": 1,
                    "args": {"duration_ms": 700}}],
    }}})
    node.start()
    replica = _replica(node, rpc_timeout=0.2, rpc_retries=0)
    try:
        replica.start()
        snap = replica.load_snapshot()  # times out -> unresponsive verdict
        assert snap.get("unresponsive") is True
        assert snap["failed"] is False
        time.sleep(1.0)  # the stalled ack lands (and is discarded)
        with replica._reply_cond:
            assert replica._replies == {}
            assert replica._expected == set()
        snap = replica.load_snapshot()
        assert snap["alive"] and not snap.get("unresponsive")
        req = replica.submit([3], max_new_tokens=2)
        assert req.result(30.0) == _expected_answer([3], 2)
    finally:
        replica.shutdown()
        node.shutdown()


def test_reconnect_with_resume_completes_inflight_without_reroute():
    """The tentpole's resume pin: a peer RST mid-generation reconnects
    and re-attaches to the node's in-flight session — the request
    completes on the ORIGINAL node (zero re-routes burned), the
    reconnect is counted, and the replica never reads failed."""
    # sends: (1) the post-start snapshot, (2) submit, (3) the snapshot
    # that eats the injected RST while the stub still generates
    faults = FaultInjector(
        [FaultSpec("conn.reset", after=2, times=1, seed=0)], seed=0
    )
    node = _node(delay=0.6)
    node.start()
    replica = _replica(node, faults=faults)
    try:
        replica.start()
        assert replica.load_snapshot()["alive"]
        req = replica.submit([7], max_new_tokens=4)
        replica.load_snapshot()  # hits the armed RST, drops the socket
        assert faults.injected["conn.reset"] == 1
        out = req.result(30.0)
        assert out == _expected_answer([7], 4)
        assert req.finish_reason == "max_new_tokens"
        assert replica._net_reconnects.value >= 1
        assert replica.failed is False and replica.alive
    finally:
        replica.shutdown()
        node.shutdown()


def test_reconnect_exhausted_fails_replica_and_inflight():
    """A node that truly died: the reconnect budget exhausts, the
    replica flips failed (eviction/breaker food — never before), and
    every in-flight request fail-finishes for re-route."""
    node = _node(hang=True)
    node.start()
    replica = _replica(node, reconnect_attempts=2)
    replica.start()
    try:
        req = replica.submit([5], max_new_tokens=2)  # hangs on the node
        assert not replica.failed
        node.shutdown()
        # poll for BOTH: the reader marks the replica failed before its
        # EOF sweep finishes the orphans — observing one does not yet
        # imply the other on a loaded box
        deadline = time.monotonic() + 15.0
        while (
            not (replica.failed and req.done)
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        assert replica.failed is True
        assert replica.alive is False
        assert req.done and req.finish_reason == "error"
        snap = replica.load_snapshot()
        assert snap["failed"] is True and snap["alive"] is False
    finally:
        replica.shutdown()


def test_accept_drop_absorbed_by_connect_retry():
    """accept.drop: the node accepts then slams the door once; the
    client's connect retry absorbs it and start() succeeds."""
    node = _node(config={"resilience": {"fault_injection": {
        "enabled": True,
        "faults": [{"site": "accept.drop", "times": 1}],
    }}})
    node.start()
    replica = _replica(node)
    try:
        replica.start()
        assert node._faults.injected["accept.drop"] == 1
        req = replica.submit([9], max_new_tokens=2)
        assert req.result(10.0) == _expected_answer([9], 2)
    finally:
        replica.shutdown()
        node.shutdown()


def test_session_reaped_past_resume_grace_requests_reroutable():
    """A client gone past resume_grace_secs loses its node session: the
    in-flight requests cancel (slots free), and the returning client's
    welcome lists nothing — its reconcile fail-finishes the orphans for
    re-route (exactly-once: the node cancelled them, so the answer is
    re-derived exactly once elsewhere)."""
    node = _node(delay=30.0, resume_grace_secs=0.3, lease_secs=0.2)
    node.start()
    replica = _replica(node)
    try:
        replica.start()
        req = replica.submit([5], max_new_tokens=2)
        assert not req.done
        # kill the connection WITHOUT shutdown (an unplanned vanish) and
        # block the reconnect path long enough for the grace to lapse
        replica._hb_stop.set()
        # well past the 0.3s grace: the reap must win even when a loaded
        # CI box starves the reaper thread for a few hundred ms — a
        # reconnect that lands first re-binds the OLD session and the
        # orphan never fail-finishes
        replica._reconnect_backoff = 1.5
        replica._abort_connection("test: simulated client vanish")
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with node._sessions_lock:
                if not node._sessions:
                    break
            time.sleep(0.02)
        with node._sessions_lock:
            assert not node._sessions, "session outlived its grace"
        # the engine slot frees at the next step boundary after the
        # reap's cancel — poll for it: on a loaded box the stub driver
        # can lag behind the reaper by more than one scheduler pass
        slot_deadline = time.monotonic() + 10.0
        while (
            node.engines["r0"].load_snapshot()["active_slots"] != 0
            and time.monotonic() < slot_deadline
        ):
            time.sleep(0.02)
        assert node.engines["r0"].load_snapshot()["active_slots"] == 0
        # the client reconnects into a FRESH session; the welcome's
        # empty inflight list fail-finishes the orphan for re-route
        assert req.result(15.0) is not None or True
        assert req.finish_reason == "error"
    finally:
        replica.shutdown()
        node.shutdown()


def test_socket_fleet_router_integration_exactly_once():
    """Two single-replica nodes behind a FleetRouter: a black-holed
    submit on one replica feeds its breaker and falls through to the
    other node — every request answered exactly once, bitwise."""
    node_a, node_b = _node(node_id="na"), _node(node_id="nb")
    node_a.start()
    node_b.start()
    # sends on replica A: start-refresh snapshot (1), candidates
    # snapshot (2), first submit (3)
    faults = FaultInjector(
        [FaultSpec("net.partition", after=2, times=1, seed=0)], seed=0
    )
    ra = _replica(node_a, rid="na:r0", faults=faults, rpc_timeout=0.5)
    rb = _replica(node_b, rid="nb:r0", rpc_timeout=0.5)
    router = FleetRouter(
        [ra, rb], monitor_interval=0.005, telemetry_refresh_secs=3600.0,
        breaker_failure_threshold=1, breaker_backoff_secs=0.25,
    ).start()
    try:
        reqs = [router.submit([20 + i], max_new_tokens=3)
                for i in range(4)]
        for i, req in enumerate(reqs):
            assert req.result(60.0) == _expected_answer([20 + i], 3)
            assert req.finish_reason == "max_new_tokens"
        assert faults.injected["net.partition"] == 1
        snap = router.metrics.snapshot()
        assert snap["fleet/breaker_opens"] >= 1
        assert snap["fleet/requests_rerouted"] == 0
        assert snap["fleet/requests_completed"] == 4
    finally:
        router.shutdown()
        node_a.shutdown()
        node_b.shutdown()


# ---------------------------------------------------------------------------
# protocol-version handshake (both transports)
# ---------------------------------------------------------------------------
def test_socket_protocol_mismatch_fail_fasts_with_both_versions(
        monkeypatch):
    import deepspeed_tpu.serving.node as node_mod

    monkeypatch.setattr(node_mod, "RPC_PROTOCOL_VERSION", 99)
    node = _node()
    node.start()
    replica = _replica(node)
    try:
        with pytest.raises(ReplicaProtocolError) as exc_info:
            replica.start()
        msg = str(exc_info.value)
        assert "v1" in msg and "v99" in msg
    finally:
        replica.shutdown()
        node.shutdown()


def test_subprocess_protocol_mismatch_fail_fasts_typed(monkeypatch):
    """Satellite pin: a version-skewed WORKER fail-fasts at start() with
    a typed error naming both versions — never one undecodable line at a
    time until the breaker opens. (The parent's version is patched; the
    real worker subprocess answers the genuine v1.)"""
    import deepspeed_tpu.serving.replica as replica_mod

    monkeypatch.setattr(replica_mod, "RPC_PROTOCOL_VERSION", 2)
    replica = SubprocessReplica(
        "skewed", {"stub": {}}, start_timeout=90.0, rpc_timeout=2.0,
    )
    with pytest.raises(ReplicaProtocolError) as exc_info:
        replica.start()
    msg = str(exc_info.value)
    assert "v2" in msg and "v1" in msg
    assert replica.alive is False


# ---------------------------------------------------------------------------
# graceful-EOF satellite (subprocess backend)
# ---------------------------------------------------------------------------
def test_requested_shutdown_reads_graceful_not_breaker_food():
    """Satellite pin: a REQUESTED shutdown's pipe EOF finishes orphans
    "cancelled" quietly — it neither logs a died-in-flight warning nor
    feeds the transport-death diagnostics that breaker streaks ride."""
    replica = SubprocessReplica(
        "clean", {"stub": {"hang": True}}, start_timeout=90.0,
        rpc_timeout=2.0,
    )
    replica.start()
    req = replica.submit([5], max_new_tokens=2)  # never finishes
    before = suppressed_errors_snapshot().get(
        "internal/suppressed_errors/serving.transport_died_inflight", 0
    )
    replica.shutdown()
    assert req.done and req.finish_reason == "cancelled"
    after = suppressed_errors_snapshot().get(
        "internal/suppressed_errors/serving.transport_died_inflight", 0
    )
    assert after == before, "clean shutdown counted as a transport death"
    # and the replica reads shut-down, not failed
    assert replica.failed is False
    snap = replica.load_snapshot()
    assert snap["alive"] is False and snap["failed"] is False


def test_unrequested_worker_death_still_counts_and_fails():
    """The inverse guard: a worker killed WITHOUT being asked keeps the
    loud path — orphans fail-finish "error" and the death is counted."""
    replica = SubprocessReplica(
        "killed", {"stub": {"hang": True}}, start_timeout=90.0,
        rpc_timeout=2.0,
    )
    replica.start()
    req = replica.submit([5], max_new_tokens=2)
    before = suppressed_errors_snapshot().get(
        "internal/suppressed_errors/serving.transport_died_inflight", 0
    )
    replica._proc.kill()
    # generous: a loaded CI box can take a while to deliver the EOF
    deadline = time.monotonic() + 30.0
    while not req.done and time.monotonic() < deadline:
        time.sleep(0.01)
    assert req.done and req.finish_reason == "error"
    after = suppressed_errors_snapshot().get(
        "internal/suppressed_errors/serving.transport_died_inflight", 0
    )
    assert after == before + 1
    assert replica.failed is True
    replica.shutdown()
