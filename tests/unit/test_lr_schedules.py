"""LR schedule tests (coverage analog of the reference's schedule params in
tests + CLI plumbing behavior)."""

import argparse

import pytest

from deepspeed_tpu.runtime.lr_schedules import (
    LRRangeTest,
    OneCycle,
    WarmupLR,
    WarmupDecayLR,
    add_tuning_arguments,
    build_lr_scheduler,
    get_config_from_args,
)


def test_warmup_lr_ramp():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10)
    lrs = [s.step() for _ in range(15)]
    assert lrs[0] < lrs[5] < lrs[9]
    assert all(lr == pytest.approx(0.1) for lr in lrs[10:])


def test_warmup_decay_lr():
    s = WarmupDecayLR(
        warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=5, total_num_steps=15
    )
    lrs = [s.step() for _ in range(16)]
    assert max(lrs) == pytest.approx(0.1)
    assert lrs[-1] == pytest.approx(0.0, abs=1e-9)


def test_lr_range_test_continuous_and_staircase():
    cont = LRRangeTest(
        lr_range_test_min_lr=0.01, lr_range_test_step_size=5, lr_range_test_step_rate=1.0
    )
    vals = [cont.step() for _ in range(10)]
    assert vals[-1] > vals[0]
    stair = LRRangeTest(
        lr_range_test_min_lr=0.01,
        lr_range_test_step_size=5,
        lr_range_test_step_rate=1.0,
        lr_range_test_staircase=True,
    )
    svals = [stair.step() for _ in range(10)]
    assert svals[0] == svals[4]  # flat within an interval
    assert svals[5] > svals[4]  # jumps at the boundary


def test_one_cycle_shape():
    s = OneCycle(
        cycle_min_lr=0.1, cycle_max_lr=1.0, cycle_first_step_size=10,
        decay_lr_rate=0.5, decay_step_size=1,
    )
    lrs = [s.step() for _ in range(30)]
    peak = max(lrs)
    assert peak == pytest.approx(1.0, rel=0.15)
    assert lrs[20] == pytest.approx(0.1, rel=0.15)  # back to min after cycle
    assert lrs[-1] < 0.1  # decay tail below min


def test_one_cycle_staircase():
    s = OneCycle(
        cycle_min_lr=0.0, cycle_max_lr=1.0, cycle_first_step_size=10,
        cycle_first_stair_count=2,
    )
    lrs = [s.step() for _ in range(10)]
    # only the stair values 0 and 0.5 appear during the up phase
    assert set(round(v, 6) for v in lrs[:10]) == {0.0, 0.5}


def test_one_cycle_momentum():
    s = OneCycle(
        cycle_min_lr=0.0, cycle_max_lr=1.0, cycle_first_step_size=10,
        cycle_min_mom=0.8, cycle_max_mom=0.9,
    )
    s.step()
    assert s.get_mom() == pytest.approx(0.9, rel=0.05)
    for _ in range(9):
        s.step()
    assert s.get_mom() == pytest.approx(0.8, rel=0.05)


def test_state_dict_roundtrip():
    s = WarmupLR(warmup_max_lr=0.1, warmup_num_steps=10)
    for _ in range(5):
        s.step()
    sd = s.state_dict()
    s2 = WarmupLR(warmup_max_lr=0.1, warmup_num_steps=10)
    s2.load_state_dict(sd)
    assert s2.get_lr() == s.get_lr()


def test_build_by_name():
    s = build_lr_scheduler("WarmupLR", {"warmup_max_lr": 0.5})
    assert isinstance(s, WarmupLR)
    with pytest.raises(ValueError):
        build_lr_scheduler("Nope", {})


def test_cli_args_roundtrip():
    parser = add_tuning_arguments(argparse.ArgumentParser())
    args = parser.parse_args(
        ["--lr_schedule", "LRRangeTest", "--lr_range_test_min_lr", "0.007",
         "--lr_range_test_step_size", "42"]
    )
    cfg, err = get_config_from_args(args)
    assert err is None
    assert cfg["type"] == "LRRangeTest"
    assert cfg["params"]["lr_range_test_min_lr"] == 0.007
    assert cfg["params"]["lr_range_test_step_size"] == 42
    sched = build_lr_scheduler(cfg["type"], cfg["params"])
    assert sched.min_lr == 0.007


def test_cli_args_invalid_schedule():
    parser = add_tuning_arguments(argparse.ArgumentParser())
    args = parser.parse_args(["--lr_schedule", "Bogus"])
    cfg, err = get_config_from_args(args)
    assert cfg is None and "not a valid" in err
