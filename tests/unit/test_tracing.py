"""Distributed request tracing + flight recorder (telemetry/tracing.py).

Covers the ISSUE-9 acceptance surface: span/context mechanics, ring-buffer
overwrite order, sampling at 0.0/1.0, the zero-overhead-when-disabled pin,
RPC trace propagation through the worker protocol, subprocess replica
span adoption, scheduler phase spans with globally-unique request ids,
flight dumps on decode-driver crashes, histogram exemplars, and a real
in-process fleet request reconstructing end-to-end from one trace file.
"""

import json
import os
import queue
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.inference.scheduler import (  # noqa: E402
    ContinuousBatchingScheduler,
    RequestRejected,
)
from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel  # noqa: E402
from deepspeed_tpu.serving.replica import SubprocessReplica  # noqa: E402
from deepspeed_tpu.serving.worker import WorkerServer  # noqa: E402
from deepspeed_tpu.telemetry.exporters import (  # noqa: E402
    PrometheusTextfileExporter,
)
from deepspeed_tpu.telemetry.manager import Telemetry  # noqa: E402
from deepspeed_tpu.telemetry.registry import (  # noqa: E402
    Histogram,
    MetricsRegistry,
)
from deepspeed_tpu.telemetry.tracing import (  # noqa: E402
    NOOP_TRACER,
    NoopTracer,
    SpanTracer,
    TraceContext,
    build_tracer,
    load_chrome_trace,
)


# ---------------------------------------------------------------------------
# core span mechanics
# ---------------------------------------------------------------------------
def test_record_parents_under_context():
    t = SpanTracer(ring_events=16)
    root = t.child_of(None)
    child = t.record("child", 1.0, 2.0, ctx=root)
    assert child["trace_id"] == root.trace_id
    assert child["parent_id"] == root.span_id
    assert child["dur_ms"] == pytest.approx(1000.0)
    # explicit span_id override: how a pre-allocated container span
    # closes retroactively
    closed = t.record(
        "root", 0.5, 3.0,
        ctx=TraceContext(root.trace_id, None, root.sampled),
        span_id=root.span_id,
    )
    assert closed["span_id"] == root.span_id
    assert closed["parent_id"] is None
    assert closed["trace_id"] == child["trace_id"]


def test_span_context_manager_records_block():
    t = SpanTracer(ring_events=16)
    with t.span("blk", attrs={"a": 1}) as h:
        h.set_attr("b", 2)
    (span,) = t.flight_snapshot()
    assert span["name"] == "blk"
    assert span["attrs"] == {"a": 1, "b": 2}


def test_wire_roundtrip():
    ctx = TraceContext("t" * 16, "s" * 16, sampled=False)
    wire = ctx.to_wire()
    json.dumps(wire)  # must be RPC-safe
    back = TraceContext.from_wire(wire)
    assert (back.trace_id, back.span_id, back.sampled) == (
        ctx.trace_id, ctx.span_id, False,
    )
    assert TraceContext.from_wire(None) is None
    assert TraceContext.from_wire(ctx) is ctx
    assert TraceContext.from_wire({"junk": 1}) is None


def test_ring_overwrite_order():
    t = SpanTracer(ring_events=4, sample_rate=0.0)
    for i in range(10):
        t.record(f"s{i}", 0.0, 1.0)
    names = [s["name"] for s in t.flight_snapshot()]
    assert names == ["s6", "s7", "s8", "s9"]  # oldest evicted, order kept


def test_sampling_zero_keeps_ring_but_exports_nothing(tmp_path):
    path = str(tmp_path / "trace.json")
    t = SpanTracer(sample_rate=0.0, ring_events=32, export_path=path)
    for i in range(5):
        t.record(f"s{i}", 0.0, 1.0)
    t.close()
    # the always-on flight recorder saw everything...
    assert len(t.flight_snapshot()) == 5
    # ...but nothing was sampled for export: no trace file at all
    assert not os.path.exists(path)


def test_sampling_one_exports_everything(tmp_path):
    path = str(tmp_path / "trace.json")
    t = SpanTracer(sample_rate=1.0, ring_events=32, export_path=path)
    for i in range(5):
        t.record(f"s{i}", float(i), float(i) + 1.0)
    t.close()
    events = load_chrome_trace(path)
    assert [e["name"] for e in events] == [f"s{i}" for i in range(5)]
    # Perfetto-loadable complete events with the ids in args
    assert all(e["ph"] == "X" and e["args"]["trace_id"] for e in events)


def test_flight_dump_writes_complete_chrome_trace(tmp_path):
    t = SpanTracer(ring_events=8, dump_dir=str(tmp_path))
    ctx = t.child_of(None)
    t.record("a", 0.0, 1.0, ctx=ctx)
    t.event("boom", attrs={"reason": "test"}, ctx=ctx)
    path = t.dump_flight("unit_test", extra={"k": "v"})
    payload = json.load(open(path))
    names = [e["name"] for e in payload["traceEvents"]]
    assert names == ["a", "boom"]
    assert payload["metadata"]["reason"] == "unit_test"
    assert payload["metadata"]["k"] == "v"
    assert "suppressed_errors" in payload["metadata"]
    # a second dump gets its own file
    assert t.dump_flight("unit_test") != path


def test_ingest_adopts_foreign_pids_only():
    t = SpanTracer(ring_events=8)
    mine = t.record("local", 0.0, 1.0)
    foreign = dict(mine, pid=mine["pid"] + 1, name="remote")
    assert t.ingest([mine, foreign, "junk", None]) == 1
    names = [s["name"] for s in t.flight_snapshot()]
    assert names == ["local", "remote"]


# ---------------------------------------------------------------------------
# the zero-overhead-when-disabled pin
# ---------------------------------------------------------------------------
def test_noop_tracer_is_zero_overhead_passthrough():
    assert NOOP_TRACER.enabled is False
    # one shared allocation-free context manager, pinned by identity
    cm = NOOP_TRACER.span("anything")
    assert cm is NOOP_TRACER.span("something else")
    with cm as h:
        h.set_attr("ignored", 1)
    assert NOOP_TRACER.record("x", 0.0, 1.0) is None
    assert NOOP_TRACER.child_of(None) is None
    assert NOOP_TRACER.dump_flight("nope") is None
    assert NOOP_TRACER.flight_snapshot() == []


def test_disabled_config_builds_the_noop_singleton(tmp_path):
    cfg = deepspeed_tpu.DeepSpeedConfig(
        None, param_dict={"train_batch_size": 1}, world_size=1
    )
    assert build_tracer(cfg) is NOOP_TRACER
    # a disabled Telemetry facade carries the same singleton
    assert Telemetry(enabled=False).tracer is NOOP_TRACER


def test_build_tracer_from_armed_config(tmp_path):
    cfg = deepspeed_tpu.DeepSpeedConfig(
        None,
        param_dict={
            "train_batch_size": 1,
            "telemetry": {
                "enabled": True,
                "output_path": str(tmp_path),
                "tracing": {"enabled": True, "sample_rate": 0.5,
                            "ring_events": 99},
            },
        },
        world_size=1,
    )
    t = build_tracer(cfg)
    assert isinstance(t, SpanTracer)
    assert t.sample_rate == 0.5 and t.ring_events == 99
    assert t.export_path.endswith("trace.json")
    t.close()


# ---------------------------------------------------------------------------
# histogram exemplars: the metric -> trace link
# ---------------------------------------------------------------------------
def test_histogram_exemplars_record_per_bucket():
    h = Histogram("x/lat", buckets=(10.0, 100.0))
    h.observe(5.0)  # untraced: no exemplar
    h.observe(50.0, trace_id="abc")
    h.observe(500.0, trace_id="inf-bucket")
    assert 0 not in h.exemplars
    assert h.exemplars[1][:2] == (50.0, "abc")
    assert h.exemplars[2][:2] == (500.0, "inf-bucket")


def test_prometheus_exporter_emits_exemplar_comment_lines(tmp_path):
    reg = MetricsRegistry()
    h = reg.histogram("infer/ttft_ms", buckets=(10.0, 100.0))
    h.observe(50.0, trace_id="deadbeef")
    path = str(tmp_path / "m.prom")
    PrometheusTextfileExporter(path).export(reg.collect(), step=1)
    text = open(path).read()
    assert (
        '# EXEMPLAR infer_ttft_ms_bucket{le="100.0"} '
        '{trace_id="deadbeef"} 50.0'
    ) in text
    # every SAMPLE line stays valid classic 0.0.4 text format: the
    # trace link rides full-line comments only (a trailing-token tail
    # would make the node-exporter textfile collector reject the file)
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert len(line.rsplit(" ", 1)) == 2, line
    assert 'infer_ttft_ms_bucket{le="10.0"} 0\n' in text


# ---------------------------------------------------------------------------
# scheduler integration: phase spans, unique request ids, crash dump
# ---------------------------------------------------------------------------
class _StubEngine:
    """The minimal engine surface the scheduler drives."""

    prefill_len = 16

    def __init__(self, crash_on_decode=False):
        self.crash_on_decode = crash_on_decode

    def prefill_request(self, slot, prompt_tokens, temperature):
        return 100 + slot

    def prefill_trace_attrs(self, slot):
        return {"prefix_hit": False, "prompt_tokens": 3}

    def decode_tokens(self, active):
        if self.crash_on_decode:
            raise RuntimeError("injected decode crash")
        return [7 for _ in active]


def _scheduler(engine, tracer=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("queue_depth", 8)
    kw.setdefault("queue_timeout", 0.1)
    kw.setdefault("eos_token_id", None)
    kw.setdefault("temperature", 0.0)
    return ContinuousBatchingScheduler(
        engine, registry=MetricsRegistry(), tracer=tracer, **kw
    )


def test_scheduler_phase_spans_and_exemplars():
    tracer = SpanTracer(ring_events=64)
    sched = _scheduler(_StubEngine(), tracer=tracer)
    sched.set_id_prefix("r7")
    req = sched.submit([1, 2, 3], max_new_tokens=2)
    assert req.request_id.startswith("rr7-")
    sched.run_until_idle()
    assert req.result(1.0)
    names = {s["name"] for s in req.trace_spans}
    assert {"sched.queue", "sched.prefill", "sched.request"} <= names
    by_name = {s["name"]: s for s in req.trace_spans}
    # one connected trace: phases parent to the request's container span
    assert by_name["sched.queue"]["parent_id"] == req.trace_ctx.span_id
    assert by_name["sched.prefill"]["parent_id"] == req.trace_ctx.span_id
    assert by_name["sched.request"]["span_id"] == req.trace_ctx.span_id
    assert len({s["trace_id"] for s in req.trace_spans}) == 1
    assert by_name["sched.prefill"]["attrs"]["prefix_hit"] is False
    assert by_name["sched.request"]["attrs"]["request_id"] == req.request_id
    assert by_name["sched.request"]["attrs"]["finish_reason"] == (
        "max_new_tokens"
    )
    # decode-step batch spans landed in the ring under the driver trace
    ring_names = [s["name"] for s in tracer.flight_snapshot()]
    assert "sched.decode_step" in ring_names
    # TTFT exemplar links the histogram bucket to this trace
    ttft = sched._registry.histogram("infer/ttft_ms")
    assert any(
        e[1] == req.trace_ctx.trace_id for e in ttft.exemplars.values()
    )


def test_scheduler_joins_caller_trace_context():
    tracer = SpanTracer(ring_events=64)
    sched = _scheduler(_StubEngine(), tracer=tracer)
    parent = tracer.child_of(None)
    req = sched.submit(
        [1, 2, 3], max_new_tokens=1, trace_ctx=parent.to_wire()
    )
    sched.run_until_idle()
    req.result(1.0)
    assert req.trace_ctx.trace_id == parent.trace_id
    by_name = {s["name"]: s for s in req.trace_spans}
    # the request's container span parents to the caller's span
    assert by_name["sched.request"]["parent_id"] == parent.span_id


def test_scheduler_disabled_tracing_is_inert():
    sched = _scheduler(_StubEngine())  # no tracer -> NOOP passthrough
    assert isinstance(sched._tracer, NoopTracer)
    req = sched.submit([1, 2, 3], max_new_tokens=1)
    sched.run_until_idle()
    req.result(1.0)
    assert req.trace_ctx is None
    assert req.trace_spans == []


def test_request_ids_globally_unique_across_instances():
    a = _scheduler(_StubEngine())
    b = _scheduler(_StubEngine())  # same replica id, e.g. post-restart
    a.set_id_prefix("0")
    b.set_id_prefix("0")
    ids = set()
    for sched in (a, b):
        for _ in range(3):
            ids.add(sched.submit([1], max_new_tokens=1).request_id)
        sched.run_until_idle()
    assert len(ids) == 6  # the per-instance token keeps restarts distinct
    assert all(i.startswith("r0-") for i in ids)


def test_decode_crash_dumps_flight_recorder(tmp_path):
    tracer = SpanTracer(ring_events=64, dump_dir=str(tmp_path))
    sched = _scheduler(
        _StubEngine(crash_on_decode=True), tracer=tracer,
        driver_restart_budget=0,
    )
    sched.submit([1, 2, 3], max_new_tokens=4)
    with pytest.raises(RuntimeError):
        sched.run_until_idle()
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("flight-")]
    assert len(dumps) == 1
    payload = json.load(open(tmp_path / dumps[0]))
    assert payload["metadata"]["reason"] == "decode_driver_crash"
    # the ring carried the request's phase spans into the dump
    assert any(
        e["name"] == "sched.prefill" for e in payload["traceEvents"]
    )


# ---------------------------------------------------------------------------
# worker RPC propagation (in-process protocol, no spawn)
# ---------------------------------------------------------------------------
class _ChanIn:
    def __init__(self):
        self._q = queue.Queue()

    def send(self, line):
        self._q.put(line + "\n")

    def __iter__(self):
        while True:
            line = self._q.get()
            if line is None:
                return
            yield line


class _ChanOut:
    def __init__(self):
        self.lines = []
        self._cond = threading.Condition()

    def write(self, text):
        with self._cond:
            self.lines.append(text.strip())
            self._cond.notify_all()

    def flush(self):
        pass

    def wait_for(self, predicate, timeout=5.0):
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                for raw in self.lines:
                    msg = json.loads(raw)
                    if predicate(msg):
                        return msg
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"no matching line in {self.lines}")
                self._cond.wait(remaining)


class _TracedHandle:
    def __init__(self, spans):
        self.tokens = [1, 2]
        self.finish_reason = "max_new_tokens"
        self.first_token_at = time.monotonic()
        self.done = True
        self.trace_spans = spans


class _TracedWorkerEngine:
    """Records the kwargs the worker hands to submit (the trace_ctx wire
    dict must survive the RPC) and hands back pre-traced requests."""

    def __init__(self):
        self.scheduler = self
        self.submit_kwargs = None
        self.replica_prefix = None

    def serve_forever(self):
        pass

    def set_id_prefix(self, replica_id):
        self.replica_prefix = replica_id

    def drain(self):
        pass

    def close(self):
        pass

    def submit(self, prompt, max_new_tokens=32, **kwargs):
        self.submit_kwargs = dict(kwargs)
        ctx = kwargs.get("trace_ctx") or {}
        spans = [{
            "name": "sched.request", "trace_id": ctx.get("trace_id"),
            "span_id": "w" * 16, "parent_id": ctx.get("span_id"),
            "ts": time.time(), "dur_ms": 1.0, "pid": os.getpid() + 1,
            "tid": 0, "attrs": {}, "sampled": True,
        }]
        return _TracedHandle(spans)


def test_worker_rpc_carries_trace_context_and_returns_spans():
    stdin, stdout = _ChanIn(), _ChanOut()
    engine = _TracedWorkerEngine()
    server = WorkerServer(stdin, stdout, lambda spec: engine,
                          poll_interval=0.001)
    thread = threading.Thread(target=server.run, daemon=True)
    thread.start()
    stdin.send(json.dumps({
        "op": "init", "spec": {"replica_id": "3"},
    }))
    stdout.wait_for(lambda m: m.get("event") == "ready")
    # the init spec's replica id reached the scheduler's id prefix
    assert engine.replica_prefix == "3"
    wire = {"trace_id": "t" * 16, "span_id": "p" * 16, "sampled": True}
    stdin.send(json.dumps({
        "op": "submit", "id": 1, "prompt": [5, 6],
        "max_new_tokens": 2, "kwargs": {"trace_ctx": wire},
    }))
    stdout.wait_for(
        lambda m: m.get("event") == "reply" and m.get("id") == 1
    )
    # the wire dict crossed the protocol untouched
    assert engine.submit_kwargs["trace_ctx"] == wire
    fin = stdout.wait_for(
        lambda m: m.get("event") == "finished" and m.get("id") == 1
    )
    # ...and the worker shipped its spans home with the answer,
    # parented to the router's wire context
    assert fin["spans"][0]["trace_id"] == wire["trace_id"]
    assert fin["spans"][0]["parent_id"] == wire["span_id"]
    stdin.send(json.dumps({"op": "shutdown"}))
    thread.join(5.0)
    assert not thread.is_alive()


def test_subprocess_replica_adopts_finished_spans():
    replica = SubprocessReplica("0", {})
    from deepspeed_tpu.serving.replica import RemoteRequest

    req = RemoteRequest(1, [1, 2], 4)
    replica._outstanding[1] = req
    spans = [{"name": "sched.request", "pid": os.getpid() + 1,
              "sampled": True}]
    replica._dispatch({
        "event": "finished", "id": 1, "tokens": [9],
        "reason": "max_new_tokens", "spans": spans,
    })
    assert req.done and req.trace_spans == spans


# ---------------------------------------------------------------------------
# end-to-end: one fleet request -> one connected trace in one file
# ---------------------------------------------------------------------------
VOCAB = 96


def _small_engine_factory():
    cfg = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        dropout=0.0, use_flash=False,
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jnp.asarray(
        np.random.default_rng(0).integers(0, VOCAB, (1, 8)), jnp.int32
    )
    params = model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        ids0, ids0,
    )["params"]

    def build():
        return deepspeed_tpu.init_inference(
            model=model, model_parameters=params,
            config={"inference": {
                "max_batch_slots": 2, "max_seq_len": 48,
                "prefill_len": 16, "sampling": {"greedy": True},
            }},
        )

    return build


def test_fleet_request_trace_connects_end_to_end(tmp_path):
    router = deepspeed_tpu.init_fleet(
        engine_factory=_small_engine_factory(),
        config={
            "serving": {"replicas": 1, "placement": "least_loaded"},
            "telemetry": {
                "enabled": True,
                "output_path": str(tmp_path),
                "job_name": "trace_e2e",
                "watchdog": {"enabled": False},
                "tracing": {"enabled": True, "sample_rate": 1.0},
            },
        },
    )
    try:
        fr = router.submit([3, 1, 4, 1, 5], max_new_tokens=4)
        assert len(fr.result(30.0)) == 4
        deadline = time.monotonic() + 5.0
        while router.outstanding_count and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        router.shutdown()
    events = load_chrome_trace(
        str(tmp_path / "trace_e2e" / "trace.json")
    )
    spans = {e["name"]: e["args"] for e in events}
    required = {"fleet.request", "router.admission", "router.place",
                "sched.request", "sched.queue", "sched.prefill"}
    assert required <= set(spans), sorted(spans)
    # ONE trace id end to end, router door to finish-reason
    tids = {e["args"]["trace_id"] for e in events
            if e["name"] in required}
    assert len(tids) == 1
    root = spans["fleet.request"]
    assert root["parent_id"] is None
    assert root["finish_reason"] == "max_new_tokens"
    # parent links reconstruct the chain: admission/place under the
    # root, scheduler phases under the replica's request span
    assert spans["router.admission"]["parent_id"] == root["span_id"]
    assert spans["router.place"]["parent_id"] == root["span_id"]
    assert spans["sched.request"]["parent_id"] == root["span_id"]
    assert spans["sched.queue"]["parent_id"] == (
        spans["sched.request"]["span_id"]
    )
    assert spans["sched.prefill"]["parent_id"] == (
        spans["sched.request"]["span_id"]
    )
    # replica-prefixed request id rides the trace as the root attr
    assert str(spans["sched.request"]["request_id"]).startswith("r0-")


def test_fleet_tracing_disabled_writes_no_trace_files(tmp_path):
    router = deepspeed_tpu.init_fleet(
        engine_factory=_small_engine_factory(),
        config={
            "serving": {"replicas": 1},
            "telemetry": {
                "enabled": True,
                "output_path": str(tmp_path),
                "job_name": "untraced",
                "watchdog": {"enabled": False},
            },
        },
    )
    try:
        assert router.tracer is NOOP_TRACER
        fr = router.submit([3, 1, 4], max_new_tokens=2)
        assert len(fr.result(30.0)) == 2
    finally:
        router.shutdown()
    leftovers = [
        f for f in os.listdir(tmp_path / "untraced")
        if "trace" in f or f.startswith("flight-")
    ]
    assert leftovers == []
