"""Durable control plane tests (deepspeed_tpu/serving/journal.py,
docs/serving.md "Control-plane durability"): the write-ahead segment
protocol (checksummed envelopes, atomic latest pointer, newest-valid
recovery over a full corruption matrix), the journal's mutation
ordering and bounded in-flight table, adoption planning against both
injected fakes and REAL loopback node sessions (bitwise prefix replay,
finished-while-dead delivery, forgotten-entry fail-finish), the
router's crash-recovery cycle end to end, and the door's resume
surface (SSE ``id:`` fields, ``Last-Event-ID`` replay, the
Idempotency-Key LRU, graceful restart).

Everything is jax-free: node-backed tests host worker.py's
StubWorkerEngine (answers are a pure function of the prompt, so
exactly-once and bitwise-resume are assertable), door tests drive a
host-side harness around the real ContinuousBatchingScheduler."""

import json
import os
import socket
import threading
import time

import pytest

from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.resilience import atomic_io
from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec
from deepspeed_tpu.serving import (
    Autoscaler,
    FleetJournal,
    FleetRouter,
    HTTPDoor,
    InProcessReplica,
    init_fleet,
    load_journal_state,
    plan_adoption,
)
from deepspeed_tpu.serving.journal import (
    JOURNAL_CORRUPT,
    JOURNAL_MISSING,
    JOURNAL_VALID,
    LATEST_FILE,
    RPC_ID_INCARNATION_BLOCK,
    list_segments,
    verify_segment,
)
from deepspeed_tpu.serving.node import NodeServer
from deepspeed_tpu.serving.transport import NodeControlClient, SocketReplica
from deepspeed_tpu.telemetry.registry import MetricsRegistry, wire_scalars


def _expected_answer(prompt, max_new):
    """StubWorkerEngine's deterministic answer (worker.py)."""
    base = prompt[-1] if prompt else 0
    return [(base + i + 1) % 1000 for i in range(max_new)]


def _node(replicas=("r0",), *, delay=0.02, token_delay=0.0,
          node_id="n0", lease_secs=5.0, resume_grace_secs=10.0):
    spec = {
        "node_id": node_id,
        "replicas": {
            name: {"stub": {
                "delay_secs": delay, "token_delay_secs": token_delay,
            }}
            for name in replicas
        },
        "lease_secs": lease_secs,
        "resume_grace_secs": resume_grace_secs,
    }
    return NodeServer(spec)


def _replica(node, name="r0", *, rid=None, **kw):
    host, port = node.address
    return SocketReplica(
        rid or f"{node.node_id}:{name}", (host, port), remote_name=name,
        rpc_timeout=2.0, rpc_retries=1, rpc_backoff_secs=0.01,
        reconnect_backoff_secs=0.02, reconnect_attempts=3, **kw,
    )


_SOCKET_KW = dict(
    rpc_timeout=2.0, rpc_retries=1, rpc_backoff_secs=0.01,
    reconnect_backoff_secs=0.02, reconnect_attempts=3,
)


def _crash_replica(replica):
    """Sever a socket replica the way a SIGKILLed router would: no bye
    frame, no reconnect — the node's session survives (disconnected)
    into its resume grace, exactly what a restarted router adopts."""
    replica._shutdown_requested = True
    replica._hb_stop.set()
    replica._abort_connection("simulated router crash")
    for t in (replica._heartbeat, replica._reader):
        if t is not None:
            t.join(5.0)


def _node_scalars(node, name="r0"):
    snap = NodeControlClient(node.address).metrics_snapshot()
    return wire_scalars(snap["replicas"][name])


# ---------------------------------------------------------------------------
# segment protocol: checksummed envelopes, the recovery walk
# ---------------------------------------------------------------------------
def test_segment_roundtrip_valid(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.set_brownout(True)
    name = list_segments(str(tmp_path))[0]
    verdict, payload, reason = verify_segment(str(tmp_path / name))
    assert verdict == JOURNAL_VALID and reason == "ok"
    assert payload == j.state()
    assert payload["brownout"] is True


def test_segment_payload_tamper_is_corrupt(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.set_brownout(False)
    name = list_segments(str(tmp_path))[0]
    path = tmp_path / name
    env = json.loads(path.read_bytes())
    env["payload"]["brownout"] = True  # flip a field, keep the old sha
    path.write_bytes(json.dumps(env).encode())
    verdict, payload, reason = verify_segment(str(path))
    assert verdict == JOURNAL_CORRUPT and payload is None
    assert "checksum" in reason


def test_segment_truncated_is_corrupt(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.set_brownout(True)
    name = list_segments(str(tmp_path))[0]
    path = str(tmp_path / name)
    atomic_io.torn_write_bytes(path, atomic_io.read_bytes(path), 0.5)
    verdict, payload, _reason = verify_segment(path)
    assert verdict == JOURNAL_CORRUPT and payload is None


def test_segment_format_version_mismatch_is_corrupt(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.set_brownout(True)
    name = list_segments(str(tmp_path))[0]
    path = tmp_path / name
    env = json.loads(path.read_bytes())
    env["format_version"] = 99
    path.write_bytes(json.dumps(env).encode())
    verdict, _payload, reason = verify_segment(str(path))
    assert verdict == JOURNAL_CORRUPT and "format_version" in reason


def test_segment_absent_is_missing(tmp_path):
    verdict, payload, _ = verify_segment(str(tmp_path / "journal-x.json"))
    assert verdict == JOURNAL_MISSING and payload is None


def test_list_segments_newest_first_ignores_strangers(tmp_path):
    for name in ("journal-00000002.json", "journal-00000010.json",
                 "notes.txt", "journal-abc.json", LATEST_FILE):
        (tmp_path / name).write_text("x")
    assert list_segments(str(tmp_path)) == [
        "journal-00000010.json", "journal-00000002.json",
    ]


def test_load_missing_directory(tmp_path):
    payload, info = load_journal_state(str(tmp_path / "never"))
    assert payload is None
    assert info == {"status": "missing", "segment": None, "corrupt": []}


def test_load_recovers_newest_and_counts(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.set_brownout(True)
    j.record_node("n0", ("127.0.0.1", 4242))
    reg = MetricsRegistry()
    payload, info = load_journal_state(str(tmp_path), registry=reg)
    assert info["status"] == "recovered" and info["corrupt"] == []
    assert payload["brownout"] is True
    assert payload["nodes"] == {"n0": ["127.0.0.1", 4242]}
    assert reg.counter("fleet/journal_recoveries").value == 1
    assert reg.counter("fleet/journal_corruptions").value == 0


def test_load_stale_latest_falls_back(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.set_brownout(True)
    j.set_brownout(False)
    newest = list_segments(str(tmp_path))[0]
    os.unlink(tmp_path / newest)  # latest now points at a ghost
    reg = MetricsRegistry()
    payload, info = load_journal_state(str(tmp_path), registry=reg)
    assert info["status"] == "recovered"
    assert LATEST_FILE in info["corrupt"]
    assert payload["brownout"] is True  # the surviving older snapshot
    assert reg.counter("fleet/journal_corruptions").value == 1


def test_load_torn_newest_falls_back_whole(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.record_adapter("fr", {"rank": 8})
    j.record_adapter("de", {"rank": 16})
    newest = list_segments(str(tmp_path))[0]
    path = str(tmp_path / newest)
    atomic_io.torn_write_bytes(path, atomic_io.read_bytes(path), 0.4)
    payload, info = load_journal_state(str(tmp_path))
    assert info["status"] == "recovered"
    assert info["corrupt"] == [newest]
    # the PREVIOUS snapshot adopted whole — never a half-adopt of the
    # torn one
    assert payload["adapters"] == {"fr": {"rank": 8}}


def test_load_all_corrupt_starts_cold_loudly(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.set_brownout(True)
    j.set_brownout(False)
    for name in list_segments(str(tmp_path)):
        (tmp_path / name).write_bytes(b"\x00 not json at all")
    reg = MetricsRegistry()
    payload, info = load_journal_state(str(tmp_path), registry=reg)
    assert payload is None and info["status"] == "cold"
    assert len(info["corrupt"]) == 2
    assert reg.counter("fleet/journal_corruptions").value == 2
    assert reg.counter("fleet/journal_recoveries").value == 0


def test_load_non_object_payload_is_corrupt(tmp_path):
    # a well-formed envelope whose payload is not a dict must not adopt
    (tmp_path / "journal-00000001.json").write_bytes(b'{"a": 1}')
    payload, info = load_journal_state(str(tmp_path))
    assert payload is None and info["status"] == "cold"


# ---------------------------------------------------------------------------
# FleetJournal: mutation ordering, bounds, incarnations
# ---------------------------------------------------------------------------
def test_every_mutation_is_durable_before_return(tmp_path):
    reg = MetricsRegistry()
    j = FleetJournal(tmp_path, fsync=False, registry=reg)
    mutations = [
        lambda: j.record_node("n0", ("127.0.0.1", 1000)),
        lambda: j.record_replica("n0:r0", node="n0",
                                 address=("127.0.0.1", 1000),
                                 remote_name="r0", client="c1", rpc_seq=3),
        lambda: j.record_adapter("fr", {"rank": 8}),
        lambda: j.set_brownout(True),
        lambda: j.set_autoscaler({"target": 2}),
        lambda: j.open_request(5, prompt=[1], tenant="t",
                               kwargs={"max_new_tokens": 4},
                               replica_id="n0:r0", rpc_id=7),
        lambda: j.move_request(5, replica_id="n0:r1", rpc_id=9, reroutes=1),
        lambda: j.close_request(5),
        lambda: j.forget_adapter("fr"),
        lambda: j.forget_replica("n0:r0"),
    ]
    for i, mutate in enumerate(mutations, start=1):
        mutate()
        # the newest on-disk segment is the post-mutation state: the
        # write happened BEFORE the mutator returned
        name = list_segments(str(tmp_path))[0]
        verdict, payload, _ = verify_segment(str(tmp_path / name))
        assert verdict == JOURNAL_VALID
        assert payload == j.state()
        assert atomic_io.read_text(j.latest_path()).strip() == name
    assert reg.counter("fleet/journal_writes").value == len(mutations)


def test_record_node_accepts_host_port_string(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.record_node("n0", "10.0.0.9:7001")
    assert j.state()["nodes"] == {"n0": ["10.0.0.9", 7001]}


def test_inflight_open_move_close_descriptor_shape(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.open_request(41, prompt=[7, 9], tenant="acme",
                   kwargs={"max_new_tokens": 8}, replica_id="a",
                   rpc_id=3, idempotency_key="k1", reroutes=0)
    st = j.state()
    assert st["request_seq"] == 41
    assert st["inflight"]["41"] == {
        "prompt": [7, 9], "tenant": "acme",
        "kwargs": {"max_new_tokens": 8}, "replica": "a", "rpc_id": 3,
        "idem": "k1", "deadline_unix": None, "reroutes": 0,
    }
    j.move_request(41, replica_id="b", rpc_id=11, reroutes=1)
    entry = j.state()["inflight"]["41"]
    assert (entry["replica"], entry["rpc_id"], entry["reroutes"]) == (
        "b", 11, 1,
    )
    j.close_request(41)
    assert j.state()["inflight"] == {}
    assert j.state()["request_seq"] == 41  # the high-water mark stays


def test_inflight_bound_evicts_oldest_counted(tmp_path):
    reg = MetricsRegistry()
    j = FleetJournal(tmp_path, fsync=False, max_inflight=2, registry=reg)
    for rid in (1, 2, 3):
        j.open_request(rid, prompt=[rid], tenant="t",
                       kwargs={}, replica_id="a", rpc_id=rid)
    assert sorted(j.state()["inflight"]) == ["2", "3"]
    assert reg.counter("fleet/journal_inflight_evicted").value == 1


def test_keep_segments_prunes_history(tmp_path):
    j = FleetJournal(tmp_path, fsync=False, keep_segments=2)
    for i in range(5):
        j.set_brownout(i % 2 == 0)
    names = list_segments(str(tmp_path))
    assert names == ["journal-00000005.json", "journal-00000004.json"]


def test_recovered_journal_bumps_incarnation_and_seq(tmp_path):
    j1 = FleetJournal(tmp_path, fsync=False)
    assert j1.incarnation == 1
    j1.set_brownout(True)
    j1.record_adapter("fr", {"rank": 4})
    state, info = load_journal_state(str(tmp_path))
    assert info["status"] == "recovered"
    j2 = FleetJournal(tmp_path, fsync=False, state=state)
    assert j2.incarnation == 2
    assert j2.state()["brownout"] is True
    assert j2.state()["adapters"] == {"fr": {"rank": 4}}
    j2.set_brownout(False)
    # the sequence continues PAST the previous life's segments — history
    # stays inspectable, never overwritten
    assert j2.seq == 3
    assert list_segments(str(tmp_path))[0] == "journal-00000003.json"


def test_journal_torn_fault_site_recovers_previous(tmp_path):
    faults = FaultInjector(
        [FaultSpec("journal.torn", after=1, times=1,
                   args={"keep_fraction": 0.3}, seed=0)], seed=0,
    )
    j = FleetJournal(tmp_path, fsync=False, fault_injector=faults)
    j.set_brownout(True)    # commit 1: clean
    j.set_brownout(False)   # commit 2: torn mid-write
    assert faults.injected["journal.torn"] == 1
    torn = "journal-00000002.json"
    assert verify_segment(str(tmp_path / torn))[0] == JOURNAL_CORRUPT
    payload, info = load_journal_state(str(tmp_path))
    assert info["status"] == "recovered" and torn in info["corrupt"]
    assert payload["brownout"] is True  # the pre-torn snapshot


def test_autoscaler_and_brownout_roundtrip(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    j.set_autoscaler({"target": 3, "last_scale_unix": 123.0})
    j.set_brownout(True)
    state, _ = load_journal_state(str(tmp_path))
    assert state["autoscaler"] == {"target": 3, "last_scale_unix": 123.0}
    assert state["brownout"] is True
    j.set_autoscaler(None)
    assert j.state()["autoscaler"] is None


# ---------------------------------------------------------------------------
# plan_adoption: the decision matrix (injected transport fakes)
# ---------------------------------------------------------------------------
def _fake_ctl(rosters, dials=None):
    """A NodeControlClient stand-in: ``rosters`` maps address tuples to
    replica-name lists; a missing address refuses the dial."""
    class _Ctl:
        def __init__(self, address, **_kw):
            self.address = tuple(address)
            if dials is not None:
                dials.append(self.address)

        def node_info(self):
            roster = rosters.get(self.address)
            if roster is None:
                raise OSError("connection refused")
            return {"replicas": list(roster)}
    return _Ctl


class _FakeReplica:
    def __init__(self, replica_id, address, *, remote_name=None,
                 registry=None, fault_injector=None, **kw):
        self.replica_id = replica_id
        self.address = tuple(address)
        self.remote_name = remote_name
        self.kw = kw
        self.adopt = None

    def adopt_session(self, client, *, rpc_base, entries=()):
        self.adopt = {
            "client": client, "rpc_base": rpc_base,
            "entries": list(entries),
        }
        return self


def _journal_state(**over):
    state = {
        "format_version": 1, "seq": 4, "incarnation": 2,
        "written_unix": 0.0, "nodes": {}, "replicas": {}, "adapters": {},
        "brownout": False, "autoscaler": None, "request_seq": -1,
        "inflight": {},
    }
    state.update(over)
    return state


def _membership(node="n0", port=7000, remote="r0", client="tok-1",
                rpc_seq=5):
    return {
        "node": node, "address": ["127.0.0.1", port],
        "remote_name": remote, "client": client, "rpc_seq": rpc_seq,
    }


def test_adoption_arms_surviving_replicas(tmp_path):
    state = _journal_state(
        nodes={"n0": ["127.0.0.1", 7000]},
        replicas={"n0:r0": _membership(), "n0:r1": _membership(remote="r1")},
        inflight={
            "10": {"prompt": [3], "kwargs": {"max_new_tokens": 6},
                   "tenant": "t", "replica": "n0:r0", "rpc_id": 4,
                   "idem": None, "deadline_unix": None, "reroutes": 0},
            "11": {"prompt": [5], "kwargs": {}, "tenant": "t",
                   "replica": "n0:r0", "rpc_id": 5, "idem": None,
                   "deadline_unix": None, "reroutes": 0},
        },
    )
    plan = plan_adoption(
        state, socket_kwargs={"rpc_timeout": 9.0},
        node_control_client=_fake_ctl({("127.0.0.1", 7000): ["r0", "r1"]}),
        socket_replica=_FakeReplica,
    )
    assert sorted(plan.adopted_ids) == ["n0:r0", "n0:r1"]
    assert plan.lost_replicas == []
    assert plan.inflight == {10: state["inflight"]["10"],
                             11: state["inflight"]["11"]}
    r0 = next(r for r in plan.replicas if r.replica_id == "n0:r0")
    assert r0.adopt["client"] == "tok-1"
    assert r0.adopt["rpc_base"] == 2 * RPC_ID_INCARNATION_BLOCK
    assert r0.adopt["entries"] == [
        {"rpc_id": 4, "prompt": [3], "max_new_tokens": 6},
        {"rpc_id": 5, "prompt": [5], "max_new_tokens": 32},
    ]
    assert r0.kw == {"rpc_timeout": 9.0}
    r1 = next(r for r in plan.replicas if r.replica_id == "n0:r1")
    assert r1.adopt["entries"] == []


def test_adoption_dead_node_reports_lost(tmp_path):
    state = _journal_state(replicas={"n0:r0": _membership()})
    plan = plan_adoption(
        state, node_control_client=_fake_ctl({}),
        socket_replica=_FakeReplica,
    )
    assert plan.replicas == []
    assert plan.lost_replicas == [("n0:r0", "node n0 dead")]


def test_adoption_replica_left_roster_reports_lost(tmp_path):
    state = _journal_state(replicas={"n0:r0": _membership(remote="r9")})
    plan = plan_adoption(
        state,
        node_control_client=_fake_ctl({("127.0.0.1", 7000): ["r0"]}),
        socket_replica=_FakeReplica,
    )
    assert plan.replicas == []
    assert plan.lost_replicas == [
        ("n0:r0", "replica 'r9' left node n0's roster"),
    ]


def test_adoption_non_socket_membership_is_lost(tmp_path):
    state = _journal_state(replicas={"0": {
        "node": None, "address": None, "remote_name": None,
        "client": None, "rpc_seq": 0,
    }})
    plan = plan_adoption(
        state, node_control_client=_fake_ctl({}),
        socket_replica=_FakeReplica,
    )
    assert plan.replicas == []
    assert plan.lost_replicas == [
        ("0", "not a socket replica (dies with the router)"),
    ]


def test_adoption_dials_each_node_once(tmp_path):
    dials = []
    state = _journal_state(
        nodes={"n0": ["127.0.0.1", 7000]},
        replicas={
            "n0:r0": _membership(), "n0:r1": _membership(remote="r1"),
            "n0:r2": _membership(remote="r2"),
        },
    )
    plan_adoption(
        state,
        node_control_client=_fake_ctl(
            {("127.0.0.1", 7000): ["r0", "r1", "r2"]}, dials,
        ),
        socket_replica=_FakeReplica,
    )
    assert dials == [("127.0.0.1", 7000)]


def test_adoption_prefers_journaled_node_address(tmp_path):
    # the nodes table is authoritative: a membership journaled against
    # an older node address follows the node's CURRENT address
    dials = []
    state = _journal_state(
        nodes={"n0": ["127.0.0.1", 8000]},
        replicas={"n0:r0": _membership(port=7000)},
    )
    plan = plan_adoption(
        state,
        node_control_client=_fake_ctl({("127.0.0.1", 8000): ["r0"]}, dials),
        socket_replica=_FakeReplica,
    )
    assert dials == [("127.0.0.1", 8000)]
    assert plan.adopted_ids == ["n0:r0"]


def test_adoption_node_dying_mid_plan_loses_only_its_replicas(tmp_path):
    """A node that accepts the confirm dial but dies DURING node_info
    (connection reset mid-handshake) is a dead node: its replicas are
    lost, every other node's adoption is unaffected, and the lost
    replicas' inflight descriptors stay in the plan for re-routing."""
    dials = []

    class _Ctl:
        def __init__(self, address, **_kw):
            self.address = tuple(address)
            dials.append(self.address)

        def node_info(self):
            if self.address == ("127.0.0.1", 7001):
                raise ConnectionResetError("peer died mid-handshake")
            return {"replicas": ["r0", "r1"]}

    state = _journal_state(
        nodes={"n0": ["127.0.0.1", 7000], "n1": ["127.0.0.1", 7001]},
        replicas={
            "n0:r0": _membership(),
            "n0:r1": _membership(remote="r1"),
            "n1:r0": _membership(node="n1", port=7001),
        },
        inflight={"7": {
            "prompt": [5], "tenant": "default",
            "kwargs": {"max_new_tokens": 4}, "replica": "n1:r0",
            "rpc_id": 3, "idem": "mid-key", "deadline_unix": None,
            "reroutes": 0,
        }},
    )
    plan = plan_adoption(
        state, node_control_client=_Ctl, socket_replica=_FakeReplica,
    )
    assert sorted(dials) == [("127.0.0.1", 7000), ("127.0.0.1", 7001)]
    assert sorted(plan.adopted_ids) == ["n0:r0", "n0:r1"]
    assert plan.lost_replicas == [("n1:r0", "node n1 dead")]
    # the dead node's request rides along for orphan re-placement
    assert plan.inflight == {7: state["inflight"]["7"]}


def test_inflight_on_node_dead_mid_plan_re_routes(tmp_path):
    """End-to-end: the mid-plan death's orphaned request re-places
    through the ordinary re-route budget on the recovered fleet."""

    class _Ctl:
        def __init__(self, address, **_kw):
            pass

        def node_info(self):
            raise ConnectionResetError("peer died mid-handshake")

    plan = plan_adoption(
        _orphan_state(), node_control_client=_Ctl,
        socket_replica=_FakeReplica,
    )
    assert plan.lost_replicas == [("gone", "node nX dead")]
    router = _fleet(max_reroutes=2, recovered=plan)
    try:
        req = router.find_inflight("orph-key")
        assert req is not None and req.request_id == 7
        assert req.result(20.0) == _expected_answer([5], 4)
        assert req.reroutes == 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# adoption over REAL loopback node sessions
# ---------------------------------------------------------------------------
def test_adopted_session_replays_prefix_bitwise():
    """The resume pin: tokens already streamed to the dead incarnation
    re-emit from absolute index 0 into the adopted handle — the full
    answer is bitwise the stub's pure function, no gap, no dup."""
    node = _node(token_delay=0.05)
    node.start()
    rep1 = _replica(node)
    rep2 = None
    try:
        rep1.start()
        req1 = rep1.submit([7], max_new_tokens=12)
        deadline = time.monotonic() + 10.0
        while len(req1.tokens) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(req1.tokens) >= 3, "stub never started streaming"
        client = rep1.client_token
        _crash_replica(rep1)
        rep2 = _replica(node)
        rep2.adopt_session(client, rpc_base=2 * RPC_ID_INCARNATION_BLOCK,
                           entries=[{"rpc_id": req1.rpc_id, "prompt": [7],
                                     "max_new_tokens": 12}])
        rep2.start()
        handle = rep2.adopted_handles()[req1.rpc_id]
        assert handle.result(20.0) == _expected_answer([7], 12)
        assert handle.finish_reason == "max_new_tokens"
        # exactly-once: the node ran ONE generation across both lives
        scalars = _node_scalars(node)
        assert scalars["infer/requests_submitted"] == 1
        assert scalars["infer/requests_completed"] == 1
    finally:
        if rep2 is not None:
            rep2.shutdown()
        node.shutdown()


def test_finished_while_dead_delivers_from_outbox():
    """A generation that completed between the crash and the adoption
    DELIVERS from the node's outbox — never re-runs."""
    node = _node(delay=0.2)
    node.start()
    rep1 = _replica(node)
    rep2 = None
    try:
        rep1.start()
        req1 = rep1.submit([9], max_new_tokens=4)
        client = rep1.client_token
        _crash_replica(rep1)  # crash BEFORE the 0.2s generation lands
        deadline = time.monotonic() + 10.0
        while (
            _node_scalars(node).get("infer/requests_completed", 0) < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        rep2 = _replica(node)
        rep2.adopt_session(client, rpc_base=2 * RPC_ID_INCARNATION_BLOCK,
                           entries=[{"rpc_id": req1.rpc_id, "prompt": [9],
                                     "max_new_tokens": 4}])
        rep2.start()
        handle = rep2.adopted_handles()[req1.rpc_id]
        assert handle.result(15.0) == _expected_answer([9], 4)
        assert _node_scalars(node)["infer/requests_submitted"] == 1
    finally:
        if rep2 is not None:
            rep2.shutdown()
        node.shutdown()


def test_adopted_entry_node_forgot_fail_finishes():
    """An adopted descriptor the node does not remember (its session
    was reaped, or it never landed) fail-finishes at the welcome
    reconcile — the router's re-route path, never a silent hang."""
    node = _node()
    node.start()
    rep = _replica(node)
    try:
        rep.adopt_session("ghost-client", rpc_base=RPC_ID_INCARNATION_BLOCK,
                          entries=[{"rpc_id": 77, "prompt": [1],
                                    "max_new_tokens": 4}])
        rep.start()
        handle = rep.adopted_handles()[77]
        deadline = time.monotonic() + 10.0
        while not handle.done and time.monotonic() < deadline:
            time.sleep(0.01)
        assert handle.done and handle.finish_reason == "error"
        assert rep.alive  # the replica itself is healthy for new work
        assert rep.submit([2], max_new_tokens=2).result(10.0) == (
            _expected_answer([2], 2)
        )
    finally:
        rep.shutdown()
        node.shutdown()


def test_router_crash_recovery_cycle_end_to_end(tmp_path):
    """The tentpole in miniature, in-process: router 1 journals its
    fleet and dies mid-generation (no shutdown, no cancels); router 2
    recovers the journal, adopts the live node session, reports
    "recovering" until its first full refresh, and the request finishes
    bitwise with the node having run exactly one generation."""
    node = _node(token_delay=0.05, node_id="nA")
    node.start()
    router2 = None
    try:
        j1 = FleetJournal(tmp_path, fsync=False)
        j1.record_node("nA", node.address)
        rep1 = _replica(node, rid="nA:r0")
        router1 = FleetRouter([rep1], monitor_interval=0.02, journal=j1)
        router1.start()
        req = router1.submit([5], max_new_tokens=14,
                             idempotency_key="cycle-key")
        assert j1.state()["inflight"], "submit did not journal its open"
        # crash: stop the monitor cold and sever the socket — no bye,
        # no outstanding sweep, no journal closes
        router1._stop.set()
        router1._monitor.join(5.0)
        _crash_replica(rep1)

        state, info = load_journal_state(str(tmp_path))
        assert info["status"] == "recovered"
        plan = plan_adoption(state, socket_kwargs=_SOCKET_KW)
        assert plan.adopted_ids == ["nA:r0"]
        j2 = FleetJournal(tmp_path, fsync=False, state=state)
        router2 = FleetRouter(
            plan.replicas, monitor_interval=0.02, journal=j2,
            recovered=plan,
        )
        assert router2.recovering
        ready, reasons = router2.readiness()
        assert not ready and "recovering" in reasons
        router2.start()
        assert not router2.recovering  # first full refresh ran in start()
        assert router2.metrics.gauge("fleet/adopted_replicas").value == 1
        adopted_req = router2.find_inflight("cycle-key")
        assert adopted_req is not None
        assert adopted_req.request_id == req.request_id
        assert adopted_req.result(30.0) == _expected_answer([5], 14)
        assert adopted_req.finish_reason == "max_new_tokens"
        # terminal close left the next life's journal clean
        assert j2.state()["inflight"] == {}
        # adopted replicas re-earn trust via half-open probation, and
        # exactly one generation ever ran on the node
        scalars = _node_scalars(node)
        assert scalars["infer/requests_submitted"] == 1
        assert scalars["infer/requests_completed"] == 1
    finally:
        if router2 is not None:
            router2.shutdown()
        node.shutdown()


# ---------------------------------------------------------------------------
# router-level journaling (in-process replicas)
# ---------------------------------------------------------------------------
class _HostEngine:
    """test_door's scheduler harness: each decode step yields prev + 1
    per slot, paced by ``step_secs`` (jax-free)."""

    prefill_len = 16
    paged = False
    speculative = False

    def __init__(self, step_secs=0.01):
        self.step_secs = float(step_secs)
        self._last = {}
        self.scheduler = None

    def prefill_request(self, slot, prompt_tokens, temperature):
        del temperature
        first = (int(prompt_tokens[-1]) + 1) % 1000
        self._last[slot] = first
        return first

    def decode_tokens(self, active_slots):
        time.sleep(self.step_secs)
        out = []
        for slot in active_slots:
            nxt = (self._last.get(slot, 0) + 1) % 1000
            self._last[slot] = nxt
            out.append(nxt)
        return out

    def submit(self, prompt_tokens, **kwargs):
        return self.scheduler.submit(prompt_tokens, **kwargs)

    def load_snapshot(self):
        return self.scheduler.load_snapshot()

    def serve_forever(self):
        self.scheduler.serve_forever(idle_sleep=0.001)

    def close(self):
        self.scheduler.shutdown()


def _make_engine(step_secs=0.01, num_slots=4):
    engine = _HostEngine(step_secs=step_secs)
    engine.scheduler = ContinuousBatchingScheduler(
        engine, num_slots=num_slots, max_seq_len=512, queue_depth=16,
        queue_timeout=0.0, eos_token_id=None, temperature=0.0,
        registry=MetricsRegistry(),
    )
    return engine


def _fleet(step_secs=0.01, n_replicas=1, **router_kw):
    def factory():
        return _make_engine(step_secs=step_secs)

    replicas = [
        InProcessReplica(str(i), factory) for i in range(n_replicas)
    ]
    return FleetRouter(
        replicas, monitor_interval=0.005, **router_kw
    ).start()


def test_disabled_journal_builds_nothing(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    router = init_fleet(engine_factory=_make_engine, config={})
    try:
        assert router.journal is None
        assert router.submit([3], max_new_tokens=2).result(10.0) == [4, 5]
        # the disabled contract: no journal directory, no files, ever
        assert "fleet_journal" not in os.listdir(tmp_path)
    finally:
        router.shutdown()


def test_router_journals_membership_and_request_lifecycle(tmp_path):
    j = FleetJournal(tmp_path, fsync=False)
    router = _fleet(n_replicas=2, journal=j)
    try:
        members = j.state()["replicas"]
        assert sorted(members) == ["0", "1"]
        # in-process replicas journal as non-adoptable (address None):
        # they die with the router, and recovery rebuilds them cold
        assert members["0"]["address"] is None
        req = router.submit([7], max_new_tokens=40,
                            idempotency_key="life-key")
        entry = j.state()["inflight"].get(str(req.request_id))
        assert entry is not None and entry["idem"] == "life-key"
        assert router.find_inflight("life-key") is req
        assert req.result(15.0) == _expected_answer([7], 40)
        deadline = time.monotonic() + 5.0
        while j.state()["inflight"] and time.monotonic() < deadline:
            time.sleep(0.01)
        assert j.state()["inflight"] == {}
        assert router.remove_replica("1")
        assert sorted(j.state()["replicas"]) == ["0"]
    finally:
        router.shutdown()


def test_recovered_request_seq_reseeds_new_ids(tmp_path):
    plan = plan_adoption(
        _journal_state(request_seq=41),
        node_control_client=_fake_ctl({}), socket_replica=_FakeReplica,
    )
    router = _fleet(recovered=plan)
    try:
        req = router.submit([1], max_new_tokens=1)
        assert req.request_id >= 42
    finally:
        router.shutdown()


def _orphan_state(reroutes=0):
    return _journal_state(
        request_seq=7,
        replicas={"gone": _membership(node="nX", port=1)},
        inflight={"7": {
            "prompt": [5], "tenant": "default",
            "kwargs": {"max_new_tokens": 4}, "replica": "gone",
            "rpc_id": 3, "idem": "orph-key", "deadline_unix": None,
            "reroutes": reroutes,
        }},
    )


def test_orphaned_inflight_re_places_within_budget(tmp_path):
    """A journaled request whose replica could not be adopted re-places
    through the ordinary re-route budget and completes elsewhere."""
    plan = plan_adoption(
        _orphan_state(), node_control_client=_fake_ctl({}),
        socket_replica=_FakeReplica,
    )
    assert plan.lost_replicas == [("gone", "node nX dead")]
    router = _fleet(max_reroutes=2, recovered=plan)
    try:
        req = router.find_inflight("orph-key")
        assert req is not None and req.request_id == 7
        assert req.result(20.0) == _expected_answer([5], 4)
        assert req.reroutes == 1
    finally:
        router.shutdown()


def test_orphan_past_reroute_budget_fails_honestly(tmp_path):
    plan = plan_adoption(
        _orphan_state(reroutes=2), node_control_client=_fake_ctl({}),
        socket_replica=_FakeReplica,
    )
    router = _fleet(max_reroutes=2, recovered=plan)
    try:
        req = router.find_inflight("orph-key")
        with pytest.raises(RuntimeError, match="error"):
            req.result(20.0)
        assert req.finish_reason == "error"
    finally:
        router.shutdown()


def test_adopted_brownout_replays_then_first_refresh_reevaluates(tmp_path):
    """A journaled brownout restarts DEGRADED (the adopted engines
    re-hear the toggle before traffic lands); the first refresh then
    recomputes the real fill ratio and — with the queue empty — exits
    the band. The journal's segment history pins both edges in order."""
    plan = plan_adoption(
        _journal_state(brownout=True),
        node_control_client=_fake_ctl({}), socket_replica=_FakeReplica,
    )
    j = FleetJournal(tmp_path, fsync=False, keep_segments=50,
                     state=plan.state)
    router = _fleet(recovered=plan, journal=j, brownout_queue_ratio=0.9)
    try:
        flags = []
        for name in reversed(list_segments(str(tmp_path))):
            _v, payload, _r = verify_segment(str(tmp_path / name))
            if not flags or flags[-1] != payload["brownout"]:
                flags.append(payload["brownout"])
        assert flags == [True, False]
        assert router.metrics.gauge("fleet/brownout").value == 0.0
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# autoscaler durable half
# ---------------------------------------------------------------------------
def test_autoscaler_journal_snapshot_roundtrip():
    a = Autoscaler(None, min_replicas=1, max_replicas=8)
    a.state.target = 3
    now = a._clock()
    a.state.last_scale_at = now - 5.0
    a.state.headroom_since = None
    a.state.transitions = ((now - 10.0, "up"), (now - 2.0, "down"))
    snap = a.journal_snapshot()
    assert snap["target"] == 3 and snap["headroom_since_unix"] is None

    b = Autoscaler(None, min_replicas=1, max_replicas=8)
    b.state.op_in_flight = True
    b.restore_journal(snap)
    assert b.state.target == 3
    assert b.state.op_in_flight is False  # transient, never journaled
    assert abs((b._clock() - b.state.last_scale_at) - 5.0) < 0.5
    assert [d for _t, d in b.state.transitions] == ["up", "down"]
    assert abs((b._clock() - b.state.transitions[0][0]) - 10.0) < 0.5


def test_autoscaler_restore_clamps_target_to_policy():
    a = Autoscaler(None, min_replicas=1, max_replicas=8)
    a.state.target = 6
    snap = a.journal_snapshot()
    b = Autoscaler(None, min_replicas=1, max_replicas=2)
    b.restore_journal(snap)
    assert b.state.target == 2


# ---------------------------------------------------------------------------
# the door's resume surface
# ---------------------------------------------------------------------------
def _door(router, **kw):
    door = HTTPDoor(router, **kw)
    host, port = door.start()
    return door, host, port


def _http_json(host, port, method, target, payload=None, headers=None):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30.0)
    body = None if payload is None else json.dumps(payload)
    conn.request(method, target, body, headers or {})
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp, (json.loads(raw) if raw else None)


def _sse_request(host, port, payload, headers=None):
    sock = socket.create_connection((host, port))
    body = json.dumps(payload).encode()
    head = b"POST /v1/generate HTTP/1.1\r\nHost: door\r\n"
    for k, v in (headers or {}).items():
        head += f"{k}: {v}\r\n".encode()
    sock.sendall(head + b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
    sock.settimeout(30.0)
    return sock


def _read_until(sock, marker, buf=b""):
    while marker not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            break
        buf += chunk
    return buf


def _events(buf):
    """Parse SSE frames out of a raw response: [(event, id, data)]."""
    body = buf.split(b"\r\n\r\n", 1)[1] if b"\r\n\r\n" in buf else buf
    out = []
    for block in body.decode("utf-8", "replace").split("\n\n"):
        ev = eid = data = None
        for line in block.split("\n"):
            if line.startswith("event: "):
                ev = line[len("event: "):]
            elif line.startswith("id: "):
                eid = int(line[len("id: "):])
            elif line.startswith("data: "):
                data = json.loads(line[len("data: "):])
        if ev is not None:
            out.append((ev, eid, data))
    return out


def test_sse_token_events_carry_absolute_index_ids():
    router = _fleet()
    door, host, port = _door(router)
    try:
        sock = _sse_request(host, port, {
            "prompt": [3], "max_new_tokens": 5, "stream": True,
        })
        buf = _read_until(sock, b"event: done")
        sock.close()
        tokens = [e for e in _events(buf) if e[0] == "token"]
        assert [eid for _ev, eid, _d in tokens] == [0, 1, 2, 3, 4]
        assert [d["i"] for _ev, _eid, d in tokens] == [0, 1, 2, 3, 4]
        assert [d["t"] for _ev, _eid, d in tokens] == _expected_answer(
            [3], 5,
        )
    finally:
        door.shutdown()
        router.shutdown()


def test_last_event_id_replays_terminal_bitwise():
    router = _fleet()
    door, host, port = _door(router)
    try:
        sock = _sse_request(
            host, port,
            {"prompt": [8], "max_new_tokens": 6, "stream": True},
            headers={"Idempotency-Key": "rk-1"},
        )
        _read_until(sock, b"event: done")
        sock.close()
        # reconnect as an SSE client would: same key, the last id seen
        sock = _sse_request(
            host, port,
            {"prompt": [8], "max_new_tokens": 6, "stream": True},
            headers={"Idempotency-Key": "rk-1", "Last-Event-ID": "2"},
        )
        buf = _read_until(sock, b"event: done")
        sock.close()
        events = _events(buf)
        tokens = [e for e in events if e[0] == "token"]
        answer = _expected_answer([8], 6)
        assert [eid for _ev, eid, _d in tokens] == [3, 4, 5]
        assert [d["t"] for _ev, _eid, d in tokens] == answer[3:]
        done = next(d for ev, _eid, d in events if ev == "done")
        assert done["tokens"] == answer
        assert door._m_idem_replays.value == 1
        # the replay never re-submitted: one routed request total
        assert router.metrics.counter("fleet/requests_routed").value == 1
    finally:
        door.shutdown()
        router.shutdown()


def test_malformed_last_event_id_is_400():
    router = _fleet()
    door, host, port = _door(router)
    try:
        resp, out = _http_json(
            host, port, "POST", "/v1/generate",
            {"prompt": [1], "max_new_tokens": 2},
            headers={"Last-Event-ID": "three"},
        )
        assert resp.status == 400
        assert "Last-Event-ID" in out["error"]
    finally:
        door.shutdown()
        router.shutdown()


def test_unary_idempotent_replay_runs_once():
    router = _fleet()
    door, host, port = _door(router)
    try:
        payload = {"prompt": [4], "max_new_tokens": 3, "stream": False}
        headers = {"Idempotency-Key": "uk-1"}
        resp1, out1 = _http_json(
            host, port, "POST", "/v1/generate", payload, headers,
        )
        resp2, out2 = _http_json(
            host, port, "POST", "/v1/generate", payload, headers,
        )
        assert resp1.status == resp2.status == 200
        assert out1 == out2
        assert out1["tokens"] == _expected_answer([4], 3)
        assert door._m_idem_replays.value == 1
        assert router.metrics.counter("fleet/requests_routed").value == 1
    finally:
        door.shutdown()
        router.shutdown()


def test_idempotency_cache_is_bounded_lru():
    router = _fleet()
    door, host, port = _door(router, idempotency_cache_size=2)
    try:
        for key in ("ka", "kb", "kc"):
            _http_json(
                host, port, "POST", "/v1/generate",
                {"prompt": [2], "max_new_tokens": 2, "stream": False},
                {"Idempotency-Key": key},
            )
        assert list(door._idem_lru) == ["kb", "kc"]
        # the evicted key re-runs (greedy: bitwise the same answer)
        resp, out = _http_json(
            host, port, "POST", "/v1/generate",
            {"prompt": [2], "max_new_tokens": 2, "stream": False},
            {"Idempotency-Key": "ka"},
        )
        assert resp.status == 200
        assert out["tokens"] == _expected_answer([2], 2)
        assert door._m_idem_replays.value == 0
    finally:
        door.shutdown()
        router.shutdown()


def test_retried_stream_attaches_to_inflight_generation():
    router = _fleet(step_secs=0.05)
    door, host, port = _door(router)
    try:
        first = _sse_request(
            host, port,
            {"prompt": [6], "max_new_tokens": 12, "stream": True},
            headers={"Idempotency-Key": "at-1"},
        )
        _read_until(first, b"event: token")
        # a second POST with the key while the first still streams:
        # attach, don't re-run
        second = _sse_request(
            host, port,
            {"prompt": [6], "max_new_tokens": 12, "stream": True},
            headers={"Idempotency-Key": "at-1"},
        )
        buf2 = _read_until(second, b"event: done")
        second.close()
        _read_until(first, b"event: done")
        first.close()
        tokens = [e for e in _events(buf2) if e[0] == "token"]
        assert [eid for _ev, eid, _d in tokens] == list(range(12))
        assert [d["t"] for _ev, _eid, d in tokens] == _expected_answer(
            [6], 12,
        )
        assert door._m_resumed.value == 1
        assert router.metrics.counter("fleet/requests_routed").value == 1
    finally:
        door.shutdown()
        router.shutdown()


def test_resumed_sampled_stream_after_reroute_fails_honestly():
    router = _fleet(step_secs=0.05)
    door, host, port = _door(router)
    try:
        req = router.submit([5], max_new_tokens=8, temperature=0.5,
                            idempotency_key="smp-1")
        req.reroutes = 1  # as if its replica died and it re-placed
        resp, out = _http_json(
            host, port, "POST", "/v1/generate",
            {"prompt": [5], "max_new_tokens": 8, "temperature": 0.5,
             "stream": False},
            {"Idempotency-Key": "smp-1"},
        )
        assert resp.status == 502
        assert out["finish_reason"] == "rerouted_sampling"
        req.result(15.0)
    finally:
        door.shutdown()
        router.shutdown()


def test_graceful_restart_hands_resume_tokens_and_flips_readyz():
    router = _fleet(step_secs=0.05)
    door, host, port = _door(router)
    try:
        sock = _sse_request(host, port, {
            "prompt": [9], "max_new_tokens": 16, "stream": True,
        })
        buf = _read_until(sock, b"event: token")
        assert not door.restarting
        door.graceful_restart(retry_after=3)
        buf = _read_until(sock, b"event: restart", buf)
        sock.close()
        events = _events(buf)
        restart = next(d for ev, _eid, d in events if ev == "restart")
        assert restart["finish_reason"] == "restart"
        assert restart["retry_after_secs"] == 3
        resume = restart["resume"]
        # the door auto-minted the key, so even a keyless client can
        # come back; last_event_id names the last delivered token
        assert resume["idempotency_key"].startswith("auto-")
        delivered = [eid for ev, eid, _d in events if ev == "token"]
        assert resume["last_event_id"] == delivered[-1]
        resp, out = _http_json(host, port, "GET", "/readyz")
        assert resp.status == 503 and out["reasons"] == ["restarting"]
        # the fleet request was NOT cancelled: the generation finishes
        # and the resume token redeems it in full
        live = router.find_inflight(resume["idempotency_key"])
        assert live is not None
        assert live.result(20.0) == _expected_answer([9], 16)
        resp, out = _http_json(
            host, port, "POST", "/v1/generate",
            {"prompt": [9], "max_new_tokens": 16, "stream": False},
            {"Idempotency-Key": resume["idempotency_key"]},
        )
        assert resp.status == 200
        assert out["tokens"] == _expected_answer([9], 16)
    finally:
        door.shutdown()
        router.shutdown()
