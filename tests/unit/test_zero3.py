"""ZeRO stage 3 — parameter partitioning with layer-wise JIT gather.

Three layers of guarantees (docs/performance.md "ZeRO-3 & collective
overlap"):

1. SPEC derivation edge cases (runtime/zero.py): undivisible leaves stay
   replicated (warned once, never a crash), model-parallel leaves only
   gain the data axis on a FREE dimension, quantized int8 optimizer
   state never splits mid-block — parameterized over dp ∈ {2, 4, 8}
   with mesh-backed placement/lowering checks.
2. The zero3 stack's MATH (models/stack.py): at gather_block=1 it is
   bitwise-identical to the nn.scan stack — loss AND grads — over the
   same layouts; gather_block=2 (the overlap structure) re-associates
   only the last ulp.
3. The ENGINE contract on a 2-way dp CPU mesh: persistent param leaves
   verifiably dp-sharded, first window bitwise vs stage 2 (identical
   initial params => identical loss + grad norm), full trajectory equal
   to float tolerance (sharding changes which contractions GSPMD splits
   — same math, re-associated), stage-3 runs bitwise-reproducible
   against themselves, and checkpoints layout-independent:
   stage3-save -> stage0-load and stage2-save -> stage3-load bitwise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.config import constants as C
from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.runtime import zero as zero_lib


# ---------------------------------------------------------------------------
# 1. stage-3 spec derivation edge cases
# ---------------------------------------------------------------------------
def _mesh_for(dp):
    devs = np.array(jax.devices()[: dp * (2 if dp < 8 else 1)])
    if dp < 8:
        return Mesh(devs.reshape(dp, 2), ("data", "model"))
    return Mesh(devs.reshape(dp, 1), ("data", "model"))


@pytest.mark.parametrize("dp", [2, 4, 8])
def test_stage3_undivisible_leaf_stays_replicated(dp):
    params = {
        "odd": jnp.zeros((3, 5), jnp.float32),  # no dp-divisible dim
        "ok": jnp.zeros((8, 16), jnp.float32),
    }
    specs = zero_lib.zero_param_specs(params, dp, stage=3)
    assert specs["odd"] == P()
    assert zero_lib.has_axis(specs["ok"], C.DATA_AXIS)
    # the replicated-leaf condition warned (once per process)
    from deepspeed_tpu.utils.logging import _warned_keys

    assert "zero3-replicated-leaves" in _warned_keys
    # mesh-backed placement: the derived specs are valid on a real mesh
    mesh = _mesh_for(dp)
    placed = jax.device_put(
        params, zero_lib.specs_to_shardings(specs, mesh)
    )
    assert placed["odd"].sharding.spec == P()


@pytest.mark.parametrize("dp", [2, 4, 8])
def test_stage3_composes_with_model_parallel_free_dim_only(dp):
    # column-parallel [H, 3H] sharded on dim 1 over 'model': the data
    # axis must land on dim 0 (the free dim), never double-shard dim 1
    params = {"w": jnp.zeros((16, 48), jnp.float32)}
    mspecs = {"w": P(None, "model")}
    specs = zero_lib.zero_param_specs(
        params, dp, stage=3, model_specs=mspecs
    )
    assert specs["w"] == P(C.DATA_AXIS, "model")
    # row-parallel [H, H] sharded dim 0: data goes to dim 1
    params2 = {"w": jnp.zeros((16, 16), jnp.float32)}
    specs2 = zero_lib.zero_param_specs(
        params2, dp, stage=3, model_specs={"w": P("model", None)}
    )
    assert specs2["w"] == P("model", C.DATA_AXIS)
    # already dp-sharded (MoE experts over data): spec unchanged, the
    # axis is never repeated
    specs3 = zero_lib.zero_param_specs(
        params2, dp, stage=3, model_specs={"w": P(C.DATA_AXIS, None)}
    )
    assert specs3["w"] == P(C.DATA_AXIS, None)
    # mesh-backed jit lowering: constraining to the composed spec
    # compiles and runs on a real (data, model) mesh
    mesh = _mesh_for(dp)
    sh = NamedSharding(mesh, specs["w"])
    out = jax.jit(
        lambda x: jax.lax.with_sharding_constraint(x * 2.0, sh)
    )(jax.device_put(params["w"], sh))
    assert out.sharding == sh


@pytest.mark.parametrize("dp", [2, 4, 8])
def test_quantized_optstate_never_shards_mid_block(dp):
    from deepspeed_tpu.ops.quant import BLOCK

    params = {"w": jnp.zeros((16, 64), jnp.float32)}
    pspecs = zero_lib.zero_optstate_specs(params, dp, stage=1)
    # engine-padded layout: block count divides dp -> flat dp shard on
    # BLOCK boundaries
    nb_ok = 8
    state = {
        "mu": {
            "w": {
                "q": jnp.zeros((nb_ok * BLOCK,), jnp.int8),
                "scale": jnp.zeros((nb_ok,), jnp.float32),
            }
        }
    }
    ospecs = zero_lib.optstate_specs_like(
        state, pspecs, params, dp_size=dp
    )
    assert ospecs["mu"]["w"]["q"] == P(C.DATA_AXIS)
    assert ospecs["mu"]["w"]["scale"] == P(C.DATA_AXIS)
    # unpadded client leaf: nb % dp != 0 -> BOTH leaves replicate (a
    # q-shard boundary mid-block would force cross-shard gathers)
    nb_bad = dp + 1
    state_bad = {
        "mu": {
            "w": {
                "q": jnp.zeros((nb_bad * BLOCK,), jnp.int8),
                "scale": jnp.zeros((nb_bad,), jnp.float32),
            }
        }
    }
    ospecs_bad = zero_lib.optstate_specs_like(
        state_bad, pspecs, params, dp_size=dp
    )
    assert ospecs_bad["mu"]["w"]["q"] == P()
    assert ospecs_bad["mu"]["w"]["scale"] == P()


def test_gathered_spec_strips_only_data_axis():
    assert zero_lib.gathered_spec(P(C.DATA_AXIS, "model")) == P(None, "model")
    assert zero_lib.gathered_spec(P(("model", C.DATA_AXIS), None)) == P(
        "model", None
    )
    assert zero_lib.gathered_spec(P(None, C.DATA_AXIS)) == P(None, None)
    assert zero_lib.gathered_spec(P()) == P()


# ---------------------------------------------------------------------------
# 2. the zero3 stack's math (no sharding: pure structure equivalence)
# ---------------------------------------------------------------------------
def _tiny_cfg(**kw):
    kw.setdefault("remat", True)
    kw.setdefault("n_layer", 4)
    return GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_head=2,
        dropout=0.0, **kw,
    )


def _stack_fixtures():
    import flax.linen as nn

    from deepspeed_tpu.ops.transformer import DeepSpeedTransformerLayer

    cfg = _tiny_cfg()
    layer_cfg = cfg.layer_config()

    class NNScanStack(nn.Module):
        @nn.compact
        def __call__(self, x):
            x, _ = nn.scan(
                lambda mdl, c, _: (mdl(c, None, train=True), None),
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.n_layer,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(
                DeepSpeedTransformerLayer(
                    config=layer_cfg, causal=True,
                    use_flash=cfg.use_flash, mesh=None, name="h",
                ),
                x, None,
            )
            return x

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 16, 32)), jnp.float32)
    m = NNScanStack()
    params = m.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x,
    )["params"]
    return cfg, layer_cfg, m, params, x


@pytest.mark.parametrize("gb,expect_bitwise", [(1, True), (2, False)])
def test_zero3_stack_math_vs_nnscan(gb, expect_bitwise):
    from deepspeed_tpu.models.stack import zero3_scan_stack

    cfg, layer_cfg, m, params, x = _stack_fixtures()
    arming = {"specs": {}, "stacked_specs": {}, "block": gb}

    def loss_ref(p, x_):
        return jnp.sum(m.apply({"params": p}, x_) ** 2)

    def loss_zero3(p, x_):
        return jnp.sum(
            zero3_scan_stack(
                layer_cfg, p["h"], x_, arming, None,
                causal=True, use_flash=cfg.use_flash, train=True,
            ) ** 2
        )

    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_ref))(params, x)
    l_z, g_z = jax.jit(jax.value_and_grad(loss_zero3))(params, x)
    if expect_bitwise:
        assert float(l_ref) == float(l_z)
        for k in g_ref["h"]:
            assert np.array_equal(
                np.asarray(g_ref["h"][k]), np.asarray(g_z["h"][k])
            ), f"grad {k} not bitwise at gather_block=1"
    else:
        # the unrolled pair shares one scan body: same math, compiler
        # may re-associate the last ulp
        assert np.allclose(float(l_ref), float(l_z), rtol=1e-6)
        for k in g_ref["h"]:
            np.testing.assert_allclose(
                np.asarray(g_ref["h"][k]), np.asarray(g_z["h"][k]),
                rtol=1e-4, atol=1e-6,
            )


def test_resolve_gather_block_divisor():
    from deepspeed_tpu.models.stack import resolve_gather_block

    assert resolve_gather_block(48, 2) == 2
    assert resolve_gather_block(48, 5) == 4  # largest divisor <= 5
    assert resolve_gather_block(7, 2) == 1
    assert resolve_gather_block(4, 99) == 4


# ---------------------------------------------------------------------------
# 3. engine contract on a 2-way dp CPU mesh
# ---------------------------------------------------------------------------
def _dp2_mesh():
    return Mesh(np.array(jax.devices()[:2]), ("data",))


def _build_engine(stage, zextra=None, seed=0):
    cfg = _tiny_cfg(n_layer=2)
    model = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids, ids,
    )["params"]
    z = {"stage": stage}
    if zextra:
        z.update(zextra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        mesh=_dp2_mesh(),
        rng_seed=seed,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": z,
            "steps_per_print": 10_000,
        },
    )
    return engine, model


def _run_windows(engine, n=3):
    r = np.random.default_rng(7)
    seq = []
    for _ in range(n):
        b = r.integers(0, 128, (8, 16)).astype(np.int32)
        loss = engine.train_batch(iter([(b, b)]))
        seq.append((float(loss), float(engine._last_grad_norm)))
    return seq


def test_engine_stage3_first_window_bitwise_and_trajectory():
    e2, _ = _build_engine(2)
    e3, m3 = _build_engine(3, {"stage3_gather_block": 1})
    assert e3.zero3_gather_enabled
    assert m3.config.zero3_gather is not None
    s2 = _run_windows(e2)
    s3 = _run_windows(e3)
    # first window: identical initial params => bitwise loss + grad norm
    assert s2[0] == s3[0], (s2[0], s3[0])
    # trajectory: same math, reductions re-associated by the sharded
    # layouts — tight float agreement, not bitwise
    np.testing.assert_allclose(
        np.asarray(s2), np.asarray(s3), rtol=2e-5, atol=1e-6
    )


def test_engine_stage3_persistent_params_dp_sharded():
    e3, _ = _build_engine(3)
    flat = jax.tree_util.tree_flatten_with_path(e3.params)[0]
    sharded = {
        "/".join(str(getattr(k, "key", k)) for k in p)
        for p, leaf in flat
        if zero_lib.has_axis(leaf.sharding.spec, C.DATA_AXIS)
    }
    # every block matrix + the embeddings persist dp-sharded
    for name in ("attn_qkvw", "attn_ow", "inter_w", "output_w"):
        assert f"transformer/h/{name}" in sharded
    assert "transformer/wte" in sharded
    # accounting gauges see the sharding
    assert e3._zero3_shard_bytes > 0
    assert e3._zero3_gather_bytes > 0
    full = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for _, l in flat
    )
    assert e3._zero3_shard_bytes < full  # strictly below replicated


def test_engine_stage3_bitwise_reproducible():
    a = _run_windows(_build_engine(3)[0])
    b = _run_windows(_build_engine(3)[0])
    assert a == b


def test_engine_stage3_default_gather_block_trajectory():
    # the default overlap structure (gather_block=2): same math to float
    # tolerance vs stage 2
    e2, _ = _build_engine(2)
    e3, m3 = _build_engine(3)
    assert m3.config.zero3_gather["block"] == 2
    np.testing.assert_allclose(
        np.asarray(_run_windows(e2)), np.asarray(_run_windows(e3)),
        rtol=2e-5, atol=1e-6,
    )


def test_engine_stage3_seam_declines_lora():
    # adapters do not compose with the zero3 stack yet: params stay
    # dp-sharded but the seam must not arm (and must say so)
    cfg = _tiny_cfg(n_layer=2, lora_rank=2)
    model = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids, ids,
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=_dp2_mesh(),
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3},
            "steps_per_print": 10_000,
        },
    )
    assert not engine.zero3_gather_enabled
    assert model.config.zero3_gather is None
    # still trains (XLA places the gathers)
    seq = _run_windows(engine, n=1)
    assert np.isfinite(seq[0][0])


# ---------------------------------------------------------------------------
# checkpoint roundtrips: artifacts are layout-independent
# ---------------------------------------------------------------------------
def _host_params(engine):
    return jax.tree_util.tree_map(np.asarray, engine.params)


def test_checkpoint_stage3_save_stage0_load_bitwise(tmp_path):
    src, _ = _build_engine(3)
    _run_windows(src, n=2)
    src.save_checkpoint(str(tmp_path), tag="xfer")
    want = _host_params(src)
    dst, _ = _build_engine(0)
    path, _ = dst.load_checkpoint(str(tmp_path), tag="xfer")
    assert path is not None
    got = _host_params(dst)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), want, got
    )
    # the restored replicated engine continues bitwise-deterministically
    assert np.isfinite(_run_windows(dst, n=1)[0][0])


def test_checkpoint_stage2_save_stage3_load_bitwise(tmp_path):
    src, _ = _build_engine(2)
    _run_windows(src, n=2)
    src.save_checkpoint(str(tmp_path), tag="xfer")
    want = _host_params(src)
    dst, _ = _build_engine(3)
    path, _ = dst.load_checkpoint(str(tmp_path), tag="xfer")
    assert path is not None
    got = _host_params(dst)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), want, got
    )
    # loaded leaves re-sharded to the ACTIVE stage-3 specs
    flat = jax.tree_util.tree_flatten_with_path(dst.params)[0]
    assert any(
        zero_lib.has_axis(l.sharding.spec, C.DATA_AXIS) for _, l in flat
    )
    # optimizer moments roundtripped through the shard files
    mu = jax.tree_util.tree_leaves(dst.optimizer_state)
    assert all(np.isfinite(np.asarray(x)).all() for x in mu if hasattr(x, "shape"))


# ---------------------------------------------------------------------------
# BERT rides the same seam
# ---------------------------------------------------------------------------
def test_bert_stage3_seam_armed_and_trains():
    from deepspeed_tpu.models import BertConfig, BertForPreTraining

    cfg = BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
        attn_dropout_checkpoint=True,
    )
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (8, 16)).astype(np.int32)
    mask = np.ones((8, 16), np.int32)
    mlm = np.where(rng.random((8, 16)) < 0.3, ids, -1).astype(np.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.asarray(ids[:2]), jnp.asarray(mask[:2]), None,
        jnp.asarray(mlm[:2]),
    )["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=_dp2_mesh(),
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "stage3_gather_block": 1},
            "steps_per_print": 10_000,
        },
    )
    assert engine.zero3_gather_enabled
    losses = []
    for _ in range(2):
        loss = engine.train_batch(iter([(ids, mask, np.zeros_like(ids), mlm)]))
        losses.append(float(loss))
    assert all(np.isfinite(losses))


# ---------------------------------------------------------------------------
# overlap flag arming (runtime/overlap.py)
# ---------------------------------------------------------------------------
def test_arm_latency_hiding_tpu_only():
    from deepspeed_tpu.runtime import overlap

    env = {}
    assert overlap.arm_latency_hiding(platform="cpu", env=env) == ()
    assert "XLA_FLAGS" not in env
    added = overlap.arm_latency_hiding(platform="tpu", env=env)
    assert added == overlap.LATENCY_HIDING_XLA_FLAGS
    for flag in overlap.LATENCY_HIDING_XLA_FLAGS:
        assert flag in env["XLA_FLAGS"]
    # idempotent
    assert overlap.arm_latency_hiding(platform="tpu", env=env) == ()


def test_arm_latency_hiding_respects_user_setting():
    from deepspeed_tpu.runtime import overlap

    env = {"XLA_FLAGS": "--xla_enable_async_all_gather=false"}
    overlap.arm_latency_hiding(platform="tpu", env=env)
    # the user's explicit value wins — never overridden or duplicated
    assert env["XLA_FLAGS"].count("--xla_enable_async_all_gather") == 1
    assert "--xla_enable_async_all_gather=false" in env["XLA_FLAGS"]
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" in env["XLA_FLAGS"]


def test_stale_seam_disarmed_on_non_stage3_reinitialize():
    # the arming is a model-config mutation; a second engine built over
    # the SAME model object at stage < 3 must disarm it (stale specs
    # from the first engine's mesh would silently run the zero3 stack)
    e3, model = _build_engine(3)
    assert model.config.zero3_gather is not None
    params = jax.tree_util.tree_map(np.asarray, e3.params)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=_dp2_mesh(),
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10_000,
        },
    )
    assert model.config.zero3_gather is None
    assert not engine2.zero3_gather_enabled
    assert np.isfinite(_run_windows(engine2, n=1)[0][0])


def test_zero3_accounting_respects_full_sharding():
    # the layout gauges divide each leaf by EVERY mesh axis its spec
    # names (a dp x mp leaf is nbytes/(dp*mp) resident), and gather
    # traffic covers only the mp-local portion — recomputed here from
    # the live arrays' .sharding as the exact expected value
    from deepspeed_tpu.models.gpt2 import partition_specs

    cfg = _tiny_cfg(n_layer=2)
    model = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        ids, ids,
    )["params"]
    mesh = Mesh(
        np.array(jax.devices()[:4]).reshape(2, 2), ("data", "model")
    )
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=mesh,
        param_specs=partition_specs(params),
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3},
            "steps_per_print": 10_000,
        },
    )
    axes = dict(mesh.shape)

    def factor(spec, skip=()):
        f = 1
        for e in spec:
            for n in (e if isinstance(e, tuple) else (e,)):
                if n is not None and n not in skip:
                    f *= axes.get(n, 1)
        return f

    resident = gather = 0
    for _, leaf in jax.tree_util.tree_flatten_with_path(engine.params)[0]:
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        spec = leaf.sharding.spec
        resident += nbytes // factor(spec)
        if zero_lib.has_axis(spec, C.DATA_AXIS):
            mp_local = nbytes // factor(spec, skip=(C.DATA_AXIS,))
            gather += 2 * (mp_local * (axes["data"] - 1) // axes["data"])
    assert engine._zero3_shard_bytes == resident
    assert engine._zero3_gather_bytes == gather
    # and at least one leaf really is sharded over both axes
    assert any(
        zero_lib.has_axis(l.sharding.spec, C.DATA_AXIS)
        and zero_lib.has_axis(l.sharding.spec, "model")
        for _, l in jax.tree_util.tree_flatten_with_path(engine.params)[0]
    )


@pytest.mark.parametrize(
    "value,armed",
    [("1", True), ("true", True), ("False", False), ("off", False),
     ("no", False), ("0", False), ("", False)],
)
def test_launcher_latency_hiding_env_truthiness(value, armed, monkeypatch):
    from deepspeed_tpu.launcher import launch as dsl

    class Args:
        master_addr = "10.0.0.1"
        master_port = 29501

    monkeypatch.setenv("DS_TPU_LATENCY_HIDING", value)
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    # the launcher refuses TPU-only flags for a non-TPU-pinned process
    # (unknown XLA_FLAGS abort at backend init) — pin tpu to test arming
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    env = dsl.build_env(Args, {"h0": [0]}, 0)
    assert (
        "xla_tpu_enable_latency_hiding_scheduler" in env.get("XLA_FLAGS", "")
    ) is armed


@pytest.mark.parametrize("platforms", ["cpu", "cuda,cpu", None])
def test_launcher_latency_hiding_skips_non_tpu(platforms, monkeypatch):
    # DS_TPU_LATENCY_HIDING=1 must NOT export the flags when the child
    # will not load the TPU backend: XLA fatally aborts on unknown
    # XLA_FLAGS. Covers both an explicit non-TPU JAX_PLATFORMS pin and
    # the autodetect case (unset) on a host with no TPU stack — this CI
    # box has no libtpu, so autodetect must skip too.
    from deepspeed_tpu.launcher import launch as dsl

    class Args:
        master_addr = "10.0.0.1"
        master_port = 29501

    monkeypatch.setenv("DS_TPU_LATENCY_HIDING", "1")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    if platforms is None:
        # autodetect on a non-TPU host: probe says no real TPU
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.setattr(dsl, "_autodetect_tpu_host", lambda env: False)
    else:
        monkeypatch.setenv("JAX_PLATFORMS", platforms)
    env = dsl.build_env(Args, {"h0": [0]}, 0)
    assert "xla_tpu_enable_latency_hiding_scheduler" not in env.get(
        "XLA_FLAGS", ""
    )


def test_launcher_latency_hiding_autodetect_real_tpu_host(monkeypatch):
    # unset JAX_PLATFORMS on a real TPU host (runtime + device nodes —
    # the normal TPU launch shape) arms the flags
    from deepspeed_tpu.launcher import launch as dsl

    class Args:
        master_addr = "10.0.0.1"
        master_port = 29501

    monkeypatch.setenv("DS_TPU_LATENCY_HIDING", "1")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(dsl, "_autodetect_tpu_host", lambda env: True)
    env = dsl.build_env(Args, {"h0": [0]}, 0)
    assert (
        "--xla_tpu_enable_latency_hiding_scheduler=true"
        in env["XLA_FLAGS"].split()
    )


def test_autodetect_tpu_host_probe_this_box():
    # this CI/dev box has a stub libtpu wheel but NO TPU device nodes —
    # the probe must refuse (arming here is an XLA_FLAGS fatal abort,
    # verified empirically)
    import glob

    from deepspeed_tpu.launcher import launch as dsl

    if glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*"):
        pytest.skip("real TPU device nodes present")
    assert dsl._autodetect_tpu_host({}) is False
    assert dsl._autodetect_tpu_host({"TPU_LIBRARY_PATH": "/x.so"}) is False


def test_append_latency_hiding_flags_exact_name_match():
    # substring matching would see the base fusion flag inside its
    # longer _fuse_all_gather variant and skip arming it
    from deepspeed_tpu.runtime import overlap

    existing = "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=false"
    out = overlap.append_latency_hiding_flags(existing)
    assert "--xla_tpu_enable_async_collective_fusion=true" in out.split()
    # the user's explicit longer flag is kept, never duplicated
    assert out.split().count(existing) == 1
    assert (
        "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true"
        not in out.split()
    )


def test_telemetry_zero3_layout_gauges():
    from deepspeed_tpu.telemetry.manager import ENGINE_METRICS, Telemetry
    from deepspeed_tpu.telemetry.registry import MetricsRegistry

    names = {n for _, n, _ in ENGINE_METRICS}
    assert "train/hbm_peak_bytes" in names
    assert "train/zero3_param_shard_bytes" in names
    assert "train/zero3_gather_bytes_per_window" in names
    t = Telemetry(enabled=True, registry=MetricsRegistry())
    t.set_zero3_layout(123, 456)
    snap = t.registry.snapshot()
    assert snap["train/zero3_param_shard_bytes"] == 123
    assert snap["train/zero3_gather_bytes_per_window"] == 456
    t.close()
