"""argparse injection tests (reference tests/unit/test_ds_arguments.py):
add_config_arguments must coexist with user args, default sensibly, and
accept the deprecated --deepscale* aliases."""

import argparse

import pytest

import deepspeed_tpu


def basic_parser():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_epochs", type=int)
    return parser


def test_no_ds_arguments_no_ds_parser():
    args = basic_parser().parse_args(["--num_epochs", "2"])
    assert args.num_epochs == 2
    assert not hasattr(args, "deepspeed")
    assert not hasattr(args, "deepspeed_config")


def test_no_ds_arguments():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--num_epochs", "2"])
    assert args.num_epochs == 2
    assert args.deepspeed is False
    assert args.deepspeed_config is None


def test_core_deepspeed_arguments():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(
        ["--num_epochs", "2", "--deepspeed", "--deepspeed_config", "ds.json"]
    )
    assert args.deepspeed is True
    assert args.deepspeed_config == "ds.json"


def test_only_ds_arguments():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--deepspeed"])
    assert args.deepspeed is True
    assert args.num_epochs is None


def test_deprecated_deepscale_aliases():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--deepscale", "--deepscale_config", "ds.json"])
    assert args.deepscale is True
    assert args.deepscale_config == "ds.json"


def test_mpi_discovery_flag():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--deepspeed_mpi"])
    assert args.deepspeed_mpi is True


def test_engine_reads_config_path_from_args(tmp_path):
    """initialize(args=...) must pick up --deepspeed_config (and the
    deprecated alias) exactly like the reference engine
    (deepspeed_light.py:428-435)."""
    import json

    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    cfg_path = tmp_path / "ds.json"
    cfg_path.write_text(json.dumps({
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }))

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return jnp.mean(nn.Dense(4)(x) ** 2)

    model = M()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((2, 4))
    )["params"]

    for flag in ("--deepspeed_config", "--deepscale_config"):
        parser = deepspeed_tpu.add_config_arguments(argparse.ArgumentParser())
        args = parser.parse_args([flag, str(cfg_path)])
        engine, _, _, _ = deepspeed_tpu.initialize(
            args=args, model=model, model_parameters=params
        )
        assert engine.train_batch_size() == 8
