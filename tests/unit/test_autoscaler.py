"""SLO-driven predictive autoscaler tests (deepspeed_tpu/serving/
autoscaler.py, docs/serving.md "SLO autoscaling"): the cost model's
deterministic predictions, the full decision table from synthetic
snapshots with an injectable clock (surge -> scale-up before the
brownout band, headroom -> drain-then-retire, eviction -> re-provision,
cooldown / flap-budget refusal, min/max clamps), elastic replica
lifecycle end to end over real schedulers, per-replica gauge retirement,
the node agent's spawn/retire control ops over a real socket, and the
disabled-config zero-overhead pin."""

import threading
import time

import pytest

from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.serving import (
    AUTOSCALE_DOWN,
    AUTOSCALE_HOLD,
    AUTOSCALE_REPROVISION,
    AUTOSCALE_UP,
    BREAKER_CLOSED,
    BREAKER_OPEN,
    Autoscaler,
    AutoscalerPolicy,
    FleetRouter,
    InProcessReplica,
    InProcessReplicaProvider,
    PhaseCostModel,
    SLOTargets,
    SocketNodeProvider,
)
from deepspeed_tpu.serving.autoscaler import (
    AutoscaleState,
    Decision,
    ErrorBudget,
    NoPlaceableCapacity,
)
from deepspeed_tpu.serving.node import NodeServer
from deepspeed_tpu.serving.replica import ReplicaBase
from deepspeed_tpu.serving.transport import (
    NodeControlClient,
    SocketReplica,
)
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.tracing import SpanTracer


# ---------------------------------------------------------------------------
# synthetic snapshots (the decision table's inputs)
# ---------------------------------------------------------------------------
def _snap(**kw):
    base = {
        "alive": True, "failed": False, "queue_depth": 0,
        "queue_capacity": 8, "active_slots": 0, "free_slots": 2,
        "num_slots": 2, "health": 0, "mean_prefill_ms": 10.0,
        "p99_prefill_ms": 20.0, "mean_decode_ms": 3.0,
        "mean_queue_wait_ms": 1.0, "requests_shed": 0.0,
        "restarts_used": 0, "requests_completed": 10,
        "tokens_generated": 320, "driving": True, "stopped": False,
        "driver_failed": False,
    }
    base.update(kw)
    return base


def _fitted_model(snaps):
    model = PhaseCostModel()
    model.observe(snaps)
    return model


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------
def test_cost_model_fit_and_prediction_deterministic():
    snaps = [("0", _snap())]
    model = _fitted_model(snaps)
    assert model.fitted
    # service = prefill + tokens/request * decode = 10 + 32*3 = 106ms
    assert model.service_ms() == pytest.approx(106.0)
    p1 = model.predict(snaps, arrival_rps=5.0)
    p2 = model.predict(snaps, arrival_rps=5.0)
    assert p1 == p2  # pure arithmetic: same inputs, same numbers
    # 2 slots / 106ms => ~18.87 sustainable rps
    assert p1.sustainable_rps == pytest.approx(2000.0 / 106.0)
    assert p1.utilization == pytest.approx(5.0 / (2000.0 / 106.0))
    assert p1.token_ms == pytest.approx(3.0)


def test_cost_model_saturation_amplifies_predicted_wait():
    snaps = [("0", _snap(queue_depth=6))]
    model = _fitted_model(snaps)
    calm = model.predict(snaps, arrival_rps=1.0)
    saturated = model.predict(snaps, arrival_rps=100.0)
    assert saturated.utilization > 1.0
    # the same backlog predicts an exploding wait near saturation —
    # the property that lets the autoscaler act while queues are shallow
    assert saturated.ttft_ms > 10 * calm.ttft_ms
    assert saturated.ttft_ms < float("inf")


def test_cost_model_unfitted_predicts_zero_utilization():
    model = PhaseCostModel()
    snaps = [("0", _snap(mean_prefill_ms=0.0, mean_decode_ms=0.0,
                         queue_depth=4))]
    model.observe(snaps)  # zero means contribute nothing
    assert not model.fitted
    p = model.predict(snaps, arrival_rps=100.0)
    assert p.utilization == 0.0 and not p.fitted
    assert p.queue_ratio == pytest.approx(0.5)  # fill still reported


def test_error_budget_window_prunes_and_accounts():
    budget = ErrorBudget(window_secs=10.0)
    assert budget.remaining(now=0.0) == 1.0  # idle fleet: full budget
    budget.record(0.0, violated=True)
    budget.record(1.0, violated=False)
    budget.record(2.0, violated=False)
    budget.record(3.0, violated=False)
    assert budget.remaining(now=3.0) == pytest.approx(0.75)
    # the violation ages out of the window; the budget refills
    assert budget.remaining(now=11.5) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# the decision table (pure: synthetic snapshots + injectable clock)
# ---------------------------------------------------------------------------
def _policy(**kw):
    kw.setdefault("slo", SLOTargets(ttft_p99_ms=250.0))
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("cooldown_secs", 30.0)
    kw.setdefault("hysteresis_secs", 60.0)
    return AutoscalerPolicy(**kw)


def test_decide_surge_scales_up_on_predicted_slo_miss():
    snaps = [("0", _snap(queue_depth=6, active_slots=2))]
    model = _fitted_model(snaps)
    prediction = model.predict(snaps, arrival_rps=50.0)
    policy = _policy()
    state = AutoscaleState(target=1)
    d = policy.decide(live_replicas=1, candidates=snaps,
                      prediction=prediction, state=state, now=100.0)
    assert d.action == AUTOSCALE_UP
    assert "SLO" in d.reason
    # purity: the identical inputs yield the identical decision
    d2 = policy.decide(live_replicas=1, candidates=snaps,
                       prediction=prediction, state=state, now=100.0)
    assert d == d2


def test_decide_scales_up_before_brownout_band_engages():
    """Queue fill at 80% of the brownout threshold triggers capacity
    growth even with an UNFITTED cost model — degradation must never be
    the first responder."""
    policy = _policy(slo=SLOTargets(), brownout_queue_ratio=0.5)
    snaps = [("0", _snap(queue_depth=4, queue_capacity=10,
                         mean_prefill_ms=0.0, mean_decode_ms=0.0))]
    model = PhaseCostModel()
    model.observe(snaps)
    prediction = model.predict(snaps, arrival_rps=0.0)
    assert prediction.queue_ratio == pytest.approx(0.4)  # = 0.8 * 0.5
    d = policy.decide(live_replicas=1, candidates=snaps,
                      prediction=prediction, state=AutoscaleState(1),
                      now=0.0)
    assert d.action == AUTOSCALE_UP
    assert "brownout" in d.reason


def test_decide_base_latency_slo_miss_is_not_scalable_overload():
    """Capacity shrinks only the QUEUEING term: a fleet whose prefill
    tail alone busts the TTFT SLO (a first-compile outlier pinning the
    cumulative p99, or a model simply too slow for the target) must not
    read as a permanent overload — scale-up could never fix it, and it
    would also block every future scale-down."""
    snaps = [("0", _snap(p99_prefill_ms=5000.0, queue_depth=0))]
    model = _fitted_model(snaps)
    prediction = model.predict(snaps, arrival_rps=0.1)
    assert prediction.ttft_ms > 250.0  # the base alone busts the SLO
    assert prediction.wait_ms < prediction.ttft_ms
    policy = _policy(slo=SLOTargets(ttft_p99_ms=250.0))
    overloaded, _why = policy.overloaded(prediction)
    assert not overloaded
    # with headroom sustained, the same fleet may still scale DOWN
    state = AutoscaleState(target=2)
    state.headroom_since = 0.0
    snaps2 = [("0", _snap(p99_prefill_ms=5000.0)),
              ("1", _snap(p99_prefill_ms=5000.0))]
    d = policy.decide(live_replicas=2, candidates=snaps2,
                      prediction=prediction, state=state, now=100.0)
    assert d.action == AUTOSCALE_DOWN


def test_decide_max_replicas_clamp_refuses_scale_up():
    snaps = [("0", _snap(queue_depth=6))]
    model = _fitted_model(snaps)
    prediction = model.predict(snaps, arrival_rps=50.0)
    policy = _policy(max_replicas=2)
    d = policy.decide(live_replicas=2, candidates=snaps,
                      prediction=prediction, state=AutoscaleState(2),
                      now=0.0)
    assert d.action == AUTOSCALE_HOLD and d.refused == AUTOSCALE_UP
    assert "max_replicas" in d.reason


def test_decide_cooldown_refuses_scale_up():
    snaps = [("0", _snap(queue_depth=6))]
    model = _fitted_model(snaps)
    prediction = model.predict(snaps, arrival_rps=50.0)
    policy = _policy(cooldown_secs=30.0)
    state = AutoscaleState(target=1)
    state.last_scale_at = 90.0
    d = policy.decide(live_replicas=1, candidates=snaps,
                      prediction=prediction, state=state, now=100.0)
    assert d.action == AUTOSCALE_HOLD and d.refused == AUTOSCALE_UP
    assert "cooldown" in d.reason
    # the cooldown elapses; the same pressure now scales
    d = policy.decide(live_replicas=1, candidates=snaps,
                      prediction=prediction, state=state, now=121.0)
    assert d.action == AUTOSCALE_UP


def test_decide_flap_budget_refuses_direction_reversal():
    snaps = [("0", _snap(queue_depth=6))]
    model = _fitted_model(snaps)
    prediction = model.predict(snaps, arrival_rps=50.0)
    policy = _policy(flap_budget=1, flap_window_secs=600.0,
                     cooldown_secs=1.0)
    state = AutoscaleState(target=1)
    # up -> down already burned the window's one reversal; another
    # up would be reversal #2
    state.transitions = ((10.0, "up"), (20.0, "down"))
    d = policy.decide(live_replicas=1, candidates=snaps,
                      prediction=prediction, state=state, now=100.0)
    assert d.action == AUTOSCALE_HOLD and d.refused == AUTOSCALE_UP
    assert "flap budget" in d.reason
    # once the old transitions age out of the window, pressure scales
    d = policy.decide(live_replicas=1, candidates=snaps,
                      prediction=prediction, state=state, now=700.0)
    assert d.action == AUTOSCALE_UP


def test_decide_sustained_headroom_scales_down_deterministic_victim():
    snaps = [
        ("0", _snap(queue_depth=0, active_slots=1)),
        ("1", _snap(queue_depth=0, active_slots=0)),
        ("as0", _snap(queue_depth=0, active_slots=0)),
    ]
    model = _fitted_model(snaps)
    prediction = model.predict(snaps, arrival_rps=0.1)
    policy = _policy(hysteresis_secs=60.0)
    assert policy.has_headroom(prediction, live_replicas=3)
    state = AutoscaleState(target=3)
    state.headroom_since = 0.0
    # hysteresis not yet served: hold
    d = policy.decide(live_replicas=3, candidates=snaps,
                      prediction=prediction, state=state, now=30.0)
    assert d.action == AUTOSCALE_HOLD
    # served: drain the least-loaded, ties to the LATEST-registered
    d = policy.decide(live_replicas=3, candidates=snaps,
                      prediction=prediction, state=state, now=61.0)
    assert d.action == AUTOSCALE_DOWN
    assert d.replica_id == "as0"


def test_decide_min_replicas_clamp_refuses_scale_down():
    snaps = [("0", _snap())]
    model = _fitted_model(snaps)
    prediction = model.predict(snaps, arrival_rps=0.0)
    policy = _policy(min_replicas=1, hysteresis_secs=1.0)
    state = AutoscaleState(target=1)
    state.headroom_since = 0.0
    d = policy.decide(live_replicas=1, candidates=snaps,
                      prediction=prediction, state=state, now=10.0)
    assert d.action == AUTOSCALE_HOLD and d.refused == AUTOSCALE_DOWN
    assert "min_replicas" in d.reason
    # min_replicas also kills the headroom predicate itself
    assert not policy.has_headroom(prediction, live_replicas=1)


def test_decide_reprovision_when_live_below_target_ignores_cooldown():
    """Chaos took a replica: restoring the target is not a scaling
    oscillation — the cooldown and flap clamps do not apply."""
    snaps = [("0", _snap())]
    model = _fitted_model(snaps)
    prediction = model.predict(snaps, arrival_rps=0.0)
    policy = _policy(cooldown_secs=3600.0, flap_budget=0)
    state = AutoscaleState(target=2)
    state.last_scale_at = 99.0  # cooldown would block a scale-up
    d = policy.decide(live_replicas=1, candidates=snaps,
                      prediction=prediction, state=state, now=100.0)
    assert d.action == AUTOSCALE_REPROVISION
    assert "below the target" in d.reason


def test_decide_holds_while_op_in_flight():
    snaps = [("0", _snap(queue_depth=6))]
    model = _fitted_model(snaps)
    prediction = model.predict(snaps, arrival_rps=50.0)
    state = AutoscaleState(target=1)
    state.op_in_flight = True
    d = _policy().decide(live_replicas=1, candidates=snaps,
                         prediction=prediction, state=state, now=0.0)
    assert d.action == AUTOSCALE_HOLD and "in flight" in d.reason


# ---------------------------------------------------------------------------
# stub replicas for lifecycle tests (the router contract, no engines)
# ---------------------------------------------------------------------------
class _StubHandle:
    def __init__(self, prompt_tokens):
        self.prompt_tokens = list(prompt_tokens)
        self.tokens = []
        self.finish_reason = None
        self.first_token_at = None
        self._done = threading.Event()

    @property
    def done(self):
        return self._done.is_set()

    def _finish(self, tokens, reason):
        self.tokens = list(tokens)
        self.finish_reason = reason
        self.first_token_at = time.monotonic()
        self._done.set()


class _StubReplica(ReplicaBase):
    def __init__(self, replica_id, snapshot=None, autofinish=(1, 2, 3)):
        super().__init__(replica_id)
        self.snap = _snap(**(snapshot or {}))
        self.autofinish = list(autofinish)
        self.failed = False
        self.adapters_loaded = []
        self.submit_calls = 0

    def start(self):
        return self

    def submit(self, prompt_tokens, **kwargs):
        self.submit_calls += 1
        handle = _StubHandle(prompt_tokens)
        handle._finish(self.autofinish, "max_new_tokens")
        return handle

    def load_adapter(self, name, **kwargs):
        self.adapters_loaded.append((name, dict(kwargs)))
        return len(self.adapters_loaded)

    def unload_adapter(self, name):
        return 0

    def _snapshot_now(self):
        snap = dict(self.snap)
        snap["failed"] = self.failed
        snap["alive"] = snap["alive"] and not self.failed
        return snap

    def drain(self):
        pass

    def restart(self):
        self.failed = False
        return self

    def shutdown(self):
        pass


class _StubProvider:
    name = "stub"

    def __init__(self):
        self.spawned = []
        self.retired = []

    def spawn(self, existing_ids):
        rid = f"as{len(self.spawned)}"
        while rid in set(existing_ids):
            rid += "x"
        replica = _StubReplica(rid).start()
        self.spawned.append(replica)
        return replica

    def retire(self, replica):
        self.retired.append(replica.replica_id)
        replica.shutdown()


def _wait(predicate, timeout=30.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# router elasticity: add/remove, probation, gauge retirement
# ---------------------------------------------------------------------------
def test_add_replica_joins_behind_half_open_probe():
    router = FleetRouter(
        [_StubReplica("0")], monitor_interval=0.002,
    ).start()
    try:
        new = _StubReplica("as0")
        router.add_replica(new, probation=True)
        assert "as0" in router.live_replica_ids()
        # probation: OPEN with an elapsed window — a placement candidate
        # whose first submission is the single half-open probe
        assert router.breaker_state("as0") == BREAKER_OPEN
        probes_before = router.metrics.counter(
            "fleet/breaker_probes"
        ).value
        # drain the incumbent so placement MUST pick the probationer
        router.drain("0")
        fr = router.submit([5], max_new_tokens=3)
        assert fr.result(10.0) == [1, 2, 3]
        assert router.breaker_state("as0") == BREAKER_CLOSED
        assert router.metrics.counter(
            "fleet/breaker_probes"
        ).value == probes_before + 1
        assert new.submit_calls == 1
    finally:
        router.shutdown()


def test_add_replica_replays_fleet_adapter_registry():
    r0 = _StubReplica("0")
    router = FleetRouter([r0], monitor_interval=0.002).start()
    try:
        router.load_adapter("tenant-a", load_dir="/ckpt/a")
        new = _StubReplica("as0")
        router.add_replica(new)
        assert new.adapters_loaded == [
            ("tenant-a", {"load_dir": "/ckpt/a"})
        ]
    finally:
        router.shutdown()


def test_add_replica_rejects_duplicate_id():
    router = FleetRouter([_StubReplica("0")], monitor_interval=0.002
                         ).start()
    try:
        with pytest.raises(ValueError, match="already registered"):
            router.add_replica(_StubReplica("0"))
    finally:
        router.shutdown()


def test_remove_replica_refuses_to_empty_the_fleet():
    router = FleetRouter([_StubReplica("0")], monitor_interval=0.002
                         ).start()
    try:
        with pytest.raises(RuntimeError, match="last live replica"):
            router.remove_replica("0")
    finally:
        router.shutdown()


def test_replica_gauges_retired_on_scale_down_and_eviction():
    """The satellite pin: a dead replica's fleet/replica{i}/* gauges
    must not keep exporting their stale last values."""
    r0 = _StubReplica("0")
    r1 = _StubReplica("1", snapshot={"queue_depth": 5})
    router = FleetRouter([r0, r1], monitor_interval=0.002).start()
    try:
        router.refresh_telemetry()
        snap = router.metrics.snapshot()
        assert snap["fleet/replica1/queue_depth"] == 5
        # scale-down: gauges retired with the replica (the stub reports
        # a non-empty queue forever, so cap the drain wait — the pin
        # here is gauge retirement, not the drain barrier)
        router.remove_replica("1", wait_idle_timeout=0.2)
        snap = router.metrics.snapshot()
        stale = [k for k in snap if k.startswith("fleet/replica1/")]
        assert stale == [], stale
        # eviction: same contract (the monitor's failed-replica sweep)
        new = _StubReplica("as0", snapshot={"queue_depth": 7})
        router.add_replica(new, probation=False)
        router.refresh_telemetry()
        assert router.metrics.snapshot()[
            "fleet/replicaas0/queue_depth"
        ] == 7
        new.failed = True
        assert _wait(lambda: "as0" in router.evicted_ids, timeout=10.0)
        router.refresh_telemetry()
        snap = router.metrics.snapshot()
        stale = [k for k in snap if k.startswith("fleet/replicaas0/")]
        assert stale == [], stale
        # the aggregate fleet gauges survive and reflect the shrink
        assert snap["fleet/replicas_total"] == 1
    finally:
        router.shutdown()


def test_fleet_requests_shed_aggregate_gauge():
    r0 = _StubReplica("0", snapshot={"requests_shed": 2.0})
    r1 = _StubReplica("1", snapshot={"requests_shed": 3.0})
    router = FleetRouter([r0, r1], monitor_interval=0.002).start()
    try:
        router.refresh_telemetry()
        assert router.metrics.snapshot()["fleet/requests_shed"] == 5.0
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# eviction -> re-provision (the chaos-restoration loop)
# ---------------------------------------------------------------------------
def test_eviction_triggers_reprovision_to_target():
    provider = _StubProvider()
    autoscaler = Autoscaler(
        provider, min_replicas=2, max_replicas=3, interval_secs=0.01,
        cooldown_secs=3600.0,  # re-provision must not need the cooldown
    )
    router = FleetRouter(
        [_StubReplica("0"), _StubReplica("1")],
        monitor_interval=0.002, autoscaler=autoscaler,
    ).start()
    try:
        assert autoscaler.state.target == 2
        router._replicas["1"].failed = True
        assert _wait(lambda: "1" in router.evicted_ids, timeout=10.0)
        # live dropped to 1 < target 2: the autoscaler restores capacity
        assert _wait(
            lambda: len(router.live_replica_ids()) == 2, timeout=20.0
        ), router.live_replica_ids()
        assert provider.spawned, "no replacement was spawned"
        # the executor counts the transition just after registration
        assert _wait(
            lambda: router.metrics.counter(
                "fleet/autoscale_reprovisions"
            ).value >= 1,
            timeout=10.0,
        )
        # the replacement serves
        fr = router.submit([9], max_new_tokens=3)
        assert fr.result(10.0) == [1, 2, 3]
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# end-to-end elasticity over REAL schedulers (jax-free host engines)
# ---------------------------------------------------------------------------
class _HostEngine:
    """test_door's harness: real ContinuousBatchingScheduler, plain-
    Python decode hooks paced by step_secs."""

    prefill_len = 16
    paged = False
    speculative = False

    def __init__(self, step_secs=0.02):
        self.step_secs = float(step_secs)
        self._last = {}
        self.scheduler = None

    def prefill_request(self, slot, prompt_tokens, temperature):
        del temperature
        first = (int(prompt_tokens[-1]) + 1) % 1000
        self._last[slot] = first
        return first

    def decode_tokens(self, active_slots):
        time.sleep(self.step_secs)
        out = []
        for slot in active_slots:
            nxt = (self._last.get(slot, 0) + 1) % 1000
            self._last[slot] = nxt
            out.append(nxt)
        return out

    def submit(self, prompt_tokens, **kwargs):
        return self.scheduler.submit(prompt_tokens, **kwargs)

    def load_snapshot(self):
        return self.scheduler.load_snapshot()

    def serve_forever(self):
        self.scheduler.serve_forever(idle_sleep=0.001)

    def close(self):
        self.scheduler.shutdown()


def _make_engine(step_secs=0.02, num_slots=2):
    engine = _HostEngine(step_secs=step_secs)
    engine.scheduler = ContinuousBatchingScheduler(
        engine, num_slots=num_slots, max_seq_len=512, queue_depth=64,
        queue_timeout=0.0, eos_token_id=None, temperature=0.0,
        registry=MetricsRegistry(),
    )
    return engine


def _expected(prompt, n):
    base = int(prompt[-1])
    return [(base + i + 1) % 1000 for i in range(n)]


def test_surge_scales_up_then_idle_scales_down_end_to_end():
    """The tentpole loop over real schedulers: a surge against one
    replica grows the fleet to two (behind the probation probe) with
    zero requests lost; the subsequent idle window drains the spawned
    replica back out and retires its gauges."""
    engines = []

    def factory():
        engine = _make_engine(step_secs=0.02, num_slots=2)
        engines.append(engine)
        return engine

    provider = InProcessReplicaProvider(factory)
    autoscaler = Autoscaler(
        provider,
        slo=SLOTargets(ttft_p99_ms=150.0, eval_window_secs=5.0),
        min_replicas=1, max_replicas=2, cooldown_secs=0.2,
        hysteresis_secs=0.3, flap_budget=8, interval_secs=0.02,
        scale_up_utilization=0.5, scale_down_utilization=0.3,
        drain_timeout_secs=10.0,
    )
    router = FleetRouter(
        [InProcessReplica("0", factory)], monitor_interval=0.005,
        autoscaler=autoscaler,
    ).start()
    try:
        prompts = [[10 + i] for i in range(8)]
        reqs = [router.submit(p, max_new_tokens=20) for p in prompts]
        assert _wait(
            lambda: len(router.live_replica_ids()) == 2, timeout=30.0
        ), "the surge never scaled the fleet up"
        assert _wait(
            lambda: router.metrics.counter(
                "fleet/autoscale_ups"
            ).value >= 1,
            timeout=10.0,
        )
        # the target tracks the executed transition (read promptly:
        # the later idle window legitimately shrinks it back to 1)
        assert _wait(lambda: autoscaler.state.target == 2, timeout=5.0)
        # zero lost, bitwise-exact answers through the scale event
        outs = [r.result(60.0) for r in reqs]
        assert outs == [_expected(p, 20) for p in prompts]
        snap = router.metrics.snapshot()
        assert snap["fleet/requests_shed"] == 0.0
        assert snap["fleet/requests_browned_out"] == 0.0
        assert snap["fleet/slo_predicted_ttft_ms"] >= 0.0
        # idle: sustained headroom drains the spawned replica back out
        assert _wait(
            lambda: len(router.live_replica_ids()) == 1, timeout=60.0
        ), "idle never scaled the fleet down"
        assert _wait(
            lambda: router.metrics.counter(
                "fleet/autoscale_downs"
            ).value >= 1,
            timeout=10.0,
        )
        retired = [
            rid for rid in ("as0",) if rid not in router.replica_ids
        ]
        assert retired == ["as0"], router.replica_ids
        snap = router.metrics.snapshot()
        stale = [k for k in snap if k.startswith("fleet/replicaas0/")]
        assert stale == [], stale
        # one more request serves normally on the shrunken fleet
        fr = router.submit([77], max_new_tokens=3)
        assert fr.result(30.0) == _expected([77], 3)
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# node-agent elasticity: spawn/retire over a real control socket
# ---------------------------------------------------------------------------
def test_node_spawn_and_retire_ops_over_control_session():
    node = NodeServer({
        "node_id": "n0",
        "replicas": {"r0": {"stub": {"delay_secs": 0.0}}},
        "max_replicas": 2,
        "lease_secs": 5.0, "resume_grace_secs": 5.0,
    })
    host, port = node.start()
    try:
        ctl = NodeControlClient((host, port), op_timeout=30.0)
        info = ctl.node_info()
        assert info["replicas"] == ["r0"]
        # spawn from the node's template (r0's stub spec)
        reply = ctl.spawn_replica("r1")
        assert reply["replicas"] == ["r0", "r1"]
        # the spawned replica serves real traffic over the data plane
        replica = SocketReplica(
            "n0:r1", (host, port), remote_name="r1", rpc_timeout=5.0,
            registry=MetricsRegistry(),
        ).start()
        try:
            req = replica.submit([30], max_new_tokens=3)
            assert req.result(10.0) == [31, 32, 33]
        finally:
            replica.shutdown()
        # duplicates refuse; the ceiling refuses
        with pytest.raises(RuntimeError, match="already hosts"):
            ctl.spawn_replica("r1")
        with pytest.raises(RuntimeError, match="max_replicas"):
            ctl.spawn_replica("r2")
        # retire frees the engine and the roster
        reply = ctl.retire_replica("r1")
        assert reply["replicas"] == ["r0"]
        with pytest.raises(RuntimeError, match="hosts no replica"):
            ctl.retire_replica("r1")
        # a control session cannot run engine ops
        with pytest.raises(RuntimeError, match="control session"):
            ctl._roundtrip({"op": "snapshot"})
    finally:
        node.shutdown()


def test_socket_provider_spawns_on_least_loaded_reachable_node():
    node = NodeServer({
        "node_id": "n0",
        "replicas": {"r0": {"stub": {"delay_secs": 0.0}}},
    })
    host, port = node.start()
    try:
        provider = SocketNodeProvider(
            {"n0": {"address": f"{host}:{port}", "replicas": ["r0"]},
             "dead": {"address": "127.0.0.1:9", "replicas": []}},
            connect_timeout=1.0, connect_retries=1, spawn_timeout=30.0,
            node_retry_secs=60.0, registry=MetricsRegistry(),
        )
        # "dead" hosts fewer replicas so it is tried first — the
        # connect failure marks it and the spawn lands on n0
        with pytest.raises(Exception):
            provider.spawn(["n0:r0"])
        replica = provider.spawn(["n0:r0"])
        try:
            assert replica.replica_id.startswith("n0:as")
            req = replica.submit([40], max_new_tokens=2)
            assert req.result(10.0) == [41, 42]
        finally:
            provider.retire(replica)
        assert NodeControlClient((host, port)).node_info()[
            "replicas"
        ] == ["r0"]
    finally:
        node.shutdown()


# ---------------------------------------------------------------------------
# disabled config = zero-overhead passthrough
# ---------------------------------------------------------------------------
def test_disabled_autoscale_is_zero_overhead_passthrough():
    before = {t.name for t in threading.enumerate()}
    router = FleetRouter([_StubReplica("0")], monitor_interval=0.002
                         ).start()
    try:
        assert router.autoscaler is None
        # no autoscale thread exists anywhere in the process
        new = {t.name for t in threading.enumerate()} - before
        assert not any("autoscale" in n for n in new), new
        # the slo/autoscale catalog streams exist but stay inert
        snap = router.metrics.snapshot()
        assert snap["fleet/autoscale_ups"] == 0
        assert snap["fleet/slo_violations"] == 0
    finally:
        router.shutdown()


def test_init_fleet_builds_autoscaler_only_when_enabled():
    from deepspeed_tpu.serving import init_fleet

    def factory():
        return _make_engine(step_secs=0.0)

    router = init_fleet(
        engine_factory=factory,
        config={"train_batch_size": 1,
                "serving": {"replicas": 1}},
    )
    try:
        assert router.autoscaler is None
    finally:
        router.shutdown()
    router = init_fleet(
        engine_factory=factory,
        config={
            "train_batch_size": 1,
            "serving": {
                "replicas": 1,
                "slo": {"ttft_p99_ms": 500.0},
                "autoscale": {"enabled": True, "max_replicas": 2,
                              "interval_secs": 0.05},
            },
        },
    )
    try:
        assert router.autoscaler is not None
        assert router.autoscaler.policy.slo.ttft_p99_ms == 500.0
        assert router.autoscaler.policy.max_replicas == 2
        assert router.autoscaler.state.target == 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# typed refusals: counted per reason, flight-recorded on the transition
# ---------------------------------------------------------------------------
class _RefusingProvider:
    name = "stub"

    def spawn(self, existing_ids):
        raise NoPlaceableCapacity(
            "every node dead or at ceiling and no provisioner configured"
        )

    def retire(self, replica):
        pass


def test_refused_spawn_counts_per_reason_but_flight_records_once():
    """A structurally unplaceable scale_up is a REFUSAL, not a failure:
    both counters move on every refused tick, but the flight-recorder
    instant fires only on the transition into the refusal state."""
    scaler = Autoscaler(_RefusingProvider(), min_replicas=1, max_replicas=4)
    tracer = SpanTracer(ring_events=64)
    router = FleetRouter(
        [_StubReplica("0")], monitor_interval=0.002,
        tracer=tracer, autoscaler=scaler,
    ).start()
    try:
        for _ in range(2):
            scaler._execute(
                Decision(AUTOSCALE_UP, "surge", None, None, None)
            )
        metrics = router.metrics
        assert metrics.counter("fleet/autoscale_refusals").value == 2
        assert metrics.counter(
            "fleet/autoscale_refusals/no_placeable_capacity"
        ).value == 2
        refused = [
            e for e in tracer.flight_snapshot()
            if e["name"] == "router.autoscale"
            and e["attrs"]["action"] == "refused"
        ]
        assert len(refused) == 1  # deduped while the reason is unchanged
        # target never moved: a refusal is not a transition
        assert scaler.state.target == 1
    finally:
        router.shutdown()
