"""Speculative decoding (docs/inference.md "Speculative decoding"):
draft-proposes-k / target-verifies-in-one-step with exact greedy parity
BY CONSTRUCTION — pinned here against the sequential non-speculative
engine across both acceptance regimes (an independent random draft that
mostly rejects, and a truncated agreeing draft that mostly accepts),
plus the zero-recompile pin across acceptance lengths, the length-cap
null-redirect (verify writes near max_seq_len must not corrupt shared
prefix pages), and the config/API guard rails."""

import copy

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedConfigError
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

VOCAB = 97


def _small_model(seed=0, n_layer=2, n_embd=32):
    cfg = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=n_embd, n_layer=n_layer,
        n_head=4, dropout=0.0, use_flash=False,
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jnp.asarray(
        np.random.default_rng(seed).integers(0, VOCAB, (1, 8)), jnp.int32
    )
    params = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        ids0, ids0,
    )["params"]
    return cfg, model, params


def _engine(model, params, extra=None, **kw):
    block = {"max_batch_slots": 4, "max_seq_len": 48, "prefill_len": 32,
             "kv_block_size": 8, "sampling": {"greedy": True}}
    block.update(extra or {})
    return deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config={"inference": block}, **kw,
    )


def _prompt(n=8, seed=1):
    return [int(t) for t in np.random.default_rng(seed).integers(0, VOCAB, n)]


def _agreeing_pair(seed=0, keep_layers=1):
    """(target model/params, draft model/params) that AGREE on every
    greedy choice by construction: the draft carries the target's first
    ``keep_layers`` blocks + embeddings/ln_f, and the target's REMAINING
    blocks have zero attn_ow/output_w (+ biases) — pre-LN residual
    blocks with zero output projections contribute exactly 0.0 to the
    stream, so target logits equal draft logits while the target still
    pays full-depth compute. The high-acceptance regime with no
    training. The same construction (and the same residual-path key
    set) lives in bench.py:_agreeing_draft_target — keep them in
    sync."""
    cfg, model, params = _small_model(seed=seed)
    tparams = jax.tree_util.tree_map(np.asarray, params)
    t2 = copy.deepcopy(tparams)
    h = t2["transformer"]["h"]
    for key in ("attn_ow", "output_w", "attn_ob", "output_b"):
        arr = np.array(h[key])
        arr[keep_layers:] = 0.0
        h[key] = arr
    dcfg = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=keep_layers,
        n_head=4, dropout=0.0, use_flash=False,
    )
    dmodel = GPT2LMHeadModel(dcfg)
    dparams = copy.deepcopy(tparams)
    dparams["transformer"]["h"] = {
        k: np.array(v)[:keep_layers]
        for k, v in tparams["transformer"]["h"].items()
    }
    return model, t2, dmodel, dparams


# ---------------------------------------------------------------------------
# greedy parity across acceptance regimes
# ---------------------------------------------------------------------------
def test_spec_parity_with_rejecting_random_draft():
    """An INDEPENDENT random draft (frequent rejections) must still
    yield bitwise-identical greedy tokens: every committed token is the
    target's own argmax whatever the draft proposed."""
    cfg, model, params = _small_model()
    _, dmodel, dparams = _small_model(seed=7, n_layer=1)
    e_ref = _engine(model, params)
    e_spec = _engine(
        model, params, {"speculative": {"k": 3}},
        draft_model=dmodel, draft_parameters=dparams,
    )
    try:
        prompts = [_prompt(9, 1), _prompt(5, 2), _prompt(13, 3)]
        assert e_ref.generate(prompts, max_new_tokens=10) == \
            e_spec.generate(prompts, max_new_tokens=10)
        snap = e_spec.metrics.snapshot()
        assert snap["infer/spec_proposed"] > 0
        assert 0.0 <= snap["infer/spec_acceptance_rate"] <= 1.0
    finally:
        e_ref.close()
        e_spec.close()


def test_spec_parity_and_acceptance_with_agreeing_draft():
    """The high-acceptance regime: a truncated draft that agrees with
    the target by construction. Parity still holds, the acceptance rate
    approaches 1, and each scheduler step commits multiple tokens."""
    model, tparams, dmodel, dparams = _agreeing_pair()
    e_ref = _engine(model, tparams)
    e_spec = _engine(
        model, tparams, {"speculative": {"k": 4}},
        draft_model=dmodel, draft_parameters=dparams,
    )
    try:
        prompts = [_prompt(9, 1), _prompt(5, 2)]
        assert e_ref.generate(prompts, max_new_tokens=12) == \
            e_spec.generate(prompts, max_new_tokens=12)
        snap = e_spec.metrics.snapshot()
        assert snap["infer/spec_acceptance_rate"] > 0.8, snap
        # k+1 tokens per accepted cycle => far fewer decode steps than
        # tokens: the whole point of the stack
        assert snap["infer/token_latency_ms/count"] * 2 <= \
            snap["infer/tokens_generated"]
    finally:
        e_ref.close()
        e_spec.close()


def test_spec_parity_mid_flight_join_and_eos_reuse():
    """The continuous-batching matrix on the speculative path: a
    mid-flight join and EOS slot reuse produce the sequential engine's
    exact tokens, and EOS mid-burst discards the burst's tail."""
    model, tparams, dmodel, dparams = _agreeing_pair()
    e_ref = _engine(model, tparams)
    e_spec = _engine(
        model, tparams, {"speculative": {"k": 3}},
        draft_model=dmodel, draft_parameters=dparams,
    )
    try:
        r1r = e_ref.submit(_prompt(8, 4), max_new_tokens=12)
        r1s = e_spec.submit(_prompt(8, 4), max_new_tokens=12)
        for _ in range(2):
            e_ref.scheduler.step()
        e_spec.scheduler.step()
        r2r = e_ref.submit(_prompt(7, 5), max_new_tokens=8)
        r2s = e_spec.submit(_prompt(7, 5), max_new_tokens=8)
        e_ref.scheduler.run_until_idle()
        e_spec.scheduler.run_until_idle()
        assert r1r.result(0) == r1s.result(0)
        assert r2r.result(0) == r2s.result(0)

        # EOS: pick a token the reference emits mid-stream; the burst
        # containing it must truncate exactly there
        ref = e_ref.generate([_prompt(8, 6)], max_new_tokens=8)[0]
        eos = ref[3]
        ar = e_ref.submit(_prompt(8, 6), max_new_tokens=8, eos_token_id=eos)
        asp = e_spec.submit(_prompt(8, 6), max_new_tokens=8, eos_token_id=eos)
        e_ref.scheduler.run_until_idle()
        e_spec.scheduler.run_until_idle()
        assert ar.finish_reason == asp.finish_reason == "eos"
        assert ar.result(0) == asp.result(0)
        # the freed slot serves the next request exactly
        assert e_ref.generate([_prompt(6, 9)], max_new_tokens=6) == \
            e_spec.generate([_prompt(6, 9)], max_new_tokens=6)
    finally:
        e_ref.close()
        e_spec.close()


def test_spec_disables_inert_fused_flag_and_prefix_cache_composes():
    """fused_decode configured on a speculative engine is INERT (the
    verify step is multi-token XLA, the draft rides a contiguous
    cache) — the engine disables it so infer/fused_decode reports what
    actually served. The prefix cache, by contrast, genuinely composes:
    hits still serve suffix-only under speculation, with parity."""
    model, tparams, dmodel, dparams = _agreeing_pair()
    e_ref = _engine(model, tparams)
    e_both = _engine(
        model, tparams,
        {"speculative": {"k": 3}, "fused_decode": True},
        draft_model=dmodel, draft_parameters=dparams,
    )
    try:
        assert e_both.speculative and not e_both.fused_decode
        assert e_both.metrics.gauge("infer/fused_decode").value == 0
        shared = _prompt(16, 7)
        for tail_seed in (8, 9):
            p = [shared + _prompt(3, tail_seed)]
            assert e_ref.generate(p, max_new_tokens=6) == \
                e_both.generate(p, max_new_tokens=6)
        assert e_both.metrics.counter("infer/prefix_hits").value >= 1
    finally:
        e_ref.close()
        e_both.close()


def test_spec_length_cap_null_redirect_protects_shared_pages():
    """A speculative request finishing AT the length cap: its verify
    step's would-be writes past max_seq_len redirect to the null page
    instead of clamping into the slot's real last page — which can be a
    SHARED prefix page. Pinned by serving the same long shared prefix
    again afterwards and comparing against a never-shared engine."""
    model, tparams, dmodel, dparams = _agreeing_pair()
    e_spec = _engine(
        model, tparams, {"speculative": {"k": 4}, "prefill_len": 40},
        draft_model=dmodel, draft_parameters=dparams,
    )
    e_cold = _engine(
        model, tparams,
        {"prefix_cache": {"enabled": False}, "prefill_len": 40},
    )
    try:
        shared = _prompt(32, 11)  # 4 full pages of shared prefix
        pa = shared + _prompt(2, 12)
        # run to the cap: 34 prompt + up to 30 => hits max_seq_len=48
        ra = e_spec.submit(pa, max_new_tokens=30)
        e_spec.scheduler.run_until_idle()
        assert ra.finish_reason == "length"
        assert ra.result(0) == e_cold.generate(
            [pa], max_new_tokens=30
        )[0]
        # the shared pages must still hold the PREFIX's k/v: a second
        # request hitting them decodes exactly like a cache-less engine
        pb = shared + _prompt(2, 13)
        hot = e_spec.generate([pb], max_new_tokens=6)[0]
        assert e_spec.metrics.counter("infer/prefix_hits").value >= 1
        assert hot == e_cold.generate([pb], max_new_tokens=6)[0]
    finally:
        e_spec.close()
        e_cold.close()


# ---------------------------------------------------------------------------
# zero steady-state recompiles across acceptance lengths
# ---------------------------------------------------------------------------
def test_spec_decode_does_not_recompile_across_acceptance_lengths():
    """k is static, acceptance length is DATA: scheduler steps whose
    bursts commit varying token counts (an INDEPENDENT random draft
    makes acceptance genuinely data-dependent per step) add zero XLA
    backend compiles after warmup."""
    cfg, model, params = _small_model()
    _, dmodel, dparams = _small_model(seed=7, n_layer=1)
    e_spec = _engine(
        model, params, {"speculative": {"k": 3}},
        draft_model=dmodel, draft_parameters=dparams,
    )
    try:
        recompiles = e_spec.metrics.counter("jax/recompiles")
        e_spec.generate([_prompt(8, 1)], max_new_tokens=6)
        warm = recompiles.value
        assert warm > 0
        seen_commits = set()
        for seed in range(2, 8):
            r = e_spec.submit(
                _prompt(5 + seed, seed), max_new_tokens=6 + seed % 3
            )
            steps_before = e_spec.metrics.snapshot()[
                "infer/token_latency_ms/count"
            ]
            e_spec.scheduler.run_until_idle()
            steps = e_spec.metrics.snapshot()[
                "infer/token_latency_ms/count"
            ] - steps_before
            seen_commits.add((len(r.result(0)), int(steps)))
        # the acceptance/commit pattern genuinely varied across requests
        assert len(seen_commits) > 1, seen_commits
        assert recompiles.value == warm, (
            f"speculative path recompiled: {recompiles.value - warm} new "
            "backend compiles across varied acceptance lengths"
        )
    finally:
        e_spec.close()


# ---------------------------------------------------------------------------
# telemetry + tracing
# ---------------------------------------------------------------------------
def test_spec_streams_and_phase_spans(tmp_path):
    """infer/spec_* streams move and the tracer's ring carries the
    sched.spec_draft/spec_verify/spec_commit phase spans a flight dump
    would show (docs/observability.md)."""
    model, tparams, dmodel, dparams = _agreeing_pair()
    engine = deepspeed_tpu.init_inference(
        model=model, model_parameters=tparams,
        config={
            "inference": {
                "max_batch_slots": 2, "max_seq_len": 48,
                "prefill_len": 32, "kv_block_size": 8,
                "sampling": {"greedy": True},
                "speculative": {"k": 3},
            },
            "telemetry": {
                "enabled": True, "output_path": str(tmp_path),
                "job_name": "spec_spans", "exporters": [],
                "watchdog": {"enabled": False},
                "tracing": {"enabled": True, "ring_events": 1024,
                            "export": "none"},
            },
        },
        draft_model=dmodel, draft_parameters=dparams,
    )
    try:
        engine.generate([_prompt(8, 1)], max_new_tokens=6)
        names = {s["name"] for s in engine.tracer.flight_snapshot()}
        for want in (
            "sched.decode_step", "sched.spec_draft", "sched.spec_verify",
            "sched.spec_commit",
        ):
            assert want in names, f"{want} missing from {sorted(names)}"
        snap = engine.metrics.snapshot()
        assert snap["infer/spec_proposed"] > 0
        assert snap["infer/spec_accepted"] > 0
    finally:
        engine.close()


# ---------------------------------------------------------------------------
# guard rails
# ---------------------------------------------------------------------------
def test_spec_requires_draft_model():
    cfg, model, params = _small_model()
    with pytest.raises(DeepSpeedConfigError, match="draft"):
        _engine(model, params, {"speculative": {"k": 2}})


def test_spec_requires_greedy_sampling():
    cfg, model, params = _small_model()
    _, dmodel, dparams = _small_model(seed=7, n_layer=1)
    with pytest.raises(DeepSpeedConfigError, match="[Gg]reedy"):
        _engine(
            model, params,
            {"speculative": {"k": 2},
             "sampling": {"greedy": False, "temperature": 0.8}},
            draft_model=dmodel, draft_parameters=dparams,
        )


def test_spec_submit_rejects_nonzero_temperature():
    model, tparams, dmodel, dparams = _agreeing_pair()
    engine = _engine(
        model, tparams, {"speculative": {"k": 2}},
        draft_model=dmodel, draft_parameters=dparams,
    )
    try:
        with pytest.raises(ValueError, match="speculative"):
            engine.submit(_prompt(6), temperature=0.7)
    finally:
        engine.close()


def test_spec_rejects_vocab_mismatch():
    cfg, model, params = _small_model()
    dcfg = GPT2Config(
        vocab_size=VOCAB + 1, n_positions=64, n_embd=32, n_layer=1,
        n_head=4, dropout=0.0, use_flash=False,
    )
    dmodel = GPT2LMHeadModel(dcfg)
    ids0 = jnp.zeros((1, 8), jnp.int32)
    dparams = dmodel.init(
        {"params": jax.random.PRNGKey(3), "dropout": jax.random.PRNGKey(4)},
        ids0, ids0,
    )["params"]
    with pytest.raises(DeepSpeedConfigError, match="vocab"):
        _engine(
            model, params, {"speculative": {"k": 2}},
            draft_model=dmodel, draft_parameters=dparams,
        )


def test_spec_driver_restart_resets_draft_cache_and_serves_on():
    """A decode crash on the speculative path restarts like any other:
    fresh target pool AND fresh draft cache from pinned params, queue
    preserved, post-restart output exactly a clean engine's."""
    model, tparams, dmodel, dparams = _agreeing_pair()
    engine = _engine(
        model, tparams,
        {"speculative": {"k": 3}, "driver_restart_budget": 1},
        draft_model=dmodel, draft_parameters=dparams,
    )
    clean = _engine(
        model, tparams, {"speculative": {"k": 3}},
        draft_model=dmodel, draft_parameters=dparams,
    )
    try:
        engine.generate([_prompt(8, 1)], max_new_tokens=4)
        original = engine.decode_tokens

        def crash_once(active):
            engine.decode_tokens = original
            raise RuntimeError("injected decode crash")

        r = engine.submit(_prompt(9, 2), max_new_tokens=6)
        engine.decode_tokens = crash_once
        engine.scheduler.run_until_idle()
        assert r.finish_reason == "error"
        assert engine.scheduler.restarts_used == 1
        out = engine.generate([_prompt(10, 3)], max_new_tokens=6)
        assert out == clean.generate([_prompt(10, 3)], max_new_tokens=6)
    finally:
        engine.close()
        clean.close()
