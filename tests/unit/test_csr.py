"""CSR row-sparse tensor tests (reference tests/unit/test_csr.py:
round-trip; plus the TPU additions: capacity bounding and the sharded
sparse allreduce)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.dist import shard_map
from deepspeed_tpu.runtime.sparse import (
    CSRTensor,
    sparse_all_reduce_local,
    sparse_allreduce_average,
)


def _sparse_dense(rows=32, cols=8, nnz=5, seed=0):
    rng = np.random.default_rng(seed)
    dense = np.zeros((rows, cols), np.float32)
    idx = rng.choice(rows, nnz, replace=False)
    dense[idx] = rng.standard_normal((nnz, cols))
    return jnp.asarray(dense)


def test_csr_roundtrip():
    dense = _sparse_dense()
    csr = CSRTensor.from_dense(dense)
    np.testing.assert_array_equal(np.asarray(csr.to_dense()), np.asarray(dense))


def test_csr_capacity_bounded_roundtrip():
    dense = _sparse_dense(nnz=5)
    csr = CSRTensor.from_dense(dense, max_rows=8)  # capacity > nnz: lossless
    assert csr.values.shape == (8, 8)
    np.testing.assert_array_equal(np.asarray(csr.to_dense()), np.asarray(dense))


def test_csr_add_concatenates():
    a = CSRTensor.from_dense(_sparse_dense(seed=0), max_rows=4)
    b = CSRTensor.from_dense(_sparse_dense(seed=1), max_rows=4)
    expect = np.asarray(a.to_dense()) + np.asarray(b.to_dense())
    a.add(b)
    np.testing.assert_allclose(np.asarray(a.to_dense()), expect, rtol=1e-6)


def test_csr_reduction_factor_reported():
    csr = CSRTensor.from_dense(_sparse_dense(rows=64, nnz=4), max_rows=4)
    sparse_size, dense_size = csr.sparse_size()
    assert dense_size == 64 * 8
    assert sparse_size == 4 + 4 * 8
    assert "reduction_factor" in repr(csr)


def test_sparse_all_reduce_matches_dense_psum():
    mesh = build_mesh(data_parallel_size=8)
    # one distinct sparse grad per rank: global leading dim 8*k
    per_rank = [
        CSRTensor.from_dense(_sparse_dense(seed=s), max_rows=6) for s in range(8)
    ]
    glob = CSRTensor(
        indices=jnp.concatenate([c.indices for c in per_rank]),
        values=jnp.concatenate([c.values for c in per_rank]),
        dense_size=per_rank[0].dense_size,
    )
    out = sparse_allreduce_average(glob, mesh)
    expect = np.mean(
        [np.asarray(c.to_dense()) for c in per_rank], axis=0
    )
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6, atol=1e-7)


def test_sparse_all_reduce_local_inside_jit():
    mesh = build_mesh(data_parallel_size=8)
    dense = _sparse_dense()
    csr = CSRTensor.from_dense(dense, max_rows=6)
    # replicate the same csr on all ranks: sum = 8x single
    idx = jnp.tile(csr.indices, 8)
    val = jnp.tile(csr.values, (8, 1))
    from jax.sharding import PartitionSpec as P

    fn = jax.jit(
        shard_map(
            lambda i, v: sparse_all_reduce_local(i, v, csr.dense_size),
            mesh=mesh,
            in_specs=(P("data"), P("data")),
            out_specs=P(),
            check=False,
        )
    )
    out = fn(idx, val)
    np.testing.assert_allclose(
        np.asarray(out), 8 * np.asarray(dense), rtol=1e-6
    )


# ---------------------------------------------------------------------------
# Engine wiring: sparse_gradients config routes embedding grads through the
# sparse all-reduce (reference deepspeed_light.py:177-184, 1037-1093)
# ---------------------------------------------------------------------------
def test_sparse_embedding_lookup_grad_matches_dense():
    from deepspeed_tpu.runtime.sparse import sparse_embedding_lookup

    mesh = build_mesh(data_parallel_size=8)
    table = jnp.asarray(np.random.default_rng(0).standard_normal((64, 16)), jnp.float32)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 64, (8, 4)), jnp.int32)
    w = jnp.asarray(np.random.default_rng(2).standard_normal((8, 4, 16)), jnp.float32)

    def loss_sparse(t):
        return jnp.sum(sparse_embedding_lookup(t, ids, mesh) * w)

    def loss_dense(t):
        return jnp.sum(t[ids] * w)

    gs = jax.jit(jax.grad(loss_sparse))(table)
    gd = jax.grad(loss_dense)(table)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gd), atol=1e-5)


def test_engine_sparse_gradients_parity_with_dense():
    """engine config {sparse_gradients: true} must train identically to the
    dense path for a sparsely-touched embedding (engine-level wiring test:
    the engine injects the flag into the model config and the sparse
    collective runs inside the jitted step)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadModel

    def make_engine(sparse):
        cfg = GPT2Config(
            vocab_size=256, n_positions=16, n_embd=32, n_layer=1, n_head=2,
            dropout=0.0,
        )
        model = GPT2LMHeadModel(cfg)
        ids0 = jnp.asarray(np.random.default_rng(0).integers(0, 256, (8, 16)), jnp.int32)
        params = model.init(
            {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
            ids0, ids0,
        )["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model,
            model_parameters=params,
            config_params={
                "train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "sparse_gradients": sparse,
                "steps_per_print": 10_000,
            },
            rng_seed=0,
        )
        if sparse:
            assert model.config.sparse_gradients, "engine did not inject flag"
            assert model.config.mesh is not None, "engine did not inject mesh"
        return engine

    rng = np.random.default_rng(7)
    batches = [rng.integers(0, 256, (8, 16)).astype(np.int32) for _ in range(5)]

    losses = {}
    params = {}
    for sparse in (False, True):
        e = make_engine(sparse)
        ls = []
        for ids in batches:
            loss = e(ids, ids)
            e.backward(loss)
            e.step()
            ls.append(float(loss))
        losses[sparse] = ls
        params[sparse] = jax.tree_util.tree_map(np.asarray, e.params)
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(params[True]),
        jax.tree_util.tree_leaves(params[False]),
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)
