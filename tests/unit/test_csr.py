"""CSR row-sparse tensor tests (reference tests/unit/test_csr.py:
round-trip; plus the TPU additions: capacity bounding and the sharded
sparse allreduce)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.sparse import (
    CSRTensor,
    sparse_all_reduce_local,
    sparse_allreduce_average,
)


def _sparse_dense(rows=32, cols=8, nnz=5, seed=0):
    rng = np.random.default_rng(seed)
    dense = np.zeros((rows, cols), np.float32)
    idx = rng.choice(rows, nnz, replace=False)
    dense[idx] = rng.standard_normal((nnz, cols))
    return jnp.asarray(dense)


def test_csr_roundtrip():
    dense = _sparse_dense()
    csr = CSRTensor.from_dense(dense)
    np.testing.assert_array_equal(np.asarray(csr.to_dense()), np.asarray(dense))


def test_csr_capacity_bounded_roundtrip():
    dense = _sparse_dense(nnz=5)
    csr = CSRTensor.from_dense(dense, max_rows=8)  # capacity > nnz: lossless
    assert csr.values.shape == (8, 8)
    np.testing.assert_array_equal(np.asarray(csr.to_dense()), np.asarray(dense))


def test_csr_add_concatenates():
    a = CSRTensor.from_dense(_sparse_dense(seed=0), max_rows=4)
    b = CSRTensor.from_dense(_sparse_dense(seed=1), max_rows=4)
    expect = np.asarray(a.to_dense()) + np.asarray(b.to_dense())
    a.add(b)
    np.testing.assert_allclose(np.asarray(a.to_dense()), expect, rtol=1e-6)


def test_csr_reduction_factor_reported():
    csr = CSRTensor.from_dense(_sparse_dense(rows=64, nnz=4), max_rows=4)
    sparse_size, dense_size = csr.sparse_size()
    assert dense_size == 64 * 8
    assert sparse_size == 4 + 4 * 8
    assert "reduction_factor" in repr(csr)


def test_sparse_all_reduce_matches_dense_psum():
    mesh = build_mesh(data_parallel_size=8)
    # one distinct sparse grad per rank: global leading dim 8*k
    per_rank = [
        CSRTensor.from_dense(_sparse_dense(seed=s), max_rows=6) for s in range(8)
    ]
    glob = CSRTensor(
        indices=jnp.concatenate([c.indices for c in per_rank]),
        values=jnp.concatenate([c.values for c in per_rank]),
        dense_size=per_rank[0].dense_size,
    )
    out = sparse_allreduce_average(glob, mesh)
    expect = np.mean(
        [np.asarray(c.to_dense()) for c in per_rank], axis=0
    )
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6, atol=1e-7)


def test_sparse_all_reduce_local_inside_jit():
    mesh = build_mesh(data_parallel_size=8)
    dense = _sparse_dense()
    csr = CSRTensor.from_dense(dense, max_rows=6)
    # replicate the same csr on all ranks: sum = 8x single
    idx = jnp.tile(csr.indices, 8)
    val = jnp.tile(csr.values, (8, 1))
    from jax.sharding import PartitionSpec as P

    fn = jax.jit(
        jax.shard_map(
            lambda i, v: sparse_all_reduce_local(i, v, csr.dense_size),
            mesh=mesh,
            in_specs=(P("data"), P("data")),
            out_specs=P(),
            check_vma=False,
        )
    )
    out = fn(idx, val)
    np.testing.assert_allclose(
        np.asarray(out), 8 * np.asarray(dense), rtol=1e-6
    )
