"""Whole-node failure domain tests (deepspeed_tpu/serving/provisioner.py
+ node.py epoch fencing + autoscaler.py node tier, docs/serving.md "Node
failure domain" / "Epoch fencing"): the fencing handshake on both the
control and data planes (reject below high-water, raise on >=, epoch-less
back-compat, terminal no-reconnect-through-the-fence), the router's loud
stand-down when one of its replicas is fenced, incarnation-epoch
monotonicity across journal recoveries, the node.crash / node.partition
chaos sites, the provisioner seam (StaticProvisioner against in-process
agents, LocalSubprocessProvisioner against one real forked agent), and
the SocketNodeProvider's node-tier escalation: typed NoPlaceableCapacity
refusals, re-provision-under-the-same-name, mint-new-node, and
drain-then-terminate on the last retire.

Everything except the one LocalSubprocessProvisioner test is jax-free
and fork-free: node agents are in-process NodeServers hosting worker.py's
StubWorkerEngine (answers are a pure function of the prompt)."""

import os
import signal
import socket
import time

import pytest

from deepspeed_tpu.inference.paging import PoolExhausted
from deepspeed_tpu.inference.scheduler import (
    ContinuousBatchingScheduler,
    RequestRejected,
)
from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec
from deepspeed_tpu.serving import (
    FleetRouter,
    LocalSubprocessProvisioner,
    NodeHandle,
    NodeProvisioner,
    NoPlaceableCapacity,
    ProvisionFailed,
    SocketNodeProvider,
    StaticProvisioner,
)
from deepspeed_tpu.serving.journal import FleetJournal, load_journal_state
from deepspeed_tpu.serving.node import NodeServer
from deepspeed_tpu.serving.replica import FencedOut, ReplicaBase
from deepspeed_tpu.serving.transport import NodeControlClient, SocketReplica
from deepspeed_tpu.telemetry.registry import (
    MetricsRegistry,
    suppressed_errors_snapshot,
)
from deepspeed_tpu.telemetry.tracing import SpanTracer


def _expected_answer(prompt, max_new):
    base = prompt[-1] if prompt else 0
    return [(base + i + 1) % 1000 for i in range(max_new)]


def _node(replicas=("r0",), *, delay=0.02, config=None, node_id="n0",
          spawn_spec=None):
    spec = {
        "node_id": node_id,
        "replicas": {
            name: {"stub": {"delay_secs": delay}} for name in replicas
        },
        "lease_secs": 5.0,
        "resume_grace_secs": 5.0,
    }
    if spawn_spec is not None:
        spec["spawn_spec"] = spawn_spec
    if config is not None:
        spec["config"] = config
    return NodeServer(spec)


def _replica(node, name="r0", *, rid=None, faults=None, epoch=None,
             rpc_timeout=2.0, rpc_retries=1, **kw):
    host, port = node.address
    return SocketReplica(
        rid or f"{node.node_id}:{name}", (host, port), remote_name=name,
        rpc_timeout=rpc_timeout, rpc_retries=rpc_retries,
        rpc_backoff_secs=0.01, reconnect_backoff_secs=0.02,
        reconnect_attempts=3, fault_injector=faults, epoch=epoch, **kw,
    )


def _ctl(node_or_addr, *, epoch=None, timeout=5.0):
    address = (
        node_or_addr.address
        if isinstance(node_or_addr, NodeServer) else node_or_addr
    )
    return NodeControlClient(
        address, connect_timeout=timeout, op_timeout=timeout, epoch=epoch,
    )


def _dead_address():
    """A loopback port with nothing behind it (bound then freed)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    addr = s.getsockname()
    s.close()
    return (addr[0], addr[1])


def _wait(predicate, timeout=30.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# epoch fencing: the control plane
# ---------------------------------------------------------------------------
def test_control_dial_below_high_water_is_fenced_out():
    node = _node()
    node.start()
    try:
        # first epoch-ed dial sets the mark
        info = _ctl(node, epoch=5).node_info()
        assert info["node"] == "n0"
        assert info["epoch_high_water"] == 5
        # a STALE incarnation is rejected with the typed error naming
        # both epochs — exactly what a stood-down router logs
        with pytest.raises(FencedOut) as exc:
            _ctl(node, epoch=3).node_info()
        assert exc.value.epoch == 3
        assert exc.value.high_water == 5
        # equal epoch is the same incarnation reconnecting: admitted
        assert _ctl(node, epoch=5).node_info()["epoch_high_water"] == 5
        # a newer incarnation raises the mark (monotonic, never lowers)
        assert _ctl(node, epoch=7).node_info()["epoch_high_water"] == 7
        with pytest.raises(FencedOut):
            _ctl(node, epoch=5).node_info()
    finally:
        node.shutdown()


def test_epochless_hello_never_fenced():
    """Back-compat: pre-epoch clients (and tests) fence nothing and are
    never fenced, even after the high-water mark has risen."""
    node = _node()
    node.start()
    replica = _replica(node)  # no epoch
    try:
        _ctl(node, epoch=9).node_info()
        info = _ctl(node).node_info()  # epoch-less control dial
        assert info["epoch_high_water"] == 9
        replica.start()  # epoch-less data session
        req = replica.submit([7], max_new_tokens=2)
        assert req.result(30.0) == _expected_answer([7], 2)
    finally:
        replica.shutdown()
        node.shutdown()


# ---------------------------------------------------------------------------
# epoch fencing: the data plane
# ---------------------------------------------------------------------------
def test_stale_data_session_fenced_on_start():
    node = _node()
    node.start()
    try:
        _ctl(node, epoch=5).node_info()
        replica = _replica(node, epoch=3)
        with pytest.raises(FencedOut) as exc:
            replica.start()
        assert exc.value.high_water == 5
        assert replica.fenced is True
        replica.shutdown()
    finally:
        node.shutdown()


def test_fenced_replica_never_reconnects_through_the_fence():
    """A replica whose epoch was superseded MID-LIFE (a newer router
    adopted the node) discovers the fence at its next reconnect and
    fails TERMINALLY: no retry loop hammers the node, in-flight requests
    fail for re-route, and the fenced flag (the router's stand-down
    signal) latches."""
    # frames: (1) the post-start snapshot, (2) submit — the armed RST
    # then fires on the session's next frame, mid-generation, and the
    # reconnect walks into the already-raised fence
    faults = FaultInjector(
        [FaultSpec("conn.reset", after=2, times=1, seed=0)], seed=0
    )
    node = _node(delay=0.5)
    node.start()
    replica = _replica(node, faults=faults, epoch=3)
    try:
        before = suppressed_errors_snapshot().get(
            "internal/suppressed_errors/serving.net_fenced_out", 0
        )
        replica.start()
        assert replica.load_snapshot()["alive"]
        # a newer incarnation takes the node over BEFORE the drop, so
        # the reconnect outcome is deterministic: fenced, not resumed
        _ctl(node, epoch=9).node_info()
        req = replica.submit([7], max_new_tokens=4)
        replica.load_snapshot()  # hits the armed RST, drops the socket
        assert faults.injected["conn.reset"] == 1
        assert _wait(lambda: replica.fenced and replica.failed, 15.0)
        assert replica.alive is False
        assert _wait(lambda: req.done, 15.0)
        assert req.finish_reason == "error"
        assert suppressed_errors_snapshot().get(
            "internal/suppressed_errors/serving.net_fenced_out", 0
        ) > before
    finally:
        replica.shutdown()
        node.shutdown()


# ---------------------------------------------------------------------------
# the router stands down loudly when fenced
# ---------------------------------------------------------------------------
class _FencedStub(ReplicaBase):
    """The router-facing contract of a replica the node fenced out."""

    def __init__(self, replica_id):
        super().__init__(replica_id)
        self.failed = False
        self.fenced = False

    def start(self):
        return self

    def submit(self, prompt_tokens, **kwargs):
        raise RuntimeError("stub never takes traffic")

    def _snapshot_now(self):
        return {
            "alive": not self.failed, "failed": self.failed,
            "queue_depth": 0, "queue_capacity": 8, "active_slots": 0,
            "free_slots": 2, "num_slots": 2, "health": 0,
            "mean_prefill_ms": 0.0, "mean_decode_ms": 0.0,
            "mean_queue_wait_ms": 0.0, "requests_shed": 0.0,
            "restarts_used": 0, "requests_completed": 0,
            "tokens_generated": 0, "driving": True, "stopped": False,
            "driver_failed": False,
        }

    def drain(self):
        pass

    def restart(self):
        return self

    def shutdown(self):
        pass


def test_router_stands_down_when_any_replica_is_fenced():
    healthy = _FencedStub("0")
    doomed = _FencedStub("1")
    router = FleetRouter(
        [healthy, doomed], monitor_interval=0.002,
    ).start()
    try:
        assert router.fenced is False
        # the node rejects this router's epoch: the transport latches
        # fenced AND failed (terminal), the sweep notices
        doomed.fenced = True
        doomed.failed = True
        assert _wait(lambda: router.fenced, 15.0)
        assert "1" in router.evicted_ids
        # split-brain safety beats availability: a healthy replica
        # remains, but NO traffic belongs on a stale incarnation
        ready, reasons = router.readiness()
        assert not ready and "fenced_out" in reasons
        with pytest.raises(RequestRejected) as exc:
            router.submit([1], max_new_tokens=1)
        assert exc.value.reason == "fenced_out"
        assert router.no_capacity_cause()["fenced"] is True
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# incarnation epochs are monotonic across journal recoveries
# ---------------------------------------------------------------------------
def test_incarnation_monotonic_across_recoveries(tmp_path):
    j1 = FleetJournal(tmp_path, fsync=False)
    assert j1.incarnation == 1
    j1.set_brownout(False)  # force a commit so recovery has a segment
    j1.close()
    state, info = load_journal_state(str(tmp_path))
    assert info["status"] in ("ok", "recovered")
    j2 = FleetJournal(tmp_path, fsync=False, state=state)
    assert j2.incarnation == 2  # adopted: strictly above the old life
    j2.set_brownout(False)
    j2.close()
    state2, _ = load_journal_state(str(tmp_path))
    assert state2["incarnation"] == 2
    j3 = FleetJournal(tmp_path, fsync=False, state=state2)
    assert j3.incarnation == 3  # and again: 1 -> 2 -> 3, never back
    j3.close()


def test_explicit_incarnation_override(tmp_path):
    j = FleetJournal(tmp_path, fsync=False, incarnation=41)
    assert j.incarnation == 41
    j.close()


# ---------------------------------------------------------------------------
# chaos sites at the node-agent seam
# ---------------------------------------------------------------------------
class _OSProxy:
    """``os`` with ``kill`` recorded instead of delivered — the
    node.crash site would SIGKILL the pytest process otherwise."""

    def __init__(self, real):
        self._real = real
        self.kills = []

    def kill(self, pid, sig):
        self.kills.append((pid, sig))

    def __getattr__(self, name):
        return getattr(self._real, name)


def test_node_crash_site_sigkills_the_whole_agent(monkeypatch):
    import deepspeed_tpu.serving.node as node_mod

    proxy = _OSProxy(os)
    monkeypatch.setattr(node_mod, "os", proxy)
    node = _node(config={"resilience": {"fault_injection": {
        "enabled": True, "seed": 0,
        "faults": [{"site": "node.crash", "after": 1, "times": 1}],
    }}})
    node.start()
    try:
        assert _ctl(node).node_info()["node"] == "n0"  # op 1: survives
        _ctl(node).node_info()  # op 2: the injected host death
        assert proxy.kills == [(os.getpid(), signal.SIGKILL)]
    finally:
        node.shutdown()


def test_node_partition_drop_absorbed_by_idempotent_retry():
    """node.partition black-holes ONE node->client event frame after the
    node considers it sent; the client's rpc timeout notices and the
    idempotent retry repairs the loss — bitwise-identical answers, one
    accounted drop."""
    node = _node(config={"resilience": {"fault_injection": {
        "enabled": True, "seed": 0,
        "faults": [{"site": "node.partition", "times": 1}],
    }}})
    node.start()
    replica = _replica(node, rpc_timeout=0.5, rpc_retries=3)
    before = suppressed_errors_snapshot().get(
        "internal/suppressed_errors/serving.node_partition_drop", 0
    )
    try:
        # the session's FIRST emitted frame is the one black-holed —
        # whichever reply that turns out to be, the client's retry
        # machinery absorbs it invisibly
        replica.start()
        snap = replica.load_snapshot()
        assert snap["alive"] and not snap["failed"]
        assert suppressed_errors_snapshot().get(
            "internal/suppressed_errors/serving.node_partition_drop", 0
        ) == before + 1
        req = replica.submit([3], max_new_tokens=3)
        assert req.result(30.0) == _expected_answer([3], 3)
        assert replica.failed is False
    finally:
        replica.shutdown()
        node.shutdown()


# ---------------------------------------------------------------------------
# StaticProvisioner: the fork-free seam
# ---------------------------------------------------------------------------
def test_static_provisioner_confirms_and_forgets():
    node = _node(node_id="ext0")
    node.start()
    try:
        prov = StaticProvisioner({"ext0": node.address}, epoch=4)
        handle = prov.launch_node("ext0")
        assert handle.name == "ext0"
        assert handle.address == node.address
        assert handle.alive  # no proc: externally owned, assumed alive
        assert list(prov.list_nodes()) == ["ext0"]
        # the confirm dial carried epoch 4: the node is now fenced
        # against anything older
        with pytest.raises(FencedOut):
            _ctl(node, epoch=3).node_info()
        # terminate only forgets — the process belongs to someone else
        prov.terminate_node("ext0")
        assert prov.list_nodes() == {}
        assert _ctl(node).node_info()["node"] == "ext0"
        with pytest.raises(KeyError):
            prov.terminate_node("ext0")
    finally:
        node.shutdown()


def test_static_provisioner_unknown_name_and_dead_address():
    prov = StaticProvisioner(confirm_timeout=0.5)
    with pytest.raises(ProvisionFailed, match="knows no address"):
        prov.launch_node("ghost")
    prov.register("deadbeat", _dead_address())
    with pytest.raises(ProvisionFailed, match="health"):
        prov.launch_node("deadbeat")
    assert prov.list_nodes() == {}  # a failed launch owns nothing


# ---------------------------------------------------------------------------
# LocalSubprocessProvisioner: one real forked agent, end to end
# ---------------------------------------------------------------------------
def test_local_subprocess_provisioner_launch_fence_terminate():
    reg = MetricsRegistry()
    prov = LocalSubprocessProvisioner(
        {"replicas": {"r0": {"stub": {"delay_secs": 0.01}}},
         "lease_secs": 5.0, "resume_grace_secs": 5.0},
        launch_timeout=60.0, terminate_grace=5.0, epoch=7, registry=reg,
    )
    try:
        handle = prov.launch_node("pnA")
        assert handle.alive and handle.name == "pnA"
        assert list(prov.list_nodes()) == ["pnA"]
        info = _ctl(handle.address, timeout=30.0).node_info()
        assert info["node"] == "pnA" and info["replicas"] == ["r0"]
        # the health-confirm dial stamped the launching router's epoch
        assert info["epoch_high_water"] == 7
        with pytest.raises(FencedOut):
            _ctl(handle.address, epoch=5, timeout=30.0).node_info()
        # a second launch under a live name is refused, not doubled
        with pytest.raises(ProvisionFailed, match="already owns"):
            prov.launch_node("pnA")
        assert reg.counter("fleet/nodes_provisioned").value == 1
        prov.terminate_node("pnA")
        assert handle.proc.poll() is not None  # really dead
        assert prov.list_nodes() == {}
        assert reg.counter("fleet/nodes_terminated").value == 1
        with pytest.raises(KeyError):
            prov.terminate_node("pnA")
    finally:
        prov.close()


def test_local_subprocess_launch_failure_leaks_no_process():
    prov = LocalSubprocessProvisioner(launch_timeout=60.0)
    # an empty replicas map with no spawn_spec is rejected by the agent
    # before it announces: the launch must fail typed AND clean up
    with pytest.raises(ProvisionFailed, match="exited before announcing"):
        prov.launch_node("broken", spec={"replicas": {}})
    assert prov.list_nodes() == {}
    prov.close()


# ---------------------------------------------------------------------------
# SocketNodeProvider: the node tier
# ---------------------------------------------------------------------------
class _ServerProvisioner(NodeProvisioner):
    """Real in-process NodeServers behind the provisioner seam — the
    node tier's behavior without fork cost. Launched nodes start EMPTY
    (spawn_spec only) so a retire can empty them."""

    def __init__(self):
        self.servers = {}
        self.owned = {}
        self.launches = []
        self.terminated = []

    def launch_node(self, name, spec=None):
        server = NodeServer({
            "node_id": name, "replicas": {},
            "spawn_spec": {"stub": {"delay_secs": 0.01}},
            "lease_secs": 5.0, "resume_grace_secs": 5.0,
        })
        server.start()
        self.servers[name] = server
        handle = NodeHandle(name, server.address)
        self.owned[name] = handle
        self.launches.append(name)
        return handle

    def terminate_node(self, name):
        handle = self.owned.pop(str(name))
        server = self.servers.pop(str(name), None)
        if server is not None:
            server.shutdown()
        self.terminated.append(str(name))
        return handle

    def list_nodes(self):
        return dict(self.owned)


def _provider(nodes, **kw):
    kw.setdefault("rpc_timeout", 2.0)
    kw.setdefault("connect_timeout", 2.0)
    kw.setdefault("spawn_timeout", 30.0)
    kw.setdefault("node_retry_secs", 30.0)
    return SocketNodeProvider(nodes, **kw)


def test_spawn_without_provisioner_raises_typed_refusal():
    node = _node()
    node.start()
    try:
        provider = _provider(
            {"n0": {"address": node.address}}, max_replicas_per_node=1,
        )
        with pytest.raises(NoPlaceableCapacity) as exc:
            provider.spawn({"n0:r0"})  # n0 already at its ceiling
        assert exc.value.reason == "no_placeable_capacity"
        assert "no provisioner" in str(exc.value)
    finally:
        node.shutdown()


def test_full_fleet_at_max_nodes_refuses_typed():
    node = _node()
    node.start()
    try:
        provider = _provider(
            {"n0": {"address": node.address}},
            provisioner=_ServerProvisioner(),
            max_replicas_per_node=1, max_nodes=1,
        )
        with pytest.raises(NoPlaceableCapacity, match="max_nodes"):
            provider.spawn({"n0:r0"})
    finally:
        node.shutdown()


def test_capacity_past_every_ceiling_mints_a_new_node():
    node = _node()
    node.start()
    prov = _ServerProvisioner()
    provider = _provider(
        {"n0": {"address": node.address}}, provisioner=prov,
        max_replicas_per_node=1, max_nodes=2,
    )
    replica = None
    try:
        replica = provider.spawn({"n0:r0"})
        assert replica.replica_id == "pn0:as0"
        assert prov.launches == ["pn0"]
        assert "pn0" in provider._addresses
        req = replica.submit([9], max_new_tokens=2)
        assert req.result(30.0) == _expected_answer([9], 2)
    finally:
        if replica is not None:
            replica.shutdown()
        provider.close()
        node.shutdown()


def test_dead_node_reprovisions_under_the_same_name():
    prov = _ServerProvisioner()
    provider = _provider(
        {"n0": {"address": _dead_address()}}, provisioner=prov,
        max_replicas_per_node=2, max_nodes=1,
    )
    replica = None
    try:
        # first spawn dials the corpse: the failure backs the node off
        with pytest.raises(OSError):
            provider.spawn(set())
        # next spawn escalates to the node tier: the backed-off node is
        # re-provisioned under ITS OWN name at a fresh address
        replica = provider.spawn(set())
        # as1, not as0: the failed first spawn consumed a name before
        # its dial refused — minted ids are never reused, even wasted
        assert replica.replica_id == "n0:as1"
        assert prov.launches == ["n0"]
        assert provider._addresses["n0"] == prov.servers["n0"].address
        req = replica.submit([4], max_new_tokens=2)
        assert req.result(30.0) == _expected_answer([4], 2)
    finally:
        if replica is not None:
            replica.shutdown()
        provider.close()


def test_retire_emptying_provisioned_node_terminates_it():
    node = _node()
    node.start()
    prov = _ServerProvisioner()
    provider = _provider(
        {"n0": {"address": node.address}}, provisioner=prov,
        max_replicas_per_node=1, max_nodes=2,
    )
    try:
        replica = provider.spawn({"n0:r0"})
        assert replica.replica_id == "pn0:as0"
        provider.retire(replica)
        # drain-then-terminate: the retire emptied a provisioner-owned
        # node, so the whole host is released and its address backed
        # off — the next pick must not dial the corpse
        assert prov.terminated == ["pn0"]
        assert "pn0" in provider._node_failed_at
        assert provider._pick_node(set()) == "n0"
    finally:
        provider.close()
        node.shutdown()


def test_note_live_ids_counts_capacity_from_live_view():
    """Eviction must free a node's capacity accounting: ids the router
    evicted still block name-minting (never reuse an id) but no longer
    hold replica slots."""
    node = _node()
    node.start()
    replica = None
    try:
        provider = _provider(
            {"n0": {"address": node.address}}, max_replicas_per_node=1,
        )
        everything = {"n0:r0"}  # journaled/evicted history
        provider.note_live_ids([])  # but nothing is LIVE on n0
        replica = provider.spawn(everything)
        assert replica.replica_id == "n0:as0"  # minted clear of r0
        req = replica.submit([2], max_new_tokens=2)
        assert req.result(30.0) == _expected_answer([2], 2)
    finally:
        if replica is not None:
            replica.shutdown()
        node.shutdown()


# ---------------------------------------------------------------------------
# host-tier preemption is priority-classed
# ---------------------------------------------------------------------------
class _PreemptEngine:
    """Scheduler-facing fake whose KV pool 'fits one': the first
    capacity check that sees two active slots raises PoolExhausted
    once, forcing exactly one preemption — so the victim CHOICE is the
    whole observable."""

    prefill_len = 16

    def __init__(self):
        self.raised = False

    def prefill_request(self, slot, prompt_tokens, temperature):
        del prompt_tokens, temperature
        return 100 + slot

    def decode_tokens(self, active):
        return [7 for _ in active]

    def ensure_decode_capacity(self, active):
        if len(active) >= 2 and not self.raised:
            self.raised = True
            raise PoolExhausted(1, 0)


def _preempt_scheduler():
    tracer = SpanTracer(ring_events=64)
    sched = ContinuousBatchingScheduler(
        _PreemptEngine(), num_slots=2, max_seq_len=32, queue_depth=8,
        queue_timeout=0.1, eos_token_id=None, temperature=0.0,
        registry=MetricsRegistry(), tracer=tracer,
    )
    return sched, tracer


def _preempted_ids(tracer):
    return [
        e["attrs"]["request_id"] for e in tracer.flight_snapshot()
        if e["name"] == "sched.preempt"
    ]


def test_preemption_parks_lowest_priority_class_first():
    """KV page pressure must never evict a protected tenant's
    generation for a sheddable one: the OLDER low-priority request
    parks (under admission-order-only victim choice the newest — the
    priority-0 request — would have gone)."""
    sched, tracer = _preempt_scheduler()
    low = sched.submit([1, 2], max_new_tokens=3, priority=1)
    high = sched.submit([3, 4], max_new_tokens=3, priority=0)
    sched.run_until_idle()
    assert len(low.result(10.0)) == 3
    assert len(high.result(10.0)) == 3
    assert low.finish_reason == "max_new_tokens"
    assert high.finish_reason == "max_new_tokens"
    # the sheddable request was the victim — and it still completed,
    # resumed suffix-only after the parked interval
    assert _preempted_ids(tracer) == [low.request_id]


def test_preemption_within_class_parks_newest_first():
    sched, tracer = _preempt_scheduler()
    older = sched.submit([1, 2], max_new_tokens=3, priority=1)
    newer = sched.submit([3, 4], max_new_tokens=3, priority=1)
    sched.run_until_idle()
    assert len(older.result(10.0)) == 3
    assert len(newer.result(10.0)) == 3
    # equal classes keep the old policy: most recently admitted goes
    assert _preempted_ids(tracer) == [newer.request_id]
