"""Launcher unit tests (reference analog: tests/unit/test_run.py — pure
functions, no processes; plus a real single-node end-to-end launch)."""

import base64
import json
import subprocess
import sys

import pytest

from deepspeed_tpu.launcher import launch as dsl
from deepspeed_tpu.launcher import runner as dsr


@pytest.fixture
def hostfile(tmp_path):
    def _write(text):
        p = tmp_path / "hostfile"
        p.write_text(text)
        return str(p)

    return _write


def test_fetch_hostfile(hostfile):
    path = hostfile("worker-0 slots=4\nworker-1 slots=2\n\n# comment\n")
    pool = dsr.fetch_hostfile(path)
    assert list(pool.items()) == [("worker-0", 4), ("worker-1", 2)]


def test_fetch_hostfile_missing_returns_none(tmp_path):
    assert dsr.fetch_hostfile(str(tmp_path / "nope")) is None


def test_fetch_hostfile_bad_format(hostfile):
    with pytest.raises(ValueError):
        dsr.fetch_hostfile(hostfile("worker-0 4\n"))


def test_fetch_hostfile_duplicate(hostfile):
    with pytest.raises(ValueError, match="already defined"):
        dsr.fetch_hostfile(hostfile("w0 slots=4\nw0 slots=4\n"))


def _pool(**kw):
    import collections

    return collections.OrderedDict(kw)


def test_include_filter():
    pool = _pool(w0=4, w1=4)
    active = dsr.parse_inclusion_exclusion(pool, "w0@w1:0,2", "")
    assert active == {"w0": [0, 1, 2, 3], "w1": [0, 2]}


def test_exclude_filter():
    pool = _pool(w0=4, w1=4)
    active = dsr.parse_inclusion_exclusion(pool, "", "w1:0")
    assert active == {"w0": [0, 1, 2, 3], "w1": [1, 2, 3]}


def test_exclude_whole_node_drops_host():
    pool = _pool(w0=2, w1=2)
    active = dsr.parse_inclusion_exclusion(pool, "", "w1")
    assert list(active.keys()) == ["w0"]


def test_include_exclude_mutually_exclusive():
    with pytest.raises(ValueError, match="mutually exclusive"):
        dsr.parse_inclusion_exclusion(_pool(w0=2), "w0", "w0")


def test_filter_unknown_host_and_slot():
    with pytest.raises(ValueError, match="not found"):
        dsr.parse_inclusion_exclusion(_pool(w0=2), "w9", "")
    with pytest.raises(ValueError, match="No slot"):
        dsr.parse_inclusion_exclusion(_pool(w0=2), "w0:7", "")


def test_filter_preserves_hostfile_order():
    pool = _pool(a=2, b=2, c=2)
    active = dsr.parse_inclusion_exclusion(pool, "c@a", "")
    assert list(active.keys()) == ["a", "c"]


def test_world_info_roundtrip():
    info = {"w0": [0, 1], "w1": [2]}
    enc = dsr.encode_world_info(info)
    assert dsl.decode_world_info(enc) == info
    # urlsafe base64 of compact json
    assert json.loads(base64.urlsafe_b64decode(enc)) == info


def test_resolve_node_rank_numeric_and_hostname():
    info = {"hostA": [0], "hostB": [0]}

    class A:
        node_rank = "1"

    assert dsl.resolve_node_rank(A, info) == 1

    class B:
        node_rank = "%n"  # pdsh token never substituted -> hostname lookup

    import socket

    info2 = {socket.gethostname(): [0], "other": [0]}
    assert dsl.resolve_node_rank(B, info2) == 0


def test_build_env_sets_coordinator_vars():
    class Args:
        master_addr = "10.0.0.1"
        master_port = 29501

    info = {"h0": [0, 1], "h1": [0, 1]}
    env = dsl.build_env(Args, info, 1)
    assert env["DS_TPU_COORDINATOR_ADDRESS"] == "10.0.0.1:29501"
    assert env["DS_TPU_NUM_PROCESSES"] == "2"
    assert env["DS_TPU_PROCESS_ID"] == "1"
    assert env["RANK"] == "1" and env["WORLD_SIZE"] == "2"
    assert env["DS_TPU_LOCAL_CHIPS"] == "0,1"


def test_single_node_end_to_end(tmp_path):
    """bin/deepspeed-equivalent single-node launch runs the user script with
    the launcher env set."""
    script = tmp_path / "user.py"
    script.write_text(
        "import os\n"
        "print('RANK=' + os.environ.get('RANK', 'missing'))\n"
        "print('WS=' + os.environ.get('WORLD_SIZE', 'missing'))\n"
    )
    out = subprocess.run(
        [
            sys.executable, "-m", "deepspeed_tpu.launcher.runner",
            "--hostfile", str(tmp_path / "absent"), str(script),
        ],
        capture_output=True, text=True, timeout=120, cwd="/root/repo",
    )
    assert out.returncode == 0, out.stderr
    assert "RANK=0" in out.stdout
    assert "WS=1" in out.stdout


# ------------------------------------------------------- TPU pod discovery
def test_discover_tpu_pod_from_metadata():
    """On-pod path: worker-network-endpoints + accelerator-type attributes
    become the resource pool (no hostfile — VERDICT r02 item 8)."""
    meta = {
        "worker-network-endpoints": "w0:10.0.0.2:8470,w1:10.0.0.3:8470",
        "accelerator-type": "v5litepod-8",
    }
    pool = dsr.discover_tpu_pod(
        "mypod", metadata_get=meta.get, gcloud_describe=lambda n: None
    )
    assert list(pool.items()) == [("10.0.0.2", 4), ("10.0.0.3", 4)]


def test_discover_tpu_pod_bare_ip_endpoints():
    meta = {"worker-network-endpoints": "10.0.0.2, 10.0.0.3 ,10.0.0.4",
            "accelerator-type": "v5litepod-4"}
    pool = dsr.discover_tpu_pod(
        "p", metadata_get=meta.get, gcloud_describe=lambda n: None
    )
    # 4 chips over 3 hosts -> 1 slot each (floor), never 0
    assert list(pool.items()) == [
        ("10.0.0.2", 1), ("10.0.0.3", 1), ("10.0.0.4", 1)
    ]


def test_discover_tpu_pod_via_gcloud():
    """Off-pod fallback: gcloud describe JSON supplies the endpoints."""
    desc = {
        "acceleratorType": "v4-16",
        "networkEndpoints": [
            {"ipAddress": "10.1.0.2"}, {"ipAddress": "10.1.0.3"},
        ],
    }
    pool = dsr.discover_tpu_pod(
        "mypod", metadata_get=lambda a: None, gcloud_describe=lambda n: desc
    )
    assert list(pool.keys()) == ["10.1.0.2", "10.1.0.3"]
    assert all(s == 4 for s in pool.values())


def test_discover_tpu_pod_unresolvable_raises():
    with pytest.raises(RuntimeError, match="could not discover"):
        dsr.discover_tpu_pod(
            "nope", metadata_get=lambda a: None, gcloud_describe=lambda n: None
        )


def test_parse_worker_endpoints_formats():
    assert dsr._parse_worker_endpoints("uid:1.2.3.4:8470") == ["1.2.3.4"]
    assert dsr._parse_worker_endpoints("1.2.3.4;5.6.7.8") == [
        "1.2.3.4", "5.6.7.8"
    ]
