"""Pretrained-checkpoint importer tests (tools/import_bert_checkpoint.py).

The importer is what lights up the real-data SQuAD gate (reference:
tests/model/BingBertSquad/test_e2e_squad.py:40-58 fine-tunes from a
pretrained BERT): a torch/HF ``state_dict`` becomes this repo's scanned
12-param layout. Parity here is asserted against the actual HF
``transformers`` torch model on random weights — logits must match to
float tolerance through embeddings, all encoder layers, and both heads.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp
from flax import serialization

from deepspeed_tpu.models import BertConfig, BertForQuestionAnswering
from deepspeed_tpu.models.bert import BertForPreTraining
from tools.import_bert_checkpoint import convert_state_dict

# gelu_new is the tanh approximation — the variant our block computes
# (ops/transformer.py:316); classic BERT's erf-gelu differs by ~1e-3
# which would mask real transposition bugs in this parity test
HF_KW = dict(
    vocab_size=100,
    hidden_size=32,
    num_hidden_layers=2,
    num_attention_heads=4,
    intermediate_size=64,
    max_position_embeddings=32,
    type_vocab_size=2,
    hidden_act="gelu_new",
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
)


def _our_config():
    return BertConfig(
        vocab_size=HF_KW["vocab_size"],
        hidden_size=HF_KW["hidden_size"],
        num_hidden_layers=HF_KW["num_hidden_layers"],
        num_attention_heads=HF_KW["num_attention_heads"],
        intermediate_size=HF_KW["intermediate_size"],
        max_position_embeddings=HF_KW["max_position_embeddings"],
        hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
        use_flash=False,
    )


def _batch(B=2, S=16, pad_from=12, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, HF_KW["vocab_size"], (B, S)).astype(np.int32)
    mask = np.ones((B, S), np.int32)
    mask[:, pad_from:] = 0  # exercise the padding-mask path end to end
    tt = rng.integers(0, 2, (B, S)).astype(np.int32)
    return ids, mask, tt


def test_qa_logits_match_hf():
    hf = transformers.BertForQuestionAnswering(
        transformers.BertConfig(**HF_KW)
    ).eval()
    params, inferred = convert_state_dict(
        {k: v for k, v in hf.state_dict().items()}, head="qa"
    )
    assert inferred["hidden_size"] == HF_KW["hidden_size"]
    assert inferred["num_hidden_layers"] == HF_KW["num_hidden_layers"]

    model = BertForQuestionAnswering(_our_config())
    ids, mask, tt = _batch()
    with torch.no_grad():
        out = hf(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
            token_type_ids=torch.tensor(tt, dtype=torch.long),
        )
    start, end = model.apply(
        {"params": params}, jnp.asarray(ids), jnp.asarray(mask),
        jnp.asarray(tt), train=False,
    )
    # compare only non-padded positions (HF biases padded logits by -1e4,
    # ours by -1e30 — both are "ignore"; the values there are arbitrary)
    valid = mask.astype(bool)
    np.testing.assert_allclose(
        np.asarray(start)[valid], out.start_logits.numpy()[valid],
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_allclose(
        np.asarray(end)[valid], out.end_logits.numpy()[valid],
        rtol=2e-4, atol=2e-4,
    )


def test_msgpack_roundtrip_into_model_init_structure():
    """The serialized artifact must deserialize against a fresh
    ``model.init`` tree — exactly how tests/model/test_squad_real_data.py
    consumes $BERT_CKPT_MSGPACK."""
    hf = transformers.BertForQuestionAnswering(
        transformers.BertConfig(**HF_KW)
    ).eval()
    params, _ = convert_state_dict(
        {k: v for k, v in hf.state_dict().items()}, head="qa"
    )
    model = BertForQuestionAnswering(_our_config())
    ids, mask, tt = _batch()
    target = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(tt), train=False,
    )["params"]
    restored = serialization.from_bytes(target, serialization.to_bytes(params))
    for a, b in zip(
        jax.tree_util.tree_leaves(restored),
        jax.tree_util.tree_leaves(params),
    ):
        assert a.shape == np.shape(b)
    start1, _ = model.apply(
        {"params": restored}, jnp.asarray(ids), jnp.asarray(mask),
        jnp.asarray(tt), train=False,
    )
    start2, _ = model.apply(
        {"params": params}, jnp.asarray(ids), jnp.asarray(mask),
        jnp.asarray(tt), train=False,
    )
    np.testing.assert_array_equal(np.asarray(start1), np.asarray(start2))


def test_pretraining_head_mlm_parity():
    """MLM logits over REAL vocab entries match HF exactly despite the
    128-aligned vocab padding (padded rows: zero embedding, -1e30 bias —
    exp() of which contributes nothing to any softmax)."""
    hf = transformers.BertForPreTraining(
        transformers.BertConfig(**HF_KW)
    ).eval()
    params, _ = convert_state_dict(
        {k: v for k, v in hf.state_dict().items()}, head="pretraining"
    )
    assert params["bert"]["embeddings"]["word_embeddings"].shape[0] == 128
    assert params["mlm_bias"].shape[0] == 128
    assert np.all(params["mlm_bias"][HF_KW["vocab_size"]:] < -1e29)

    model = BertForPreTraining(_our_config())
    ids, mask, tt = _batch()
    with torch.no_grad():
        out = hf(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
            token_type_ids=torch.tensor(tt, dtype=torch.long),
        )
    # our pretraining model returns the loss; recompute its logits path
    # by calling with labels over every valid position and comparing NLL
    labels = np.where(mask > 0, ids, -1).astype(np.int32)
    loss = model.apply(
        {"params": params}, jnp.asarray(ids), jnp.asarray(mask),
        jnp.asarray(tt), jnp.asarray(labels), None, train=False,
    )
    hf_logits = out.prediction_logits.numpy()  # [B, S, V]
    lse = torch.logsumexp(out.prediction_logits, dim=-1).numpy()
    picked = np.take_along_axis(hf_logits, labels.clip(0)[..., None], -1)[..., 0]
    valid = mask.astype(bool)
    hf_nll = (lse - picked)[valid].mean()
    np.testing.assert_allclose(float(loss), hf_nll, rtol=5e-4)


def test_old_style_gamma_beta_keys():
    """Pre-HF checkpoints name LayerNorm params gamma/beta; the importer
    folds them."""
    hf = transformers.BertForQuestionAnswering(
        transformers.BertConfig(**HF_KW)
    ).eval()
    sd = {}
    for k, v in hf.state_dict().items():
        k = k.replace("LayerNorm.weight", "LayerNorm.gamma")
        k = k.replace("LayerNorm.bias", "LayerNorm.beta")
        sd[k] = v
    params, _ = convert_state_dict(sd, head="qa")
    assert params["bert"]["embeddings"]["LayerNorm"]["scale"].shape == (
        HF_KW["hidden_size"],
    )
