"""bf16/fp32 device-side skip reconciliation.

The jitted update skips the optimizer step on a non-finite global grad norm
(engine update_body's lax.cond) for ALL precisions; only fp16 pays a
per-step host sync to learn about it immediately.  bf16/fp32 stay async and
reconcile the device flag one window late — these tests pin that the
counters (skipped_steps / global_steps) and the LR schedule end up exactly
as truthful as the fp16 path's (reference deepspeed_light.py:858-869).
"""

import flax.linen as nn
import pytest

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, y, train=True):
        h = nn.relu(nn.Dense(32)(x))
        logp = jax.nn.log_softmax(nn.Dense(4)(h))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))


def _data(seed=0, poison=False):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    if poison:
        X[0, 0] = np.nan  # NaN input -> NaN loss -> non-finite grads
    Y = (X[:, 1] > 0).astype(np.int32) + 2 * (X[:, 2] > 0).astype(np.int32)
    return X, Y


def _engine(precision="bf16", with_scheduler=True):
    X, Y = _data()
    model = MLP()
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.asarray(X), jnp.asarray(Y)
    )["params"]
    cfg = {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10_000,
    }
    if precision != "fp32":
        cfg[precision] = {"enabled": True}
    if with_scheduler:
        cfg["scheduler"] = {
            "type": "WarmupLR",
            "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2,
                       "warmup_num_steps": 100},
        }
    engine, _, _, sched = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        mesh=build_mesh(data_parallel_size=8),
        config_params=cfg, rng_seed=0,
    )
    return engine


def _step(engine, poison=False):
    X, Y = _data(poison=poison)
    loss = engine(X, Y)
    engine.backward(loss)
    engine.step()


def test_bf16_skip_reconciles_counters_and_lr():
    engine = _engine("bf16")
    for _ in range(3):
        _step(engine)
    # flags settle one window late; force-settle to read clean state
    engine._reconcile_deferred(keep_last=False)
    assert engine.skipped_steps == 0 and engine.global_steps == 3
    lr_before = engine.get_lr()
    sched_it_before = engine.lr_scheduler.last_batch_iteration

    _step(engine, poison=True)  # device-side skip
    _step(engine)  # next window triggers the lazy reconcile
    engine._reconcile_deferred(keep_last=False)

    assert engine.skipped_steps == 1, engine.skipped_steps
    assert engine.global_steps == 4, engine.global_steps  # 3 clean + 1 clean
    # the skipped window advanced the schedule by exactly zero net ticks:
    # 2 more windows ran, 1 skipped -> exactly 1 net scheduler tick
    assert engine.lr_scheduler.last_batch_iteration == sched_it_before + 1
    # last_overflow reports the CURRENT window only (fp16 semantics); the
    # past skip surfaces via the counters asserted above
    # params stayed finite throughout
    for leaf in jax.tree_util.tree_leaves(engine.params):
        assert np.isfinite(np.asarray(leaf, np.float32)).all()
    del lr_before


def test_bf16_clean_run_has_no_skips_and_no_sync_side_effects():
    engine = _engine("bf16")
    for _ in range(5):
        _step(engine)
    engine._reconcile_deferred(keep_last=False)
    assert engine.skipped_steps == 0
    assert engine.global_steps == 5
    assert engine.lr_scheduler.last_batch_iteration == 4  # started at -1


def test_fp32_skip_reconciles_too():
    engine = _engine("fp32")
    _step(engine)
    _step(engine, poison=True)
    _step(engine)
    engine._reconcile_deferred(keep_last=False)
    assert engine.skipped_steps == 1
    assert engine.global_steps == 2


def test_save_checkpoint_settles_pending_flags(tmp_path):
    engine = _engine("bf16")
    _step(engine)
    _step(engine, poison=True)
    # no further window ran: the poisoned flag is still deferred
    engine.save_checkpoint(str(tmp_path), tag="t")
    assert engine.skipped_steps == 1
    assert engine.global_steps == 1

    fresh = _engine("bf16")
    fresh.load_checkpoint(str(tmp_path), tag="t")
    assert fresh.skipped_steps == 1
    assert fresh.global_steps == 1


def test_load_checkpoint_discards_stale_flags(tmp_path):
    """Flags queued before a restore belong to the discarded timeline —
    reconciling them after load would corrupt the restored counters."""
    engine = _engine("bf16")
    _step(engine)
    engine.save_checkpoint(str(tmp_path), tag="t")
    _step(engine, poison=True)  # queued flag for the post-save window
    assert engine._deferred_overflows
    engine.load_checkpoint(str(tmp_path), tag="t")
    assert engine._deferred_overflows == []
    _step(engine)
    engine._reconcile_deferred(keep_last=False)
    # restored at 1 clean step + 1 clean post-restore step; no phantom skip
    assert engine.skipped_steps == 0
    assert engine.global_steps == 2


def test_train_batch_path_reconciles():
    engine = _engine("bf16")
    accum = engine.gradient_accumulation_steps()

    def window(poison):
        X, Y = _data(poison=poison)
        return [( X, Y )] * accum

    engine.train_batch(iter(window(False)))
    engine.train_batch(iter(window(True)))
    engine.train_batch(iter(window(False)))
    engine._reconcile_deferred(keep_last=False)
    assert engine.skipped_steps == 1
    assert engine.global_steps == 2


def test_monitor_steps_unique_after_reconciled_skip():
    """Round-3/4 known artifact, now fixed: monitor scalars on the async
    path settle WITH the overflow flags and write at the settled step
    index, so a reconciled skip can never make two windows share a step
    number in TensorBoard-style sinks."""

    class RecordingMonitor:
        enabled = True

        def __init__(self):
            self.writes = []

        def write_scalars(self, scalars, step):
            self.writes.append((step, dict(scalars)))

    engine = _engine("bf16")
    engine.monitor = RecordingMonitor()
    _step(engine)
    _step(engine, poison=True)  # device-side skip, settles a window late
    _step(engine)
    _step(engine)
    engine._reconcile_deferred(keep_last=False)
    steps = [s for s, _ in engine.monitor.writes]
    # 3 clean windows -> exactly 3 writes at unique, consecutive indices
    assert steps == [1, 2, 3], steps
    assert engine.global_steps == 3 and engine.skipped_steps == 1
    # the skipped window must not have produced a write at all
    for _, scalars in engine.monitor.writes:
        assert scalars.get("Train/grad_norm", 0.0) >= 0.0


def test_flush_monitor_writes_final_window():
    """The settle queue holds the NEWEST window's scalars until the next
    settle point; flush_monitor() (and checkpoint saves) must emit it."""

    class RecordingMonitor:
        enabled = True
        writer = None

        def __init__(self):
            self.writes = []

        def write_scalars(self, scalars, step):
            self.writes.append(step)

    engine = _engine("bf16")
    engine.monitor = RecordingMonitor()
    _step(engine)
    _step(engine)
    assert engine.monitor.writes == [1]  # window 2 still pending
    engine.flush_monitor()
    assert engine.monitor.writes == [1, 2]
