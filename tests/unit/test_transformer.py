"""Transformer layer + attention + model tests.

The analog of the reference's tests/unit/test_cuda_forward.py /
test_cuda_backward.py: numerical parity of the fused layer against a naive
baseline across batch/seq/pre-post-LN grids, in fwd and bwd.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import flash_attention, mha_reference
from deepspeed_tpu.ops.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


def naive_layer_forward(params, x, cfg, causal=False, mask=None):
    """Hand-written baseline of the same block (the 'vendored BertEncoder'
    role from the reference parity tests)."""

    def ln(t, w, b):
        t32 = t.astype(jnp.float32)
        mu = t32.mean(-1, keepdims=True)
        var = t32.var(-1, keepdims=True)
        return ((t32 - mu) / jnp.sqrt(var + cfg.layer_norm_eps)) * w + b

    H, heads = cfg.hidden_size, cfg.heads
    hd = H // heads
    b, s, _ = x.shape
    residual = x
    h = ln(x, params["attn_nw"], params["attn_nb"]) if cfg.pre_layer_norm else x
    qkv = h @ params["attn_qkvw"] + params["attn_qkvb"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads_split(t):
        return t.reshape(b, s, heads, hd).transpose(0, 2, 1, 3)

    ctx = mha_reference(
        heads_split(q), heads_split(k), heads_split(v), causal=causal, mask=mask
    )
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, H)
    attn_out = ctx @ params["attn_ow"] + params["attn_ob"]
    x1 = residual + attn_out
    if not cfg.pre_layer_norm:
        x1 = ln(x1, params["attn_nw"], params["attn_nb"])
    residual = x1
    h = ln(x1, params["norm_w"], params["norm_b"]) if cfg.pre_layer_norm else x1
    h = h @ params["inter_w"] + params["inter_b"]
    h = nn.gelu(h, approximate=True)
    h = h @ params["output_w"] + params["output_b"]
    x2 = residual + h
    if not cfg.pre_layer_norm:
        x2 = ln(x2, params["norm_w"], params["norm_b"])
    return x2


@pytest.mark.parametrize("pre_ln", [True, False])
@pytest.mark.parametrize("batch,seq", [(2, 64), (1, 128)])
def test_layer_parity_forward(pre_ln, batch, seq):
    cfg = DeepSpeedTransformerConfig(
        hidden_size=64, heads=4, attn_dropout_ratio=0.0,
        hidden_dropout_ratio=0.0, pre_layer_norm=pre_ln,
    )
    layer = DeepSpeedTransformerLayer(config=cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, seq, 64)), jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x, train=False)["params"]
    out = layer.apply({"params": params}, x, train=False)
    ref = naive_layer_forward(params, x, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("pre_ln", [True, False])
def test_layer_parity_backward(pre_ln):
    cfg = DeepSpeedTransformerConfig(
        hidden_size=64, heads=4, attn_dropout_ratio=0.0,
        hidden_dropout_ratio=0.0, pre_layer_norm=pre_ln,
    )
    layer = DeepSpeedTransformerLayer(config=cfg)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 64, 64)), jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x, train=False)["params"]

    def loss_ds(p):
        return jnp.sum(layer.apply({"params": p}, x, train=False) ** 2)

    def loss_ref(p):
        return jnp.sum(naive_layer_forward(p, x, cfg) ** 2)

    g1 = jax.grad(loss_ds)(params)
    g2 = jax.grad(loss_ref)(params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g1[k]), np.asarray(g2[k]), rtol=2e-3, atol=2e-3,
            err_msg=f"grad mismatch for {k}",
        )


def test_stochastic_mode_changes_bf16_path_and_warns():
    """stochastic_mode must be a real behavior change (reference builds a
    distinct relaxed kernel, setup.py:44-118), announced at rank 0 — never
    a silent no-op: under bf16 the LN statistics stay in bf16, so outputs
    differ from the default fp32-stat path while remaining close."""
    from deepspeed_tpu.ops import transformer as tr

    base = dict(
        hidden_size=64, heads=4, attn_dropout_ratio=0.0,
        hidden_dropout_ratio=0.0,
    )
    layer_d = DeepSpeedTransformerLayer(
        config=DeepSpeedTransformerConfig(**base)
    )
    layer_s = DeepSpeedTransformerLayer(
        config=DeepSpeedTransformerConfig(stochastic_mode=True, **base)
    )
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 64, 64)), jnp.bfloat16)
    params = layer_d.init(jax.random.PRNGKey(0), x, train=False)["params"]

    import logging

    from deepspeed_tpu.utils.logging import logger as ds_logger

    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r.getMessage())
    ds_logger.addHandler(handler)
    tr._STOCHASTIC_NOTICED[0] = False
    try:
        out_s = layer_s.apply({"params": params}, x, train=False)
    finally:
        ds_logger.removeHandler(handler)
    assert any("stochastic_mode" in m for m in records)
    out_d = layer_d.apply({"params": params}, x, train=False)
    a, b = np.asarray(out_d, np.float32), np.asarray(out_s, np.float32)
    assert not np.array_equal(a, b), "stochastic_mode must not be a no-op"
    np.testing.assert_allclose(a, b, rtol=0.1, atol=0.1)


def test_stochastic_mode_fp16_keeps_fp32_statistics():
    """fp16's narrow range (max 65504; eps underflow) must NOT take the
    relaxed path: outputs stay bit-identical to the default, and large
    activations don't overflow the variance."""
    base = dict(
        hidden_size=64, heads=4, attn_dropout_ratio=0.0,
        hidden_dropout_ratio=0.0,
    )
    layer_d = DeepSpeedTransformerLayer(
        config=DeepSpeedTransformerConfig(**base)
    )
    layer_s = DeepSpeedTransformerLayer(
        config=DeepSpeedTransformerConfig(stochastic_mode=True, **base)
    )
    rng = np.random.default_rng(4)
    # scale drives |x - mean| past fp16's sqrt(max) so a relaxed fp16 var
    # would overflow to inf
    x = jnp.asarray(rng.normal(size=(2, 64, 64)) * 500.0, jnp.float16)
    params = layer_d.init(jax.random.PRNGKey(0), x, train=False)["params"]
    out_d = layer_d.apply({"params": params}, x, train=False)
    out_s = layer_s.apply({"params": params}, x, train=False)
    np.testing.assert_array_equal(np.asarray(out_d), np.asarray(out_s))
    assert np.isfinite(np.asarray(out_s, np.float32)).all()


def test_remat_modes_same_output():
    """The reference's memory modes change memory, not numerics
    (ds_transformer_cuda.cpp:189-191) — remat must be invisible."""
    base = dict(
        hidden_size=64, heads=4, attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0
    )
    cfg_plain = DeepSpeedTransformerConfig(**base)
    cfg_remat = DeepSpeedTransformerConfig(
        **base, normalize_invertible=True, gelu_checkpoint=True
    )
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 64, 64)), jnp.float32)
    l1 = DeepSpeedTransformerLayer(config=cfg_plain)
    l2 = DeepSpeedTransformerLayer(config=cfg_remat)
    params = l1.init(jax.random.PRNGKey(0), x, train=False)["params"]
    o1 = l1.apply({"params": params}, x, train=False)
    o2 = l2.apply({"params": params}, x, train=False)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=1e-6, atol=1e-6)
    g1 = jax.grad(lambda p: jnp.sum(l1.apply({"params": p}, x, train=False) ** 2))(params)
    g2 = jax.grad(lambda p: jnp.sum(l2.apply({"params": p}, x, train=False) ** 2))(params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g1[k]), np.asarray(g2[k]), rtol=1e-5, atol=1e-5
        )


def test_dropout_determinism_same_rng():
    cfg = DeepSpeedTransformerConfig(
        hidden_size=64, heads=4, attn_dropout_ratio=0.1, hidden_dropout_ratio=0.1
    )
    layer = DeepSpeedTransformerLayer(config=cfg)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 64, 64)), jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x, train=False)["params"]
    key = jax.random.PRNGKey(7)
    o1 = layer.apply({"params": params}, x, train=True, rngs={"dropout": key})
    o2 = layer.apply({"params": params}, x, train=True, rngs={"dropout": key})
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = layer.apply(
        {"params": params}, x, train=True, rngs={"dropout": jax.random.PRNGKey(8)}
    )
    assert not np.allclose(np.asarray(o1), np.asarray(o3))


# --------------------------------------------------------------- flash kernel
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_mask", [False, True])
def test_flash_attention_parity(causal, with_mask):
    rng = np.random.default_rng(0)
    B, H, S, D = 2, 2, 256, 64
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    mask = None
    if with_mask:
        mask = jnp.where(
            jnp.arange(S)[None, None, None, :] < 200, 0.0, -1e30
        ).astype(jnp.float32)
    o1 = flash_attention(q, k, v, mask=mask, causal=causal)
    o2 = mha_reference(q, k, v, mask=mask, causal=causal)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=1e-5, atol=1e-5)


def test_flash_attention_grads():
    rng = np.random.default_rng(1)
    B, H, S, D = 1, 2, 128, 64
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    g1 = jax.grad(lambda a, b, c: jnp.sum(flash_attention(a, b, c, causal=True) ** 2), (0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda a, b, c: jnp.sum(mha_reference(a, b, c, causal=True) ** 2), (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_long_sequence_no_cap():
    """No seq<=1024 limit (the reference kernel hard-caps there)."""
    B, H, S, D = 1, 1, 2048, 64
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    o1 = flash_attention(q, k, v, causal=True)
    o2 = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=1e-5, atol=1e-5)
