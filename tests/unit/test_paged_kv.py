"""Paged KV cache + cross-request prefix caching (docs/inference.md
"Paged KV cache"): bitwise greedy parity against the contiguous path
(prefill logits, 16-step decode, mid-flight joins, EOS slot reuse), the
no-recompile pin on the paged path, BlockPool refcount exactness under
sharing + LRU eviction, the typed REJECT_CAPACITY admission gate, and
the prefix-hit suffix-prefill path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedConfigError
from deepspeed_tpu.inference import (
    REJECT_CAPACITY,
    BlockPool,
    PoolExhausted,
    RequestRejected,
    gpt2_decode_step,
    gpt2_decode_step_paged,
    gpt2_prefill,
    hash_full_blocks,
    init_kv_cache,
    init_kv_pool,
    write_prefill_to_cache,
    write_prefill_to_pool,
)
from deepspeed_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHeadModel,
    kv_pool_partition_specs,
)

VOCAB = 97


def _small_model(seed=0, **kw):
    cfg = GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        dropout=0.0, use_flash=False, **kw,
    )
    model = GPT2LMHeadModel(cfg)
    ids0 = jnp.asarray(
        np.random.default_rng(seed).integers(0, VOCAB, (1, 8)), jnp.int32
    )
    params = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        ids0, ids0,
    )["params"]
    return cfg, model, params


def _engine(model, params, inference=None):
    block = {"max_batch_slots": 4, "max_seq_len": 48, "prefill_len": 32,
             "kv_block_size": 8, "sampling": {"greedy": True}}
    block.update(inference or {})
    if block.get("kv_block_size") == 0:
        block.pop("kv_block_size")
    return deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config={"inference": block},
    )


def _prompt(n=8, seed=1):
    return [int(t) for t in np.random.default_rng(seed).integers(0, VOCAB, n)]


# ---------------------------------------------------------------------------
# BlockPool: refcount exactness, sharing, eviction
# ---------------------------------------------------------------------------
def test_block_pool_alloc_exactness_and_exhaustion():
    pool = BlockPool(4, block_size=8)
    a = pool.alloc(3)
    assert len(set(a)) == 3 and 0 not in a  # never the null page
    assert pool.free_blocks == 1 and pool.used_blocks == 3
    with pytest.raises(PoolExhausted) as exc:
        pool.alloc(2)  # all-or-nothing: nothing handed out
    assert exc.value.needed == 2 and exc.value.available == 1
    assert pool.free_blocks == 1 and pool.used_blocks == 3
    pool.release(a)
    assert pool.free_blocks == 4 and pool.used_blocks == 0


def test_block_pool_double_free_raises():
    pool = BlockPool(2, block_size=4)
    (b,) = pool.alloc(1)
    pool.release([b])
    with pytest.raises(ValueError, match="double free"):
        pool.release([b])


def test_block_pool_prefix_sharing_refcounts_exact():
    """Two requests sharing a prefix hold ONE set of physical pages;
    releases decref precisely, and the pages survive as cached until the
    last reference plus the registry eviction are gone."""
    pool = BlockPool(8, block_size=4)
    prompt = list(range(11))  # 2 full pages + 3-token tail
    # request A, cold: needs 3 pages, registers its 2 full ones
    a_blocks = pool.alloc(3)
    pool.register_prefix(prompt, a_blocks)
    # request B, same prompt: matches both full pages
    prefix_len, shared = pool.match_prefix(prompt)
    assert prefix_len == 8 and shared == a_blocks[:2]
    assert pool.refcount(shared[0]) == 2 and pool.refcount(shared[1]) == 2
    b_blocks = shared + pool.alloc(1)
    # A finishes: shared pages drop to one reference, stay pinned
    pool.release(a_blocks)
    assert pool.refcount(shared[0]) == 1
    assert pool.used_blocks == 3  # B's three pages
    # B finishes: registered pages park in the evictable LRU, private
    # tail pages free outright
    pool.release(b_blocks)
    assert pool.used_blocks == 0
    assert pool.cached_blocks == 2
    # the cached prefix is still matchable (re-acquire pins it again)
    prefix_len, again = pool.match_prefix(prompt)
    assert prefix_len == 8 and again == shared
    pool.release(again)


def test_block_pool_lru_eviction_under_pressure():
    pool = BlockPool(2, block_size=4)
    p1, p2 = [0, 1, 2, 3, 99], [7, 6, 5, 4, 99]
    b1 = pool.alloc(1)
    pool.register_prefix(p1, b1)
    pool.release(b1)
    b2 = pool.alloc(1)
    pool.register_prefix(p2, b2)
    pool.release(b2)
    assert pool.cached_blocks == 2 and pool.available_blocks == 2
    # allocating 1 evicts the LRU entry (p1's page, cached first)
    pool.alloc(1)
    assert pool.reclaimed == 1
    assert pool.match_prefix(p1) == (0, [])  # evicted
    got = pool.match_prefix(p2)
    assert got[0] == 4  # survivor still cached
    pool.release(got[1])


def test_block_pool_never_matches_whole_prompt():
    """A prompt that is exactly N full pages may share at most N-1: the
    last token's logits must be computed to seed generation."""
    pool = BlockPool(4, block_size=4)
    prompt = list(range(8))  # exactly 2 pages
    blocks = pool.alloc(2)
    pool.register_prefix(prompt, blocks)
    prefix_len, shared = pool.match_prefix(prompt)
    assert prefix_len == 4 and shared == blocks[:1]
    pool.release(shared)
    pool.release(blocks)


def test_hash_chain_commits_to_whole_prefix():
    a = hash_full_blocks([1, 2, 3, 4, 5, 6, 7, 8], 4)
    b = hash_full_blocks([9, 2, 3, 4, 5, 6, 7, 8], 4)
    assert len(a) == 2 and len(b) == 2
    assert a[0] != b[0]
    # identical second page, different first page => different chain hash
    assert a[1] != b[1]
    assert a == hash_full_blocks([1, 2, 3, 4, 5, 6, 7, 8], 4)


# ---------------------------------------------------------------------------
# bitwise parity with the contiguous path
# ---------------------------------------------------------------------------
def test_paged_decode_logits_bitwise_match_contiguous():
    """Acceptance pin: prefill written through pages, then 16 paged
    decode steps — every step's logits BITWISE-equal to the contiguous
    cache's (same shared decode core, same einsum HLO, masked garbage
    contributing exact zeros)."""
    cfg, model, params = _small_model()
    prompt = _prompt(11)
    plen, bs, max_len, slots = len(prompt), 8, 32, 2
    prefill_len = 16
    padded = np.zeros((1, prefill_len), np.int32)
    padded[0, :plen] = prompt
    logits, ks, vs = jax.jit(
        lambda p, t: gpt2_prefill(cfg, p, t)
    )(params, jnp.asarray(padded))

    cache = write_prefill_to_cache(
        init_kv_cache(cfg, slots, max_len), jnp.int32(0), ks, vs
    )
    pool = init_kv_pool(cfg, 6, bs)
    table = np.zeros((slots, max_len // bs), np.int32)
    table[0] = [1, 2, 3, 4]  # covers prompt + 16 generated tokens
    block_ids = np.zeros(prefill_len, np.int32)
    block_ids[:plen] = [table[0][j // bs] for j in range(plen)]
    pool = write_prefill_to_pool(
        pool, ks, vs, jnp.asarray(block_ids),
        jnp.asarray(np.arange(prefill_len, dtype=np.int32) % bs),
    )

    jd_c = jax.jit(lambda p, t, po, c: gpt2_decode_step(cfg, p, t, po, c))
    jd_p = jax.jit(
        lambda p, t, po, pl, bt: gpt2_decode_step_paged(cfg, p, t, po, pl, bt)
    )
    first = int(jnp.argmax(logits[0, plen - 1, :VOCAB]))
    toks = np.zeros(slots, np.int32)
    pos = np.zeros(slots, np.int32)
    toks[0], pos[0] = first, plen
    for _ in range(16):
        lc, cache = jd_c(params, jnp.asarray(toks), jnp.asarray(pos), cache)
        lp, pool = jd_p(
            params, jnp.asarray(toks), jnp.asarray(pos), pool,
            jnp.asarray(table),
        )
        np.testing.assert_array_equal(np.asarray(lc[0]), np.asarray(lp[0]))
        toks[0] = int(jnp.argmax(lc[0, :VOCAB]))
        pos[0] += 1


def test_paged_engine_matrix_matches_contiguous():
    """Engine-level parity matrix: concurrent mixed-length requests,
    a mid-flight join, and EOS slot reuse all produce exactly the
    contiguous engine's greedy tokens."""
    cfg, model, params = _small_model()
    e_c = _engine(model, params, {"kv_block_size": 0})
    e_p = _engine(model, params)
    try:
        prompts = [_prompt(9, 1), _prompt(5, 2), _prompt(13, 3)]
        assert e_c.generate(prompts, max_new_tokens=10) == \
            e_p.generate(prompts, max_new_tokens=10)

        # mid-flight join
        r1c = e_c.submit(_prompt(8, 4), max_new_tokens=12)
        r1p = e_p.submit(_prompt(8, 4), max_new_tokens=12)
        for _ in range(4):
            e_c.scheduler.step()
            e_p.scheduler.step()
        r2c = e_c.submit(_prompt(7, 5), max_new_tokens=8)
        r2p = e_p.submit(_prompt(7, 5), max_new_tokens=8)
        e_c.scheduler.run_until_idle()
        e_p.scheduler.run_until_idle()
        assert r1c.result(0) == r1p.result(0)
        assert r2c.result(0) == r2p.result(0)

        # EOS slot reuse: finish one request via EOS, reuse its pages
        ref = e_c.generate([_prompt(8, 6)], max_new_tokens=8)[0]
        eos = ref[3]
        ac = e_c.submit(_prompt(8, 6), max_new_tokens=8, eos_token_id=eos)
        ap = e_p.submit(_prompt(8, 6), max_new_tokens=8, eos_token_id=eos)
        e_c.scheduler.run_until_idle()
        e_p.scheduler.run_until_idle()
        assert ac.finish_reason == ap.finish_reason == "eos"
        assert ac.result(0) == ap.result(0)
        assert e_c.generate([_prompt(6, 9)], max_new_tokens=6) == \
            e_p.generate([_prompt(6, 9)], max_new_tokens=6)
    finally:
        e_c.close()
        e_p.close()


def test_paged_decode_steps_do_not_recompile():
    """The no-recompile pin holds on the paged path: joins, leaves, page
    reuse, and warm prefix hits add zero XLA backend compiles."""
    cfg, model, params = _small_model()
    engine = _engine(model, params)
    try:
        recompiles = engine.metrics.counter("jax/recompiles")
        engine.generate([_prompt(8)], max_new_tokens=4)
        # warm the prefix-hit suffix program (one bucket)
        shared = _prompt(16, 7)
        engine.generate([shared + _prompt(3, 8)], max_new_tokens=4)
        engine.generate([shared + _prompt(3, 9)], max_new_tokens=4)
        warm = recompiles.value
        assert warm > 0

        r1 = engine.submit(_prompt(5, 5), max_new_tokens=6)
        engine.scheduler.step()
        r2 = engine.submit(_prompt(11, 6), max_new_tokens=5)
        r3 = engine.submit(shared + _prompt(2, 10), max_new_tokens=4)
        engine.scheduler.run_until_idle()
        assert all(r.done for r in (r1, r2, r3))
        assert recompiles.value == warm, (
            f"paged decode path recompiled: {recompiles.value - warm} new "
            "backend compiles after warmup"
        )
    finally:
        engine.close()


# ---------------------------------------------------------------------------
# prefix cache at the engine level
# ---------------------------------------------------------------------------
def test_prefix_hit_counts_and_matches_cold_generation():
    cfg, model, params = _small_model()
    engine = _engine(model, params)
    cold_engine = _engine(model, params, {"prefix_cache": {"enabled": False}})
    try:
        shared = _prompt(16, 7)  # two full pages at kv_block_size=8
        pa = shared + _prompt(4, 8)
        pb = shared + _prompt(4, 9)
        engine.generate([pa], max_new_tokens=6)
        snap0 = engine.metrics.snapshot()
        hot = engine.generate([pb], max_new_tokens=6)[0]
        snap1 = engine.metrics.snapshot()
        assert snap1["infer/prefix_hits"] == snap0["infer/prefix_hits"] + 1
        assert snap0["infer/prefix_misses"] >= 1  # the cold admission
        assert hot == cold_engine.generate([pb], max_new_tokens=6)[0]
    finally:
        engine.close()
        cold_engine.close()


def test_engine_refcounts_exact_under_concurrent_sharing():
    """Two live requests share prefix pages (refcount 2 on device-backed
    pages); finishing one keeps the other decoding correctly; finishing
    both leaves zero pinned pages and a warm cache."""
    cfg, model, params = _small_model()
    engine = _engine(model, params)
    try:
        shared = _prompt(16, 7)
        pa, pb = shared + _prompt(4, 8), shared + _prompt(5, 9)
        # a cold pass registers the template's two full pages
        engine.generate([shared + _prompt(3, 10)], max_new_tokens=2)
        ra = engine.submit(pa, max_new_tokens=10)
        rb = engine.submit(pb, max_new_tokens=4)
        engine.scheduler.step()  # both admitted: prefix pages shared
        shared_pages = engine.block_pool._registry.values()
        assert all(
            engine.block_pool.refcount(b) == 2 for b in shared_pages
        )
        engine.scheduler.run_until_idle()  # rb finishes first (4 tokens)
        assert ra.result(0) and rb.result(0)
        assert engine.block_pool.used_blocks == 0
        assert engine.metrics.gauge("infer/kv_pool_occupancy").value == 0
        # the finished requests' outputs match a fresh engine's
        check = _engine(model, params, {"prefix_cache": {"enabled": False}})
        assert ra.tokens == check.generate([pa], max_new_tokens=10)[0]
        assert rb.tokens == check.generate([pb], max_new_tokens=4)[0]
        check.close()
    finally:
        engine.close()


def test_eviction_under_pressure_reclaims_cached_pages():
    """Filling the pool evicts cached refcount-0 prefix pages LRU-first
    (counted on infer/kv_blocks_reclaimed) and the evicted prefix simply
    misses on its next use — no correctness impact."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, {"kv_pool_blocks": 6})
    try:
        shared = _prompt(16, 7)  # caches 2 pages once finished
        out1 = engine.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        assert engine.block_pool.cached_blocks == 2
        # four concurrent 1-page... (8 tokens prompt + 8 new = 2 pages
        # each) => 2 requests need 4 pages; free = 4, so eviction bites
        rs = [engine.submit(_prompt(8, 20 + i), max_new_tokens=8)
              for i in range(3)]
        engine.scheduler.run_until_idle()
        assert all(len(r.result(0)) == 8 for r in rs)
        snap = engine.metrics.snapshot()
        assert snap["infer/kv_blocks_reclaimed"] >= 1
        # evicted template re-serves correctly (cold again)
        out2 = engine.generate([shared + _prompt(4, 8)], max_new_tokens=4)[0]
        assert out2 == out1
    finally:
        engine.close()


# ---------------------------------------------------------------------------
# capacity admission gate
# ---------------------------------------------------------------------------
def test_pool_exhaustion_rejects_with_typed_capacity_reason():
    cfg, model, params = _small_model()
    engine = _engine(model, params, {
        "kv_pool_blocks": 2, "max_batch_slots": 2,
    })
    try:
        r = engine.submit(_prompt(8, 1), max_new_tokens=8)  # 2 pages
        engine.scheduler.step()  # admitted: pool now empty
        with pytest.raises(RequestRejected) as exc:
            engine.submit(_prompt(8, 2), max_new_tokens=8)
        assert exc.value.reason == REJECT_CAPACITY
        assert engine.metrics.snapshot()["infer/requests_rejected"] == 1
        engine.scheduler.run_until_idle()
        assert len(r.result(0)) == 8
        # pages released: the same submission is admittable again
        r2 = engine.submit(_prompt(8, 2), max_new_tokens=8)
        engine.scheduler.run_until_idle()
        assert len(r2.result(0)) == 8
    finally:
        engine.close()


def test_request_that_can_never_fit_raises_value_error():
    cfg, model, params = _small_model()
    engine = _engine(model, params, {
        "kv_pool_blocks": 2, "max_batch_slots": 2,
    })
    try:
        with pytest.raises(ValueError, match="KV pages"):
            engine.submit(_prompt(10, 1), max_new_tokens=30)
    finally:
        engine.close()


def test_admission_defers_until_pages_free_then_completes():
    """Requests racing past the submit-time gate defer at the slot-join
    boundary and complete once earlier requests release pages — queue
    deeper than the pool drains without losses."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, {
        "kv_pool_blocks": 4, "max_batch_slots": 4,
    })
    try:
        rs = [engine.submit(_prompt(8, 30 + i), max_new_tokens=6)
              for i in range(2)]  # 2 pages each: pool exactly full
        engine.scheduler.run_until_idle()
        assert all(len(r.result(0)) == 6 for r in rs)
        assert engine.block_pool.used_blocks == 0
    finally:
        engine.close()


# ---------------------------------------------------------------------------
# geometry, snapshot, config
# ---------------------------------------------------------------------------
def test_kv_pool_layout_and_specs():
    cfg, model, params = _small_model()
    pool = init_kv_pool(cfg, 6, 8)
    # + the null page at physical index 0
    assert pool.k.shape == (cfg.n_layer, 7, 8, cfg.n_head,
                            cfg.n_embd // cfg.n_head)
    assert pool.num_blocks == 7 and pool.block_size == 8
    spec = kv_pool_partition_specs()
    assert spec[3] == "model" and spec[1] is None


def test_load_snapshot_reports_pool_and_prefix_state():
    cfg, model, params = _small_model()
    engine = _engine(model, params)
    try:
        shared = _prompt(16, 7)
        engine.generate([shared + _prompt(4, 8)], max_new_tokens=2)
        engine.generate([shared + _prompt(4, 9)], max_new_tokens=2)
        snap = engine.load_snapshot()
        assert snap["kv_blocks_total"] == engine.block_pool.num_blocks
        assert snap["kv_blocks_used"] == 0
        assert snap["prefix_hits"] == 1 and snap["prefix_misses"] == 1
        assert snap["prefix_hit_rate"] == 0.5
        assert snap["kv_blocks_free"] > 0
        bytes_gauge = engine.metrics.gauge("infer/kv_cache_bytes").value
        assert bytes_gauge == (
            int(engine._cache.k.nbytes) + int(engine._cache.v.nbytes)
        )
    finally:
        engine.close()


def _dead_and_live_setup():
    """One live slot (real pages, prefilled) beside one dead slot (all-
    null block table): the fixture the dead-slot masking pins run on."""
    cfg, model, params = _small_model()
    prompt = _prompt(11)
    plen, bs, max_len, slots = len(prompt), 8, 32, 2
    prefill_len = 16
    padded = np.zeros((1, prefill_len), np.int32)
    padded[0, :plen] = prompt
    logits, ks, vs = jax.jit(
        lambda p, t: gpt2_prefill(cfg, p, t)
    )(params, jnp.asarray(padded))
    pool = init_kv_pool(cfg, 6, bs)
    table = np.zeros((slots, max_len // bs), np.int32)
    table[0] = [1, 2, 3, 4]
    block_ids = np.zeros(prefill_len, np.int32)
    block_ids[:plen] = [table[0][j // bs] for j in range(plen)]
    pool = write_prefill_to_pool(
        pool, ks, vs, jnp.asarray(block_ids),
        jnp.asarray(np.arange(prefill_len, dtype=np.int32) % bs),
    )
    first = int(jnp.argmax(logits[0, plen - 1, :VOCAB]))
    toks = np.zeros(slots, np.int32)
    pos = np.zeros(slots, np.int32)
    toks[0], pos[0] = first, plen
    return cfg, params, pool, table, toks, pos


# ---------------------------------------------------------------------------
# fused Pallas decode attention (docs/inference.md "Fused decode
# attention"): greedy parity vs the XLA reference, dead-slot early-out,
# and the no-recompile pin on the fused path
# ---------------------------------------------------------------------------
def test_fused_kernel_matches_gathered_reference():
    """paged_flash_decode (online softmax over live pages) agrees with
    the XLA gather-then-softmax reference to float tolerance on every
    live slot, and emits EXACT zeros for a dead slot — the behavior the
    greedy-parity engine pins build on."""
    from deepspeed_tpu.ops.decode_attention import paged_flash_decode

    rng = np.random.default_rng(3)
    b, heads, hd, bs, mb, pages = 3, 4, 8, 4, 4, 12
    q = jnp.asarray(rng.normal(size=(b, heads, hd)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(pages, bs, heads, hd)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(pages, bs, heads, hd)), jnp.float32)
    tables = np.zeros((b, mb), np.int32)
    tables[0, :2] = [3, 7]
    tables[2] = [1, 2, 4, 5]  # slot 1 stays dead
    positions = np.asarray([5, 0, 13], np.int32)
    out = np.asarray(paged_flash_decode(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(positions)
    ))
    for slot in (0, 2):
        k_full = np.asarray(kp)[tables[slot]].reshape(
            mb * bs, heads, hd
        ).transpose(1, 0, 2)
        v_full = np.asarray(vp)[tables[slot]].reshape(
            mb * bs, heads, hd
        ).transpose(1, 0, 2)
        s = np.einsum(
            "hd,hkd->hk", np.asarray(q)[slot], k_full
        ) / np.sqrt(hd)
        s = np.where(np.arange(mb * bs) <= positions[slot], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("hk,hkd->hd", p, v_full)
        np.testing.assert_allclose(out[slot], ref, atol=1e-5, rtol=1e-5)
    assert np.all(out[1] == 0.0), "dead slot must emit exact zeros"


def test_dead_slot_masked_on_both_paths_live_logits_pinned():
    """The dead-slot fix: an empty (all-null-table) slot's attention
    context is exact zeros on the XLA path AND the fused kernel — so
    both paths' dead-slot logits are BITWISE-identical (everything
    outside attention is shared arithmetic over a deterministic
    embedding) instead of a softmax over the null page's garbage. Live
    slots' logits stay bitwise-equal to the contiguous reference, so
    the masking costs the parity contract nothing."""
    cfg, params, pool, table, toks, pos = _dead_and_live_setup()
    cache = init_kv_cache(cfg, 2, 32)
    # seed the contiguous cache with the same prefill rows
    prompt = _prompt(11)
    padded = np.zeros((1, 16), np.int32)
    padded[0, :len(prompt)] = prompt
    _, ks, vs = jax.jit(
        lambda p, t: gpt2_prefill(cfg, p, t)
    )(params, jnp.asarray(padded))
    cache = write_prefill_to_cache(cache, jnp.int32(0), ks, vs)

    jd_c = jax.jit(lambda p, t, po, c: gpt2_decode_step(cfg, p, t, po, c))
    jd_x = jax.jit(
        lambda p, t, po, pl, bt: gpt2_decode_step_paged(cfg, p, t, po, pl, bt)
    )
    jd_f = jax.jit(
        lambda p, t, po, pl, bt: gpt2_decode_step_paged(
            cfg, p, t, po, pl, bt, fused=True
        )
    )
    lc, _ = jd_c(params, jnp.asarray(toks), jnp.asarray(pos), cache)
    lx, _ = jd_x(
        params, jnp.asarray(toks), jnp.asarray(pos), pool, jnp.asarray(table)
    )
    lf, _ = jd_f(
        params, jnp.asarray(toks), jnp.asarray(pos), pool, jnp.asarray(table)
    )
    # live slot: XLA paged stays bitwise vs contiguous; fused agrees on
    # the greedy choice (its online softmax is float-tolerant, not
    # bitwise)
    np.testing.assert_array_equal(np.asarray(lc[0]), np.asarray(lx[0]))
    np.testing.assert_allclose(
        np.asarray(lf[0]), np.asarray(lx[0]), atol=1e-4, rtol=1e-4
    )
    assert int(jnp.argmax(lf[0, :VOCAB])) == int(jnp.argmax(lx[0, :VOCAB]))
    # dead slot: zero attention context on both paths -> identical
    # deterministic logits (bitwise: everything outside attend is the
    # same arithmetic, and both contexts are exact zeros)
    np.testing.assert_array_equal(np.asarray(lx[1]), np.asarray(lf[1]))
    assert np.all(np.isfinite(np.asarray(lx[1])))


def test_fused_engine_greedy_parity_matrix():
    """Engine-level fused-vs-XLA pin: concurrent mixed-length requests,
    a mid-flight join, EOS slot reuse, and a prefix-cache hit all
    produce exactly the unfused engine's greedy tokens (which are
    themselves pinned bitwise to the contiguous path above)."""
    cfg, model, params = _small_model()
    e_x = _engine(model, params)
    e_f = _engine(model, params, {"fused_decode": True})
    try:
        assert e_f.fused_decode, "fused path did not arm"
        prompts = [_prompt(9, 1), _prompt(5, 2), _prompt(13, 3)]
        assert e_x.generate(prompts, max_new_tokens=10) == \
            e_f.generate(prompts, max_new_tokens=10)

        # mid-flight join
        r1x = e_x.submit(_prompt(8, 4), max_new_tokens=12)
        r1f = e_f.submit(_prompt(8, 4), max_new_tokens=12)
        for _ in range(4):
            e_x.scheduler.step()
            e_f.scheduler.step()
        r2x = e_x.submit(_prompt(7, 5), max_new_tokens=8)
        r2f = e_f.submit(_prompt(7, 5), max_new_tokens=8)
        e_x.scheduler.run_until_idle()
        e_f.scheduler.run_until_idle()
        assert r1x.result(0) == r1f.result(0)
        assert r2x.result(0) == r2f.result(0)

        # EOS slot reuse
        ref = e_x.generate([_prompt(8, 6)], max_new_tokens=8)[0]
        eos = ref[3]
        ax = e_x.submit(_prompt(8, 6), max_new_tokens=8, eos_token_id=eos)
        af = e_f.submit(_prompt(8, 6), max_new_tokens=8, eos_token_id=eos)
        e_x.scheduler.run_until_idle()
        e_f.scheduler.run_until_idle()
        assert ax.finish_reason == af.finish_reason == "eos"
        assert ax.result(0) == af.result(0)

        # prefix-cache hit rides the fused decode unchanged
        shared = _prompt(16, 7)
        assert e_x.generate([shared + _prompt(3, 8)], max_new_tokens=6) == \
            e_f.generate([shared + _prompt(3, 8)], max_new_tokens=6)
        assert e_x.generate([shared + _prompt(3, 9)], max_new_tokens=6) == \
            e_f.generate([shared + _prompt(3, 9)], max_new_tokens=6)
        assert e_f.metrics.counter("infer/prefix_hits").value >= 1
        assert e_f.metrics.gauge("infer/fused_decode").value == 1
        assert e_x.metrics.gauge("infer/fused_decode").value == 0
    finally:
        e_x.close()
        e_f.close()


def test_fused_decode_steps_do_not_recompile():
    """The no-recompile pin extends to the fused path: joins, leaves,
    and warm prefix hits add zero XLA backend compiles — block tables
    and positions stay index ARRAYS through the kernel's scalar
    prefetch."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, {"fused_decode": True})
    try:
        recompiles = engine.metrics.counter("jax/recompiles")
        engine.generate([_prompt(8)], max_new_tokens=4)
        shared = _prompt(16, 7)
        engine.generate([shared + _prompt(3, 8)], max_new_tokens=4)
        engine.generate([shared + _prompt(3, 9)], max_new_tokens=4)
        warm = recompiles.value
        assert warm > 0

        r1 = engine.submit(_prompt(5, 5), max_new_tokens=6)
        engine.scheduler.step()
        r2 = engine.submit(_prompt(11, 6), max_new_tokens=5)
        r3 = engine.submit(shared + _prompt(2, 10), max_new_tokens=4)
        engine.scheduler.run_until_idle()
        assert all(r.done for r in (r1, r2, r3))
        assert recompiles.value == warm, (
            f"fused decode path recompiled: {recompiles.value - warm} "
            "new backend compiles after warmup"
        )
    finally:
        engine.close()


def test_fused_decode_requires_paged_cache():
    cfg, model, params = _small_model()
    with pytest.raises(DeepSpeedConfigError, match="paged"):
        _engine(model, params, {"kv_block_size": 0, "fused_decode": True})


def test_engine_rejects_block_size_not_dividing_max_seq():
    cfg, model, params = _small_model()
    with pytest.raises(DeepSpeedConfigError, match="multiple"):
        deepspeed_tpu.init_inference(
            model=model, model_parameters=params,
            config={"inference": {"max_seq_len": 48, "kv_block_size": 7}},
        )


def test_long_suffix_falls_back_cold_instead_of_corrupting_pages():
    """Regression: a hit whose smallest fitting suffix bucket would pad
    past max_seq_len must fall back to a COLD full prefill — the padded
    rows' positions would clamp into the slot's real last page and
    overwrite written prompt k/v (observed as silently wrong
    generations). Geometry: max_seq=64, bs=16, bucket ladder 16/32/64;
    template=16, suffix=40 -> bucket 64 pads positions 16..79 > 63."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, {
        "max_seq_len": 64, "prefill_len": 64, "kv_block_size": 16,
    })
    ref = _engine(model, params, {
        "max_seq_len": 64, "prefill_len": 64, "kv_block_size": 0,
    })
    try:
        template = _prompt(16, 7)  # one full 16-token page
        engine.generate([template + _prompt(4, 8)], max_new_tokens=2)
        hits0 = engine.metrics.snapshot()["infer/prefix_hits"]
        long_tail = template + _prompt(40, 9)  # suffix 40: no safe bucket
        out = engine.generate([long_tail], max_new_tokens=6)[0]
        snap = engine.metrics.snapshot()
        assert snap["infer/prefix_hits"] == hits0  # counted as a miss
        assert out == ref.generate([long_tail], max_new_tokens=6)[0]
        # a SHORT suffix on the same template still hits and is correct
        short = template + _prompt(4, 10)
        out2 = engine.generate([short], max_new_tokens=6)[0]
        assert engine.metrics.snapshot()["infer/prefix_hits"] == hits0 + 1
        assert out2 == ref.generate([short], max_new_tokens=6)[0]
    finally:
        engine.close()
        ref.close()


def test_user_bucket_list_too_small_falls_back_cold():
    """Regression: an explicit suffix_buckets list whose largest bucket
    is smaller than a hit's suffix must not crash (numpy broadcast
    error through the decode-crash path) — it serves cold instead."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, {
        "max_seq_len": 64, "prefill_len": 64, "kv_block_size": 16,
        "prefix_cache": {"suffix_buckets": [16]},
    })
    try:
        template = _prompt(16, 7)
        engine.generate([template + _prompt(4, 8)], max_new_tokens=2)
        long_tail = template + _prompt(40, 9)
        out = engine.generate([long_tail], max_new_tokens=4)[0]
        assert len(out) == 4
        check = _engine(model, params, {
            "max_seq_len": 64, "prefill_len": 64, "kv_block_size": 0,
        })
        assert out == check.generate([long_tail], max_new_tokens=4)[0]
        check.close()
    finally:
        engine.close()


def test_driver_restart_resets_pool_and_serves_on():
    """After a decode crash past the cache (driver auto-restart), the
    pool rebuilds empty and subsequent paged requests serve exactly."""
    cfg, model, params = _small_model()
    engine = _engine(model, params, {"driver_restart_budget": 1})
    try:
        ref = engine.generate([_prompt(8, 1)], max_new_tokens=6)[0]
        engine.scheduler._recover_driver_crash()
        assert engine.block_pool.used_blocks == 0
        assert engine.block_pool.cached_blocks == 0
        out = engine.generate([_prompt(8, 1)], max_new_tokens=6)[0]
        assert out == ref
    finally:
        engine.close()
