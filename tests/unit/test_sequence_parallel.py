"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Numerics oracle is ``mha_reference`` on the full (unsharded) arrays —
the same parity pattern the reference uses for its fused kernels
(reference: tests/unit/test_cuda_forward.py), applied to the mesh-level
attention decomposition instead.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import NEG_INF, mha_reference
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.parallel.sequence import (
    ring_attention,
    sequence_parallel_attention,
    ulysses_attention,
)

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`


def _mesh(sp=4, dp=2, mp=1):
    return build_mesh(
        data_parallel_size=dp, sequence_parallel_size=sp, model_parallel_size=mp
    )


def _qkv(b=2, h=4, s=64, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_reference(impl, causal):
    mesh = _mesh()
    q, k, v = _qkv()
    out = impl(q, k, v, mesh, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_padding_mask(impl):
    mesh = _mesh()
    q, k, v = _qkv()
    rng = np.random.default_rng(1)
    kv_valid = jnp.asarray(rng.random((2, 64)) < 0.8, jnp.int32)
    # keep at least the first key valid so no row is fully masked
    kv_valid = kv_valid.at[:, 0].set(1)
    out = impl(q, k, v, mesh, kv_valid)
    mask = jnp.where(kv_valid > 0, 0.0, NEG_INF)[:, None, None, :]
    ref = mha_reference(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_fully_masked_rows_are_zero():
    mesh = _mesh()
    q, k, v = _qkv()
    kv_valid = jnp.zeros((2, 64), jnp.int32)
    out = ring_attention(q, k, v, mesh, kv_valid)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_gradients_match_reference(impl):
    mesh = _mesh()
    q, k, v = _qkv(s=32)

    def loss_sp(q, k, v):
        return jnp.sum(impl(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_under_jit_and_uneven_heads_dispatch():
    # 3 heads with sp=4 -> auto must pick ring; also exercise jit.
    mesh = _mesh()
    q, k, v = _qkv(h=3)

    @jax.jit
    def f(q, k, v):
        return sequence_parallel_attention(q, k, v, mesh, causal=True)

    out = f(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_dropout_is_deterministic_and_normalized():
    mesh = _mesh()
    q, k, v = _qkv()
    key = jax.random.PRNGKey(7)
    out1 = ring_attention(q, k, v, mesh, dropout_rate=0.2, dropout_rng=key)
    out2 = ring_attention(q, k, v, mesh, dropout_rate=0.2, dropout_rng=key)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    # different key -> different output
    out3 = ring_attention(
        q, k, v, mesh, dropout_rate=0.2, dropout_rng=jax.random.PRNGKey(8)
    )
    assert not np.allclose(np.asarray(out1), np.asarray(out3))
    # dropout output stays in the same ballpark as the exact one (unbiased-ish)
    ref = mha_reference(q, k, v)
    assert np.abs(np.asarray(out1) - np.asarray(ref)).mean() < 1.0


def test_auto_dispatch_uses_local_head_count():
    # mp=2, sp=2: H=6 -> 3 local heads, 3 % 2 != 0 -> auto must pick ring
    # (global 6 % 2 == 0 would wrongly pick ulysses).
    mesh = _mesh(sp=2, dp=2, mp=2)
    q, k, v = _qkv(h=6)
    out = sequence_parallel_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_fully_masked_rows_zero_output_and_grads():
    from deepspeed_tpu.ops.attention import flash_attention

    q, k, v = _qkv(b=1, h=2, s=128, d=32)
    kv_mask = jnp.zeros((1, 128), jnp.int32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_mask=kv_mask) ** 2)

    out = flash_attention(q, k, v, kv_mask=kv_mask)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)
    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        np.testing.assert_allclose(np.asarray(g), 0.0, atol=1e-6)


def test_ulysses_requires_divisible_heads():
    mesh = _mesh()
    q, k, v = _qkv(h=3)
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh)


def test_user_mesh_without_model_axis():
    # a plain ('data','sequence') mesh — no model/pipe axes — must work
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("data", "sequence"))
    q, k, v = _qkv()
    out = sequence_parallel_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_mesh_without_sequence_axis_errors():
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="sequence"):
        sequence_parallel_attention(q, k, v, mesh)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_transformer_layer_sequence_parallel(impl):
    """The fused layer under a sequence-sharded mesh matches single-device."""
    from deepspeed_tpu.ops.transformer import (
        DeepSpeedTransformerConfig,
        DeepSpeedTransformerLayer,
    )

    mesh = _mesh()
    cfg = DeepSpeedTransformerConfig(
        hidden_size=64, heads=4, attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0
    )
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 64, 64)), jnp.float32
    )
    base = DeepSpeedTransformerLayer(cfg)
    params = base.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    ref = base.apply(params, x, train=False)
    sp_layer = DeepSpeedTransformerLayer(cfg, mesh=mesh, seq_parallel_impl=impl)
    out = sp_layer.apply(params, x, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


def test_ring_dropout_preserves_distribution_not_bits():
    """Documents the ring-dropout contract (VERDICT r1 weak #8): the mask
    BIT LAYOUT differs from single-device dropout (one folded key per
    (device, ring hop)), but the DISTRIBUTION is preserved — every softmax
    prob entry is dropped iid Bernoulli(rate) with 1/(1-rate) rescale, so
    the dropped attention output is an unbiased estimator of the clean
    output (dropout is applied post-normalization, matching the reference's
    saved-byte-mask semantics, dropout_kernels.cu)."""
    mesh = _mesh(sp=4, dp=2)
    q, k, v = _qkv(b=2, h=2, s=32, d=8, seed=7)
    rate = 0.3
    clean = ring_attention(q, k, v, mesh)

    # bits: a single-device dropout with the same key gives a different
    # output than the ring decomposition (per-hop folded keys)
    key = jax.random.PRNGKey(0)
    ring_out = ring_attention(q, k, v, mesh, dropout_rate=rate, dropout_rng=key)
    single = mha_reference(q, k, v, dropout_rate=rate, dropout_rng=key)
    assert not np.allclose(np.asarray(ring_out), np.asarray(single), atol=1e-6)

    # distribution: averaging over seeds converges to the clean output
    # (unbiasedness), and individual draws genuinely differ (dropout is on)
    f = jax.jit(
        lambda key: ring_attention(q, k, v, mesh, dropout_rate=rate, dropout_rng=key)
    )
    draws = np.stack(
        [np.asarray(f(jax.random.PRNGKey(i))) for i in range(200)]
    )
    assert draws.std(axis=0).max() > 1e-3, "dropout appears inactive"
    mean = draws.mean(axis=0)
    err = np.abs(mean - np.asarray(clean))
    # MC error ~ sigma/sqrt(200); loose 4-sigma style bound
    tol = 4.0 * draws.std(axis=0) / np.sqrt(200) + 5e-3
    assert (err < tol).mean() > 0.99, (
        f"ring dropout is biased: {np.mean(err)} vs tol {np.mean(tol)}"
    )
