"""Flash attention under data/model-parallel meshes (shard_map path).

The reference's fused attention kernel runs independently on every
data-parallel GPU (csrc/transformer/ds_transformer_cuda.cpp:217-231); the
TPU analog must keep the O(S) Pallas kernel per-shard under dp/mp meshes
instead of silently degrading to the O(S^2) XLA path. These tests assert
numerical parity of the shard_map'd kernel against ``mha_reference`` on the
virtual 8-device mesh (dp=4 x mp=2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import importlib

pytestmark = pytest.mark.slow  # compile-heavy; excluded from `make test-fast`

attn_lib = importlib.import_module("deepspeed_tpu.ops.attention")
from deepspeed_tpu.ops.attention import (
    attention,
    flash_attention_sharded,
    mha_reference,
)
from deepspeed_tpu.parallel.mesh import build_mesh


def _qkv(b=8, h=4, s=256, d=64, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) * 0.5 for k in ks)


@pytest.fixture(scope="module")
def dp_mp_mesh():
    return build_mesh(data_parallel_size=4, model_parallel_size=2)


def test_sharded_flash_matches_reference(dp_mp_mesh):
    q, k, v = _qkv()
    out = jax.jit(
        lambda q, k, v: flash_attention_sharded(q, k, v, dp_mp_mesh)
    )(q, k, v)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_flash_causal_matches_reference(dp_mp_mesh):
    q, k, v = _qkv(seed=1)
    out = jax.jit(
        lambda q, k, v: flash_attention_sharded(q, k, v, dp_mp_mesh, causal=True)
    )(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_flash_kv_mask_matches_reference(dp_mp_mesh):
    q, k, v = _qkv(seed=2)
    b, _, s, _ = q.shape
    kv_valid = (
        jnp.arange(s)[None, :] < jnp.asarray([s, s // 2] * (b // 2))[:, None]
    ).astype(jnp.int32)
    additive = jnp.where(kv_valid[:, None, None, :] > 0, 0.0, attn_lib.NEG_INF)
    out = jax.jit(
        lambda q, k, v, m: flash_attention_sharded(
            q, k, v, dp_mp_mesh, kv_mask=m
        )
    )(q, k, v, kv_valid)
    ref = mha_reference(q, k, v, mask=additive)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_flash_gradients_match_reference(dp_mp_mesh):
    q, k, v = _qkv(b=4, h=2, s=256, d=64, seed=3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention_sharded(q, k, v, dp_mp_mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v) ** 2)

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_dispatcher_routes_to_sharded_flash(dp_mp_mesh, monkeypatch):
    """attention(mesh=...) must take the shard_map path (not mha_reference)
    for a dp/mp mesh with clean tiling."""
    called = {}
    real = attn_lib.flash_attention_sharded

    def spy(*a, **kw):
        called["yes"] = True
        return real(*a, **kw)

    monkeypatch.setattr(attn_lib, "flash_attention_sharded", spy)
    q, k, v = _qkv(seed=4)
    out = attention(q, k, v, mesh=dp_mp_mesh)
    assert called.get("yes"), "dispatcher fell back off the shard_map path"
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_dispatcher_falls_back_when_heads_do_not_divide(dp_mp_mesh):
    # 3 heads % mp=2 != 0 -> must fall back to the XLA path, still correct
    q, k, v = _qkv(h=3, seed=5)
    out = attention(q, k, v, mesh=dp_mp_mesh)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_autotune_flash_blocks_smoke():
    """gemm_test.h analog: sweeps candidates, returns a valid best pair."""
    from deepspeed_tpu.ops.autotune import autotune_flash_blocks

    (bq, bk), table = autotune_flash_blocks(
        2, 2, 128, 64, causal=True, dtype=jnp.float32,
        candidates=((64, 64), (128, 128)), steps=1,
    )
    assert (bq, bk) in table and len(table) == 2
    # cached second call returns identical result without re-timing
    again, _ = autotune_flash_blocks(
        2, 2, 128, 64, causal=True, dtype=jnp.float32,
        candidates=((64, 64), (128, 128)), steps=1,
    )
    assert again == (bq, bk)


def test_pick_block_falls_back_to_dividing_block():
    from deepspeed_tpu.ops.attention import pick_block

    assert pick_block(1024, 512) == 512
    assert pick_block(768, 512) == 256   # 768 % 512 != 0 -> halve
    assert pick_block(128, 512) == 128
    assert pick_block(17, 512) == 17     # single full-dim block is tileable
    assert pick_block(1030, 512) == 0    # 2*5*103: nothing >= 8 divides


def test_resolve_remat_policy_rejects_typos():
    import pytest as _pytest

    from deepspeed_tpu.ops.transformer import resolve_remat_policy

    resolve_remat_policy("dots_with_no_batch_dims_saveable+flash_out")
    with _pytest.raises(ValueError, match="unknown remat policy part"):
        resolve_remat_policy("dots_with_no_batch_dims_savable")  # typo
