"""Crash-safe checkpointing / preemption resilience tests
(deepspeed_tpu/resilience/, docs/resilience.md).

Fault injection is a monkeypatched filesystem (resilience.atomic_io is
the single I/O choke point) — no real kills: a "crash" is an exception
raised at a chosen filesystem operation, which leaves exactly the on-disk
state a SIGKILL at that instant would.

Engine-integration tests use the smallest engine that exercises the real
save/load paths (one Dense layer, one or two steps); the compile-heavy
full matrix lives in test_checkpointing.py (slow-marked).
"""

import json
import os
import shutil
import signal

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.resilience import atomic_io, manifest, retention
from deepspeed_tpu.resilience.atomic_io import RetryPolicy, with_retries
from deepspeed_tpu.resilience.manager import ResilienceManager
from deepspeed_tpu.resilience.preemption import (
    PreemptionHandler,
    resolve_signals,
)
from tests.unit.simple_model import SimpleModel, config_dict, init_model, random_dataset

INPUT_DIM = 8


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------
def test_retry_policy_validation():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(backoff_base=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)


def test_retry_policy_delay_doubles_and_caps():
    p = RetryPolicy(max_attempts=5, backoff_base=1.0, backoff_max=3.0, jitter=0)
    assert p.delay(1) == 1.0
    assert p.delay(2) == 2.0
    assert p.delay(3) == 3.0  # capped
    assert p.delay(4) == 3.0


def test_with_retries_recovers_from_transient_oserror():
    calls = {"n": 0}
    retries = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    result = with_retries(
        flaky, policy=RetryPolicy(max_attempts=3, backoff_base=0.001),
        on_retry=lambda op, attempt, e: retries.append(attempt),
        sleep=lambda s: None,
    )
    assert result == "ok"
    assert retries == [1, 2]


def test_with_retries_exhausts_and_reraises():
    def always_fails():
        raise OSError("dead mount")

    with pytest.raises(OSError):
        with_retries(
            always_fails,
            policy=RetryPolicy(max_attempts=2, backoff_base=0.001),
            sleep=lambda s: None,
        )


def test_with_retries_does_not_retry_corruption():
    calls = {"n": 0}

    def parse_error():
        calls["n"] += 1
        raise ValueError("truncated msgpack")

    with pytest.raises(ValueError):
        with_retries(parse_error, policy=RetryPolicy(max_attempts=5))
    assert calls["n"] == 1  # corruption is not transient


# ---------------------------------------------------------------------------
# atomic writes
# ---------------------------------------------------------------------------
def test_atomic_write_roundtrip_no_temp_leftover(tmp_path):
    path = tmp_path / "f.bin"
    atomic_io.atomic_write_bytes(str(path), b"payload")
    assert path.read_bytes() == b"payload"
    assert [p.name for p in tmp_path.iterdir()] == ["f.bin"]


def test_atomic_write_crash_preserves_old_content(tmp_path, monkeypatch):
    path = tmp_path / "f.bin"
    atomic_io.atomic_write_bytes(str(path), b"old")

    def crash(src, dst):
        raise OSError("killed mid-publish")

    monkeypatch.setattr(atomic_io.os, "replace", crash)
    with pytest.raises(OSError):
        atomic_io.atomic_write_bytes(str(path), b"new-but-never-published")
    monkeypatch.undo()
    assert path.read_bytes() == b"old"  # never torn, never replaced
    assert [p.name for p in tmp_path.iterdir()] == ["f.bin"]  # tmp cleaned


# ---------------------------------------------------------------------------
# manifest verdicts
# ---------------------------------------------------------------------------
def _fake_checkpoint(dirpath, tag="t", steps=5):
    os.makedirs(dirpath, exist_ok=True)
    for name, blob in (
        ("mp_rank_00_model_states.msgpack", b"model" * 100),
        ("zero_pp_rank_0_mp_rank_00optim_states.msgpack", b"optim" * 100),
    ):
        with open(os.path.join(dirpath, name), "wb") as f:
            f.write(blob)
    manifest.write_manifest(dirpath, tag, meta={"global_steps": steps})


def test_manifest_verify_valid(tmp_path):
    d = str(tmp_path / "t")
    _fake_checkpoint(d)
    status, reason = manifest.verify_checkpoint(d)
    assert status == manifest.VALID, reason
    m = json.load(open(os.path.join(d, manifest.MANIFEST_FILE)))
    assert set(m["files"]) == {
        "mp_rank_00_model_states.msgpack",
        "zero_pp_rank_0_mp_rank_00optim_states.msgpack",
    }
    assert m["global_steps"] == 5


def test_manifest_detects_truncation_and_bitflips(tmp_path):
    d = str(tmp_path / "t")
    _fake_checkpoint(d)
    f = os.path.join(d, "mp_rank_00_model_states.msgpack")
    blob = open(f, "rb").read()
    open(f, "wb").write(blob[: len(blob) // 2])
    status, reason = manifest.verify_checkpoint(d)
    assert status == manifest.CORRUPT and "size" in reason
    # same size, flipped byte: only the deep sha pass catches it
    open(f, "wb").write(bytes([blob[0] ^ 0xFF]) + blob[1:])
    status, reason = manifest.verify_checkpoint(d)
    assert status == manifest.CORRUPT and "sha256" in reason
    assert manifest.verify_checkpoint(d, deep=False)[0] == manifest.VALID


def test_manifest_detects_missing_file(tmp_path):
    d = str(tmp_path / "t")
    _fake_checkpoint(d)
    os.unlink(os.path.join(d, "zero_pp_rank_0_mp_rank_00optim_states.msgpack"))
    status, reason = manifest.verify_checkpoint(d)
    assert status == manifest.CORRUPT and "missing" in reason


def test_manifest_legacy_and_missing_verdicts(tmp_path):
    d = str(tmp_path / "legacy")
    _fake_checkpoint(d)
    os.unlink(os.path.join(d, manifest.MANIFEST_FILE))
    assert manifest.verify_checkpoint(d)[0] == manifest.LEGACY
    assert manifest.verify_checkpoint(str(tmp_path / "nope"))[0] == manifest.MISSING
    empty = tmp_path / "empty"
    empty.mkdir()
    assert manifest.verify_checkpoint(str(empty))[0] == manifest.MISSING


def test_manifest_malformed_json_is_corrupt(tmp_path):
    d = str(tmp_path / "t")
    _fake_checkpoint(d)
    open(os.path.join(d, manifest.MANIFEST_FILE), "w").write("{not json")
    assert manifest.verify_checkpoint(d)[0] == manifest.CORRUPT


def test_ordered_tags_survives_malformed_manifest_values(tmp_path):
    """One sibling tag with a parseable-but-malformed manifest (null
    global_steps, string created_unix) must degrade to mtime ordering,
    not crash the scan every later save/load runs."""
    _fake_checkpoint(str(tmp_path / "good"), tag="good", steps=3)
    bad = str(tmp_path / "bad")
    _fake_checkpoint(bad, tag="bad", steps=1)
    m = json.load(open(os.path.join(bad, manifest.MANIFEST_FILE)))
    m["global_steps"] = None
    m["created_unix"] = "yesterday"
    json.dump(m, open(os.path.join(bad, manifest.MANIFEST_FILE), "w"))
    tags = manifest.ordered_tags(str(tmp_path))
    assert set(tags) == {"good", "bad"}
    assert tags[0] == "good"  # steps=3 outranks the degraded entry


def test_ordered_tags_newest_first(tmp_path):
    for i, tag in enumerate(["a", "b", "c"]):
        _fake_checkpoint(str(tmp_path / tag), tag=tag, steps=i * 10)
    assert manifest.ordered_tags(str(tmp_path)) == ["c", "b", "a"]
    # files (e.g. `latest`) are not tags
    (tmp_path / "latest").write_text("c")
    assert manifest.ordered_tags(str(tmp_path)) == ["c", "b", "a"]


# ---------------------------------------------------------------------------
# retention GC
# ---------------------------------------------------------------------------
def test_retention_prunes_oldest_keeps_newest(tmp_path):
    for i in range(5):
        _fake_checkpoint(str(tmp_path / f"step{i}"), tag=f"step{i}", steps=i)
    (tmp_path / "latest").write_text("step4")
    deleted = retention.prune_checkpoints(str(tmp_path), keep_last_n=2)
    assert sorted(deleted) == ["step0", "step1", "step2"]
    assert sorted(p.name for p in tmp_path.iterdir() if p.is_dir()) == [
        "step3", "step4",
    ]


def test_retention_zero_keeps_everything(tmp_path):
    for i in range(3):
        _fake_checkpoint(str(tmp_path / f"step{i}"), steps=i)
    assert retention.prune_checkpoints(str(tmp_path), keep_last_n=0) == []
    assert len(list(tmp_path.iterdir())) == 3


def test_retention_never_deletes_newest_valid_or_latest_target(tmp_path):
    # newest two tags are corrupt; the only valid one is oldest AND is the
    # latest target — keep_last_n=1 must keep it and may drop the corrupt
    # newer ones
    _fake_checkpoint(str(tmp_path / "good"), tag="good", steps=0)
    for i, tag in enumerate(["bad1", "bad2"]):
        d = str(tmp_path / tag)
        _fake_checkpoint(d, tag=tag, steps=10 + i)
        os.unlink(os.path.join(d, "mp_rank_00_model_states.msgpack"))
    (tmp_path / "latest").write_text("good")
    retention.prune_checkpoints(str(tmp_path), keep_last_n=1)
    assert (tmp_path / "good").is_dir()


# ---------------------------------------------------------------------------
# preemption handler
# ---------------------------------------------------------------------------
def test_resolve_signals_rejects_unknown():
    assert resolve_signals(["SIGTERM", "SIGINT"]) == [
        signal.SIGTERM, signal.SIGINT,
    ]
    with pytest.raises(ValueError):
        resolve_signals(["SIGNOPE"])


def test_preemption_arms_on_signal_and_disarms():
    h = PreemptionHandler()
    assert not h.armed
    h._on_signal(signal.SIGTERM, None)  # handler body, no real delivery
    assert h.armed
    h.disarm()
    assert not h.armed


def test_preemption_second_signal_exits_immediately(monkeypatch):
    h = PreemptionHandler()
    kills = []
    monkeypatch.setattr(
        "deepspeed_tpu.resilience.preemption.os.kill",
        lambda pid, sig: kills.append((pid, sig)),
    )
    h._on_signal(signal.SIGTERM, None)
    assert h.armed and not kills
    h._on_signal(signal.SIGTERM, None)  # operator insists
    assert kills == [(os.getpid(), signal.SIGTERM)]
    assert not h.armed


def test_preemption_install_uninstall_restores_disposition():
    h = PreemptionHandler(signals=("SIGUSR1",))
    prev = signal.getsignal(signal.SIGUSR1)
    assert h.install()
    assert signal.getsignal(signal.SIGUSR1) == h._on_signal
    h.uninstall()
    assert signal.getsignal(signal.SIGUSR1) == prev


# ---------------------------------------------------------------------------
# config block
# ---------------------------------------------------------------------------
def _res_cfg(block):
    return DeepSpeedConfig(
        None,
        param_dict={"train_batch_size": 8, "resilience": block},
        world_size=1,
    )


def test_config_defaults():
    cfg = DeepSpeedConfig(
        None, param_dict={"train_batch_size": 8}, world_size=1
    )
    assert cfg.resilience_enabled is True
    assert cfg.resilience_fsync is True
    assert cfg.resilience_keep_last_n == 0
    assert cfg.resilience_retry_max_attempts == 3
    assert cfg.resilience_preemption_enabled is False
    assert cfg.resilience_preemption_signals == ["SIGTERM", "SIGINT"]


def test_config_rejects_bad_values():
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"keep_last_n": -1})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"keep_last_n": True})  # bool is not a count
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"retry": {"max_attempts": 0}})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"retry": {"backoff_base": 0}})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"retry": {"jitter": 2.0}})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"preemption": {"signals": ["SIGNOPE"]}})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"preemption": {"signals": "SIGTERM"}})  # bare string
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"preemption": {"tag_prefix": "a/b"}})


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------
def _make_engine(cfg_extra=None, seed=0):
    model = SimpleModel(hidden_dim=8)
    params = init_model(model, INPUT_DIM, seed=seed)
    cfg = config_dict(batch_size=8, lr=1e-2, zero_stage=1)
    cfg.update(cfg_extra or {})
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg
    )
    return engine


def _run_steps(engine, n=1, seed=0):
    bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
    x, y = random_dataset(bs * n, INPUT_DIM, seed=seed)
    for b in range(n):
        loss = engine(x[b * bs : (b + 1) * bs], y[b * bs : (b + 1) * bs])
        engine.backward(loss)
        engine.step()


def _snapshot(engine):
    return {
        "params": jax.tree_util.tree_map(
            lambda a: np.asarray(a).copy(), engine.params
        ),
        "opt": jax.tree_util.tree_map(
            lambda a: np.asarray(a).copy(), engine.optimizer_state
        ),
        "global_steps": engine.global_steps,
        "skipped_steps": engine.skipped_steps,
        "micro_steps": engine.micro_steps,
    }


def _assert_matches(engine, snap):
    for a, b in zip(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, engine.params)
        ),
        jax.tree_util.tree_leaves(snap["params"]),
    ):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, engine.optimizer_state)
        ),
        jax.tree_util.tree_leaves(snap["opt"]),
    ):
        np.testing.assert_array_equal(a, b)
    assert engine.global_steps == snap["global_steps"]
    assert engine.skipped_steps == snap["skipped_steps"]
    assert engine.micro_steps == snap["micro_steps"]


@pytest.fixture(scope="module")
def saved_pair(tmp_path_factory):
    """One engine advanced through two saves (tagA at step 1, tagB at
    step 2) plus bitwise snapshots of the engine state at each save —
    the corruption matrix copies this base tree per case."""
    base = tmp_path_factory.mktemp("ckpt_base")
    engine = _make_engine(seed=1)
    _run_steps(engine, n=1, seed=0)
    engine.save_checkpoint(str(base), tag="tagA")
    snap_a = _snapshot(engine)
    _run_steps(engine, n=1, seed=1)
    engine.save_checkpoint(str(base), tag="tagB")
    snap_b = _snapshot(engine)
    return str(base), snap_a, snap_b


@pytest.fixture(scope="module")
def loader_engine():
    """One reusable restore target (loads fully overwrite its state)."""
    return _make_engine(seed=7)


def _case_dir(tmp_path, saved_base):
    dst = str(tmp_path / "case")
    shutil.copytree(saved_base, dst)
    return dst


def test_save_writes_verified_manifest_and_latest(saved_pair):
    base, _, _ = saved_pair
    assert open(os.path.join(base, "latest")).read() == "tagB"
    for tag in ("tagA", "tagB"):
        status, reason = manifest.verify_checkpoint(os.path.join(base, tag))
        assert status == manifest.VALID, (tag, reason)
    m = json.load(
        open(os.path.join(base, "tagB", manifest.MANIFEST_FILE))
    )
    # model file + one shard per dp rank, all hashed
    assert len(m["files"]) == 1 + 8
    assert all(
        len(e["sha256"]) == 64 and e["size"] > 0 for e in m["files"].values()
    )


def test_clean_load_is_bitwise_identical(saved_pair, loader_engine):
    base, _, snap_b = saved_pair
    path, _ = loader_engine.load_checkpoint(base)
    assert path is not None
    _assert_matches(loader_engine, snap_b)


# ---- the corruption matrix ------------------------------------------------
def _corrupt_truncate(path):
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) // 3])


def test_corrupt_truncated_model_falls_back(tmp_path, saved_pair, loader_engine):
    base, snap_a, _ = saved_pair
    d = _case_dir(tmp_path, base)
    _corrupt_truncate(os.path.join(d, "tagB", "mp_rank_00_model_states.msgpack"))
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagA" in path
    _assert_matches(loader_engine, snap_a)
    snap = loader_engine.resilience.registry.snapshot()
    assert snap["resilience/corruption_fallbacks"] >= 1


def test_corrupt_missing_optim_shard_falls_back(tmp_path, saved_pair, loader_engine):
    base, snap_a, _ = saved_pair
    d = _case_dir(tmp_path, base)
    os.unlink(
        os.path.join(d, "tagB", "zero_pp_rank_3_mp_rank_00optim_states.msgpack")
    )
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagA" in path
    _assert_matches(loader_engine, snap_a)


def test_corrupt_truncated_optim_shard_falls_back(tmp_path, saved_pair, loader_engine):
    base, snap_a, _ = saved_pair
    d = _case_dir(tmp_path, base)
    _corrupt_truncate(
        os.path.join(d, "tagB", "zero_pp_rank_0_mp_rank_00optim_states.msgpack")
    )
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagA" in path
    _assert_matches(loader_engine, snap_a)


def test_latest_pointing_at_deleted_tag_falls_back(tmp_path, saved_pair, loader_engine):
    base, snap_a, _ = saved_pair
    d = _case_dir(tmp_path, base)
    shutil.rmtree(os.path.join(d, "tagB"))  # latest still says tagB
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagA" in path
    _assert_matches(loader_engine, snap_a)


def test_kill_between_shard_write_and_tag_publish(tmp_path, saved_pair, loader_engine):
    """A save killed after the shard writes but before the manifest/tag
    publish: the torn tagC directory exists with no manifest, `latest`
    still names tagB — the next load must resume tagB untouched."""
    base, _, snap_b = saved_pair
    d = _case_dir(tmp_path, base)
    torn = os.path.join(d, "tagC")
    shutil.copytree(os.path.join(d, "tagB"), torn)
    os.unlink(os.path.join(torn, manifest.MANIFEST_FILE))
    _corrupt_truncate(
        os.path.join(torn, "zero_pp_rank_7_mp_rank_00optim_states.msgpack")
    )
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagB" in path
    _assert_matches(loader_engine, snap_b)


def test_explicit_tag_never_silently_substitutes(tmp_path, saved_pair, loader_engine):
    base, _, _ = saved_pair
    d = _case_dir(tmp_path, base)
    _corrupt_truncate(os.path.join(d, "tagB", "mp_rank_00_model_states.msgpack"))
    path, client = loader_engine.load_checkpoint(d, tag="tagB")
    assert path is None and client == {}


def test_no_loadable_checkpoint_returns_none(tmp_path, saved_pair, loader_engine):
    base, _, _ = saved_pair
    d = _case_dir(tmp_path, base)
    for tag in ("tagA", "tagB"):
        _corrupt_truncate(
            os.path.join(d, tag, "mp_rank_00_model_states.msgpack")
        )
    snap_before = _snapshot(loader_engine)
    path, client = loader_engine.load_checkpoint(d)
    assert path is None and client == {}
    _assert_matches(loader_engine, snap_before)


# ---- partial-restore regression (ISSUE satellite) -------------------------
def test_partial_restore_leaves_engine_untouched(tmp_path, saved_pair, loader_engine):
    """Regression for the pre-resilience bug: load_checkpoint overwrote
    engine.params before optimizer shards were parsed, so a truncated
    shard raised mid-restore and left the engine half-loaded. The
    transactional load must leave EVERY engine field untouched when any
    file fails to parse — including on the legacy (manifest-less) path,
    where the failure only surfaces at msgpack parse time."""
    base, _, _ = saved_pair
    d = _case_dir(tmp_path, base)
    shutil.rmtree(os.path.join(d, "tagA"))  # no fallback candidate
    torn = os.path.join(d, "tagB")
    os.unlink(os.path.join(torn, manifest.MANIFEST_FILE))  # legacy path
    _corrupt_truncate(
        os.path.join(torn, "zero_pp_rank_2_mp_rank_00optim_states.msgpack")
    )
    snap_before = _snapshot(loader_engine)
    path, client = loader_engine.load_checkpoint(d)
    assert path is None and client == {}
    _assert_matches(loader_engine, snap_before)


# ---- crash sweep: kill at EVERY filesystem publish during save ------------
def test_save_crash_sweep_never_publishes_torn_checkpoint(
    tmp_path, saved_pair, loader_engine
):
    """Acceptance: a simulated crash at any point during save_checkpoint
    never leaves `latest` pointing at an incomplete checkpoint, and the
    next load resumes a valid tag with engine state bitwise-identical to
    that tag's save. Every checkpoint file (and the manifest and the
    `latest` pointer) publishes through atomic_io's os.replace — crashing
    at the k-th replace, for every k, covers every commit-order prefix."""
    base, snap_a, snap_b = saved_pair
    engine = _make_engine(seed=3)
    _run_steps(engine, n=1, seed=5)

    class SimulatedKill(BaseException):
        """Not an Exception: nothing on the save path may swallow it."""

    real_replace = atomic_io.os.replace
    # count the publish ops of one full save (model + dp shards +
    # manifest + latest) so the sweep tracks layout changes automatically
    probe_calls = {"n": 0}

    def counting_replace(src, dst):
        probe_calls["n"] += 1
        return real_replace(src, dst)

    atomic_io.os.replace = counting_replace
    try:
        engine.save_checkpoint(str(tmp_path / "probe"), tag="probe")
    finally:
        atomic_io.os.replace = real_replace
    n_ops = probe_calls["n"]
    assert n_ops == 1 + engine.dp_world_size + 1 + 1
    for k in range(n_ops):
        workdir = str(tmp_path / f"crash{k}")
        shutil.copytree(base, workdir)
        calls = {"n": 0}

        def crashing_replace(src, dst, _k=k, _calls=calls):
            if _calls["n"] == _k:
                raise SimulatedKill(f"killed at publish op {_k}")
            _calls["n"] += 1
            return real_replace(src, dst)

        atomic_io.os.replace = crashing_replace
        try:
            with pytest.raises(SimulatedKill):
                engine.save_checkpoint(workdir, tag="tagC")
        finally:
            atomic_io.os.replace = real_replace
        # latest must still name a COMPLETE checkpoint...
        latest = open(os.path.join(workdir, "latest")).read().strip()
        status, reason = manifest.verify_checkpoint(
            os.path.join(workdir, latest)
        )
        assert status == manifest.VALID, (k, latest, reason)
        assert latest == "tagB", (k, latest)
        # ...and the next load resumes it bitwise-identically
        path, _ = loader_engine.load_checkpoint(workdir)
        assert path is not None and latest in path, (k, path)
        _assert_matches(loader_engine, snap_b)
        shutil.rmtree(workdir)
    # the un-crashed save publishes tagC and becomes the resume point
    workdir = str(tmp_path / "clean")
    shutil.copytree(base, workdir)
    engine.save_checkpoint(workdir, tag="tagC")
    snap_c = _snapshot(engine)
    assert open(os.path.join(workdir, "latest")).read().strip() == "tagC"
    path, _ = loader_engine.load_checkpoint(workdir)
    assert path is not None and "tagC" in path
    _assert_matches(loader_engine, snap_c)


# ---- retry integration ----------------------------------------------------
def test_save_retries_transient_write_failures(tmp_path, saved_pair):
    engine = _make_engine(
        cfg_extra={
            "resilience": {
                "retry": {"max_attempts": 3, "backoff_base": 0.001}
            }
        },
        seed=2,
    )
    _run_steps(engine, n=1, seed=2)
    real_replace = atomic_io.os.replace
    fails = {"n": 2}  # first two publishes flake, then the mount recovers

    def flaky_replace(src, dst):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("transient fuse error")
        return real_replace(src, dst)

    atomic_io.os.replace = flaky_replace
    try:
        assert engine.save_checkpoint(str(tmp_path), tag="t") is True
    finally:
        atomic_io.os.replace = real_replace
    assert manifest.verify_checkpoint(str(tmp_path / "t"))[0] == manifest.VALID
    snap = engine.resilience.registry.snapshot()
    assert snap["resilience/io_retries"] == 2
    assert snap["resilience/save_time_ms/count"] == 1


# ---- retention integration ------------------------------------------------
def test_keep_last_n_prunes_after_save(tmp_path):
    engine = _make_engine(
        cfg_extra={"resilience": {"keep_last_n": 2}}, seed=4
    )
    _run_steps(engine, n=1, seed=3)
    for i in range(4):
        engine.save_checkpoint(str(tmp_path), tag=f"s{i}")
    dirs = sorted(p.name for p in tmp_path.iterdir() if p.is_dir())
    assert dirs == ["s2", "s3"]
    assert open(tmp_path / "latest").read() == "s3"
    snap = engine.resilience.registry.snapshot()
    assert snap["resilience/checkpoints_pruned"] == 2


# ---- preemption drain integration -----------------------------------------
def test_preemption_drain_saves_at_step_boundary(tmp_path):
    engine = _make_engine(
        cfg_extra={
            "resilience": {
                "preemption": {
                    "enabled": True,
                    "save_dir": str(tmp_path),
                    "exit_after_save": False,  # keep the test process alive
                }
            }
        },
        seed=5,
    )
    try:
        assert engine.resilience.preemption is not None
        _run_steps(engine, n=1, seed=4)
        assert not list(tmp_path.iterdir())  # unarmed: no drain save
        # a SIGTERM lands mid-window: the handler only arms a flag...
        engine.resilience.preemption._on_signal(signal.SIGTERM, None)
        assert engine.resilience.preemption_armed
        # ...and the next step boundary commits the final checkpoint
        _run_steps(engine, n=1, seed=6)
        tag = f"preempt_global_step{engine.global_steps}"
        status, reason = manifest.verify_checkpoint(str(tmp_path / tag))
        assert status == manifest.VALID, reason
        assert open(tmp_path / "latest").read() == tag
        assert not engine.resilience.preemption_armed  # disarmed after save
        snap = engine.resilience.registry.snapshot()
        assert snap["resilience/preemption_saves"] == 1
        # snapshot state matches the engine bitwise (resume-ready)
        loader = _make_engine(seed=6)
        loader.load_checkpoint(str(tmp_path))
        _assert_matches(loader, _snapshot(engine))
    finally:
        if engine.resilience.preemption is not None:
            engine.resilience.preemption.uninstall()


def test_preemption_exit_after_save_resignals(tmp_path, monkeypatch):
    engine = _make_engine(
        cfg_extra={
            "resilience": {
                "preemption": {"enabled": True, "save_dir": str(tmp_path)}
            }
        },
        seed=8,
    )
    kills = []
    monkeypatch.setattr(
        "deepspeed_tpu.resilience.preemption.os.kill",
        lambda pid, sig: kills.append(sig),
    )
    try:
        _run_steps(engine, n=1, seed=7)
        engine.resilience.preemption.arm(signal.SIGTERM)
        _run_steps(engine, n=1, seed=8)
        assert kills == [signal.SIGTERM]  # original disposition re-raised
        tag = f"preempt_global_step{engine.global_steps}"
        assert manifest.verify_checkpoint(str(tmp_path / tag))[0] == manifest.VALID
    finally:
        engine.resilience.preemption.uninstall()


def test_preemption_without_save_target_warns_not_crashes():
    engine = _make_engine(
        cfg_extra={
            "resilience": {
                "preemption": {"enabled": True, "exit_after_save": False}
            }
        },
        seed=9,
    )
    try:
        engine.resilience.preemption.arm()
        _run_steps(engine, n=1, seed=9)  # no save dir known: warns, trains on
        assert engine.global_steps == 1
    finally:
        engine.resilience.preemption.uninstall()


# ---- disabled resilience keeps the legacy write path -----------------------
def test_resilience_disabled_writes_bare_files(tmp_path):
    engine = _make_engine(
        cfg_extra={"resilience": {"enabled": False}}, seed=10
    )
    _run_steps(engine, n=1, seed=10)
    engine.save_checkpoint(str(tmp_path), tag="t")
    files = sorted(p.name for p in (tmp_path / "t").iterdir())
    assert manifest.MANIFEST_FILE not in files  # legacy layout
    assert any("model_states" in f for f in files)
    # and the legacy checkpoint still loads (as LEGACY, parse-validated)
    loader = _make_engine(seed=11)
    path, _ = loader.load_checkpoint(str(tmp_path))
    assert path is not None
    _assert_matches(loader, _snapshot(engine))


# ---- telemetry integration -------------------------------------------------
def test_resilience_shares_telemetry_registry(tmp_path):
    engine = _make_engine(
        cfg_extra={
            "telemetry": {
                "enabled": True,
                "output_path": str(tmp_path),
                "job_name": "job",
                "watchdog": {"enabled": False},
            }
        },
        seed=12,
    )
    try:
        assert engine.resilience.registry is engine.telemetry.registry
        _run_steps(engine, n=1, seed=12)
        engine.save_checkpoint(str(tmp_path / "ck"))
        engine.flush_monitor()
        lines = [
            json.loads(l)
            for l in open(
                tmp_path / "job" / "metrics.jsonl"
            ).read().splitlines()
        ]
        tags = {l["tag"] for l in lines}
        assert "resilience/io_retries" in tags
        assert "resilience/save_time_ms" in tags
    finally:
        engine.telemetry.close()
