"""Crash-safe checkpointing / preemption resilience tests
(deepspeed_tpu/resilience/, docs/resilience.md).

Fault injection is a monkeypatched filesystem (resilience.atomic_io is
the single I/O choke point) — no real kills: a "crash" is an exception
raised at a chosen filesystem operation, which leaves exactly the on-disk
state a SIGKILL at that instant would.

Engine-integration tests use the smallest engine that exercises the real
save/load paths (one Dense layer, one or two steps); the compile-heavy
full matrix lives in test_checkpointing.py (slow-marked).
"""

import json
import os
import shutil
import signal

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.resilience import atomic_io, manifest, retention
from deepspeed_tpu.resilience.atomic_io import RetryPolicy, with_retries
from deepspeed_tpu.resilience.manager import ResilienceManager
from deepspeed_tpu.resilience.preemption import (
    PreemptionHandler,
    resolve_signals,
)
from tests.unit.simple_model import SimpleModel, config_dict, init_model, random_dataset

INPUT_DIM = 8


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------
def test_retry_policy_validation():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(backoff_base=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)


def test_retry_policy_delay_doubles_and_caps():
    p = RetryPolicy(max_attempts=5, backoff_base=1.0, backoff_max=3.0, jitter=0)
    assert p.delay(1) == 1.0
    assert p.delay(2) == 2.0
    assert p.delay(3) == 3.0  # capped
    assert p.delay(4) == 3.0


def test_with_retries_recovers_from_transient_oserror():
    calls = {"n": 0}
    retries = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    result = with_retries(
        flaky, policy=RetryPolicy(max_attempts=3, backoff_base=0.001),
        on_retry=lambda op, attempt, e: retries.append(attempt),
        sleep=lambda s: None,
    )
    assert result == "ok"
    assert retries == [1, 2]


def test_with_retries_exhausts_and_reraises():
    def always_fails():
        raise OSError("dead mount")

    with pytest.raises(OSError):
        with_retries(
            always_fails,
            policy=RetryPolicy(max_attempts=2, backoff_base=0.001),
            sleep=lambda s: None,
        )


def test_with_retries_does_not_retry_corruption():
    calls = {"n": 0}

    def parse_error():
        calls["n"] += 1
        raise ValueError("truncated msgpack")

    with pytest.raises(ValueError):
        with_retries(parse_error, policy=RetryPolicy(max_attempts=5))
    assert calls["n"] == 1  # corruption is not transient


# ---------------------------------------------------------------------------
# atomic writes
# ---------------------------------------------------------------------------
def test_atomic_write_roundtrip_no_temp_leftover(tmp_path):
    path = tmp_path / "f.bin"
    atomic_io.atomic_write_bytes(str(path), b"payload")
    assert path.read_bytes() == b"payload"
    assert [p.name for p in tmp_path.iterdir()] == ["f.bin"]


def test_atomic_write_crash_preserves_old_content(tmp_path, monkeypatch):
    path = tmp_path / "f.bin"
    atomic_io.atomic_write_bytes(str(path), b"old")

    def crash(src, dst):
        raise OSError("killed mid-publish")

    monkeypatch.setattr(atomic_io.os, "replace", crash)
    with pytest.raises(OSError):
        atomic_io.atomic_write_bytes(str(path), b"new-but-never-published")
    monkeypatch.undo()
    assert path.read_bytes() == b"old"  # never torn, never replaced
    assert [p.name for p in tmp_path.iterdir()] == ["f.bin"]  # tmp cleaned


# ---------------------------------------------------------------------------
# manifest verdicts
# ---------------------------------------------------------------------------
def _fake_checkpoint(dirpath, tag="t", steps=5):
    os.makedirs(dirpath, exist_ok=True)
    for name, blob in (
        ("mp_rank_00_model_states.msgpack", b"model" * 100),
        ("zero_pp_rank_0_mp_rank_00optim_states.msgpack", b"optim" * 100),
    ):
        with open(os.path.join(dirpath, name), "wb") as f:
            f.write(blob)
    manifest.write_manifest(dirpath, tag, meta={"global_steps": steps})


def test_manifest_verify_valid(tmp_path):
    d = str(tmp_path / "t")
    _fake_checkpoint(d)
    status, reason = manifest.verify_checkpoint(d)
    assert status == manifest.VALID, reason
    m = json.load(open(os.path.join(d, manifest.MANIFEST_FILE)))
    assert set(m["files"]) == {
        "mp_rank_00_model_states.msgpack",
        "zero_pp_rank_0_mp_rank_00optim_states.msgpack",
    }
    assert m["global_steps"] == 5


def test_manifest_detects_truncation_and_bitflips(tmp_path):
    d = str(tmp_path / "t")
    _fake_checkpoint(d)
    f = os.path.join(d, "mp_rank_00_model_states.msgpack")
    blob = open(f, "rb").read()
    open(f, "wb").write(blob[: len(blob) // 2])
    status, reason = manifest.verify_checkpoint(d)
    assert status == manifest.CORRUPT and "size" in reason
    # same size, flipped byte: only the deep sha pass catches it
    open(f, "wb").write(bytes([blob[0] ^ 0xFF]) + blob[1:])
    status, reason = manifest.verify_checkpoint(d)
    assert status == manifest.CORRUPT and "sha256" in reason
    assert manifest.verify_checkpoint(d, deep=False)[0] == manifest.VALID


def test_manifest_detects_missing_file(tmp_path):
    d = str(tmp_path / "t")
    _fake_checkpoint(d)
    os.unlink(os.path.join(d, "zero_pp_rank_0_mp_rank_00optim_states.msgpack"))
    status, reason = manifest.verify_checkpoint(d)
    assert status == manifest.CORRUPT and "missing" in reason


def test_manifest_legacy_and_missing_verdicts(tmp_path):
    d = str(tmp_path / "legacy")
    _fake_checkpoint(d)
    os.unlink(os.path.join(d, manifest.MANIFEST_FILE))
    assert manifest.verify_checkpoint(d)[0] == manifest.LEGACY
    assert manifest.verify_checkpoint(str(tmp_path / "nope"))[0] == manifest.MISSING
    empty = tmp_path / "empty"
    empty.mkdir()
    assert manifest.verify_checkpoint(str(empty))[0] == manifest.MISSING


def test_manifest_malformed_json_is_corrupt(tmp_path):
    d = str(tmp_path / "t")
    _fake_checkpoint(d)
    open(os.path.join(d, manifest.MANIFEST_FILE), "w").write("{not json")
    assert manifest.verify_checkpoint(d)[0] == manifest.CORRUPT


def test_ordered_tags_survives_malformed_manifest_values(tmp_path):
    """One sibling tag with a parseable-but-malformed manifest (null
    global_steps, string created_unix) must degrade to mtime ordering,
    not crash the scan every later save/load runs."""
    _fake_checkpoint(str(tmp_path / "good"), tag="good", steps=3)
    bad = str(tmp_path / "bad")
    _fake_checkpoint(bad, tag="bad", steps=1)
    m = json.load(open(os.path.join(bad, manifest.MANIFEST_FILE)))
    m["global_steps"] = None
    m["created_unix"] = "yesterday"
    json.dump(m, open(os.path.join(bad, manifest.MANIFEST_FILE), "w"))
    tags = manifest.ordered_tags(str(tmp_path))
    assert set(tags) == {"good", "bad"}
    assert tags[0] == "good"  # steps=3 outranks the degraded entry


def test_ordered_tags_newest_first(tmp_path):
    for i, tag in enumerate(["a", "b", "c"]):
        _fake_checkpoint(str(tmp_path / tag), tag=tag, steps=i * 10)
    assert manifest.ordered_tags(str(tmp_path)) == ["c", "b", "a"]
    # files (e.g. `latest`) are not tags
    (tmp_path / "latest").write_text("c")
    assert manifest.ordered_tags(str(tmp_path)) == ["c", "b", "a"]


# ---------------------------------------------------------------------------
# retention GC
# ---------------------------------------------------------------------------
def test_retention_prunes_oldest_keeps_newest(tmp_path):
    for i in range(5):
        _fake_checkpoint(str(tmp_path / f"step{i}"), tag=f"step{i}", steps=i)
    (tmp_path / "latest").write_text("step4")
    deleted = retention.prune_checkpoints(str(tmp_path), keep_last_n=2)
    assert sorted(deleted) == ["step0", "step1", "step2"]
    assert sorted(p.name for p in tmp_path.iterdir() if p.is_dir()) == [
        "step3", "step4",
    ]


def test_retention_zero_keeps_everything(tmp_path):
    for i in range(3):
        _fake_checkpoint(str(tmp_path / f"step{i}"), steps=i)
    assert retention.prune_checkpoints(str(tmp_path), keep_last_n=0) == []
    assert len(list(tmp_path.iterdir())) == 3


def test_retention_never_deletes_newest_valid_or_latest_target(tmp_path):
    # newest two tags are corrupt; the only valid one is oldest AND is the
    # latest target — keep_last_n=1 must keep it and may drop the corrupt
    # newer ones
    _fake_checkpoint(str(tmp_path / "good"), tag="good", steps=0)
    for i, tag in enumerate(["bad1", "bad2"]):
        d = str(tmp_path / tag)
        _fake_checkpoint(d, tag=tag, steps=10 + i)
        os.unlink(os.path.join(d, "mp_rank_00_model_states.msgpack"))
    (tmp_path / "latest").write_text("good")
    retention.prune_checkpoints(str(tmp_path), keep_last_n=1)
    assert (tmp_path / "good").is_dir()


# ---------------------------------------------------------------------------
# preemption handler
# ---------------------------------------------------------------------------
def test_resolve_signals_rejects_unknown():
    assert resolve_signals(["SIGTERM", "SIGINT"]) == [
        signal.SIGTERM, signal.SIGINT,
    ]
    with pytest.raises(ValueError):
        resolve_signals(["SIGNOPE"])


def test_preemption_arms_on_signal_and_disarms():
    h = PreemptionHandler()
    assert not h.armed
    h._on_signal(signal.SIGTERM, None)  # handler body, no real delivery
    assert h.armed
    h.disarm()
    assert not h.armed


def test_preemption_second_signal_exits_immediately(monkeypatch):
    h = PreemptionHandler()
    kills = []
    monkeypatch.setattr(
        "deepspeed_tpu.resilience.preemption.os.kill",
        lambda pid, sig: kills.append((pid, sig)),
    )
    h._on_signal(signal.SIGTERM, None)
    assert h.armed and not kills
    h._on_signal(signal.SIGTERM, None)  # operator insists
    assert kills == [(os.getpid(), signal.SIGTERM)]
    assert not h.armed


def test_preemption_install_uninstall_restores_disposition():
    h = PreemptionHandler(signals=("SIGUSR1",))
    prev = signal.getsignal(signal.SIGUSR1)
    assert h.install()
    assert signal.getsignal(signal.SIGUSR1) == h._on_signal
    h.uninstall()
    assert signal.getsignal(signal.SIGUSR1) == prev


# ---------------------------------------------------------------------------
# config block
# ---------------------------------------------------------------------------
def _res_cfg(block):
    return DeepSpeedConfig(
        None,
        param_dict={"train_batch_size": 8, "resilience": block},
        world_size=1,
    )


def test_config_defaults():
    cfg = DeepSpeedConfig(
        None, param_dict={"train_batch_size": 8}, world_size=1
    )
    assert cfg.resilience_enabled is True
    assert cfg.resilience_fsync is True
    assert cfg.resilience_keep_last_n == 0
    assert cfg.resilience_retry_max_attempts == 3
    assert cfg.resilience_preemption_enabled is False
    assert cfg.resilience_preemption_signals == ["SIGTERM", "SIGINT"]


def test_config_rejects_bad_values():
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"keep_last_n": -1})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"keep_last_n": True})  # bool is not a count
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"retry": {"max_attempts": 0}})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"retry": {"backoff_base": 0}})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"retry": {"jitter": 2.0}})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"preemption": {"signals": ["SIGNOPE"]}})
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"preemption": {"signals": "SIGTERM"}})  # bare string
    with pytest.raises(DeepSpeedConfigError):
        _res_cfg({"preemption": {"tag_prefix": "a/b"}})


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------
def _make_engine(cfg_extra=None, seed=0):
    model = SimpleModel(hidden_dim=8)
    params = init_model(model, INPUT_DIM, seed=seed)
    cfg = config_dict(batch_size=8, lr=1e-2, zero_stage=1)
    cfg.update(cfg_extra or {})
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg
    )
    return engine


def _run_steps(engine, n=1, seed=0):
    bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
    x, y = random_dataset(bs * n, INPUT_DIM, seed=seed)
    for b in range(n):
        loss = engine(x[b * bs : (b + 1) * bs], y[b * bs : (b + 1) * bs])
        engine.backward(loss)
        engine.step()


def _snapshot(engine):
    return {
        "params": jax.tree_util.tree_map(
            lambda a: np.asarray(a).copy(), engine.params
        ),
        "opt": jax.tree_util.tree_map(
            lambda a: np.asarray(a).copy(), engine.optimizer_state
        ),
        "global_steps": engine.global_steps,
        "skipped_steps": engine.skipped_steps,
        "micro_steps": engine.micro_steps,
    }


def _assert_matches(engine, snap):
    for a, b in zip(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, engine.params)
        ),
        jax.tree_util.tree_leaves(snap["params"]),
    ):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, engine.optimizer_state)
        ),
        jax.tree_util.tree_leaves(snap["opt"]),
    ):
        np.testing.assert_array_equal(a, b)
    assert engine.global_steps == snap["global_steps"]
    assert engine.skipped_steps == snap["skipped_steps"]
    assert engine.micro_steps == snap["micro_steps"]


@pytest.fixture(scope="module")
def saved_pair(tmp_path_factory):
    """One engine advanced through two saves (tagA at step 1, tagB at
    step 2) plus bitwise snapshots of the engine state at each save —
    the corruption matrix copies this base tree per case."""
    base = tmp_path_factory.mktemp("ckpt_base")
    engine = _make_engine(seed=1)
    _run_steps(engine, n=1, seed=0)
    engine.save_checkpoint(str(base), tag="tagA")
    snap_a = _snapshot(engine)
    _run_steps(engine, n=1, seed=1)
    engine.save_checkpoint(str(base), tag="tagB")
    snap_b = _snapshot(engine)
    return str(base), snap_a, snap_b


@pytest.fixture(scope="module")
def loader_engine():
    """One reusable restore target (loads fully overwrite its state)."""
    return _make_engine(seed=7)


def _case_dir(tmp_path, saved_base):
    dst = str(tmp_path / "case")
    shutil.copytree(saved_base, dst)
    return dst


def test_save_writes_verified_manifest_and_latest(saved_pair):
    base, _, _ = saved_pair
    assert open(os.path.join(base, "latest")).read() == "tagB"
    for tag in ("tagA", "tagB"):
        status, reason = manifest.verify_checkpoint(os.path.join(base, tag))
        assert status == manifest.VALID, (tag, reason)
    m = json.load(
        open(os.path.join(base, "tagB", manifest.MANIFEST_FILE))
    )
    # model file + one shard per dp rank, all hashed
    assert len(m["files"]) == 1 + 8
    assert all(
        len(e["sha256"]) == 64 and e["size"] > 0 for e in m["files"].values()
    )


def test_clean_load_is_bitwise_identical(saved_pair, loader_engine):
    base, _, snap_b = saved_pair
    path, _ = loader_engine.load_checkpoint(base)
    assert path is not None
    _assert_matches(loader_engine, snap_b)


# ---- the corruption matrix ------------------------------------------------
def _corrupt_truncate(path):
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) // 3])


def test_corrupt_truncated_model_falls_back(tmp_path, saved_pair, loader_engine):
    base, snap_a, _ = saved_pair
    d = _case_dir(tmp_path, base)
    _corrupt_truncate(os.path.join(d, "tagB", "mp_rank_00_model_states.msgpack"))
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagA" in path
    _assert_matches(loader_engine, snap_a)
    snap = loader_engine.resilience.registry.snapshot()
    assert snap["resilience/corruption_fallbacks"] >= 1


def test_corrupt_missing_optim_shard_falls_back(tmp_path, saved_pair, loader_engine):
    base, snap_a, _ = saved_pair
    d = _case_dir(tmp_path, base)
    os.unlink(
        os.path.join(d, "tagB", "zero_pp_rank_3_mp_rank_00optim_states.msgpack")
    )
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagA" in path
    _assert_matches(loader_engine, snap_a)


def test_corrupt_truncated_optim_shard_falls_back(tmp_path, saved_pair, loader_engine):
    base, snap_a, _ = saved_pair
    d = _case_dir(tmp_path, base)
    _corrupt_truncate(
        os.path.join(d, "tagB", "zero_pp_rank_0_mp_rank_00optim_states.msgpack")
    )
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagA" in path
    _assert_matches(loader_engine, snap_a)


def test_latest_pointing_at_deleted_tag_falls_back(tmp_path, saved_pair, loader_engine):
    base, snap_a, _ = saved_pair
    d = _case_dir(tmp_path, base)
    shutil.rmtree(os.path.join(d, "tagB"))  # latest still says tagB
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagA" in path
    _assert_matches(loader_engine, snap_a)


def test_kill_between_shard_write_and_tag_publish(tmp_path, saved_pair, loader_engine):
    """A save killed after the shard writes but before the manifest/tag
    publish: the torn tagC directory exists with no manifest, `latest`
    still names tagB — the next load must resume tagB untouched."""
    base, _, snap_b = saved_pair
    d = _case_dir(tmp_path, base)
    torn = os.path.join(d, "tagC")
    shutil.copytree(os.path.join(d, "tagB"), torn)
    os.unlink(os.path.join(torn, manifest.MANIFEST_FILE))
    _corrupt_truncate(
        os.path.join(torn, "zero_pp_rank_7_mp_rank_00optim_states.msgpack")
    )
    path, _ = loader_engine.load_checkpoint(d)
    assert path is not None and "tagB" in path
    _assert_matches(loader_engine, snap_b)


def test_explicit_tag_never_silently_substitutes(tmp_path, saved_pair, loader_engine):
    base, _, _ = saved_pair
    d = _case_dir(tmp_path, base)
    _corrupt_truncate(os.path.join(d, "tagB", "mp_rank_00_model_states.msgpack"))
    path, client = loader_engine.load_checkpoint(d, tag="tagB")
    assert path is None and client == {}


def test_no_loadable_checkpoint_returns_none(tmp_path, saved_pair, loader_engine):
    base, _, _ = saved_pair
    d = _case_dir(tmp_path, base)
    for tag in ("tagA", "tagB"):
        _corrupt_truncate(
            os.path.join(d, tag, "mp_rank_00_model_states.msgpack")
        )
    snap_before = _snapshot(loader_engine)
    path, client = loader_engine.load_checkpoint(d)
    assert path is None and client == {}
    _assert_matches(loader_engine, snap_before)


# ---- partial-restore regression (ISSUE satellite) -------------------------
def test_partial_restore_leaves_engine_untouched(tmp_path, saved_pair, loader_engine):
    """Regression for the pre-resilience bug: load_checkpoint overwrote
    engine.params before optimizer shards were parsed, so a truncated
    shard raised mid-restore and left the engine half-loaded. The
    transactional load must leave EVERY engine field untouched when any
    file fails to parse — including on the legacy (manifest-less) path,
    where the failure only surfaces at msgpack parse time."""
    base, _, _ = saved_pair
    d = _case_dir(tmp_path, base)
    shutil.rmtree(os.path.join(d, "tagA"))  # no fallback candidate
    torn = os.path.join(d, "tagB")
    os.unlink(os.path.join(torn, manifest.MANIFEST_FILE))  # legacy path
    _corrupt_truncate(
        os.path.join(torn, "zero_pp_rank_2_mp_rank_00optim_states.msgpack")
    )
    snap_before = _snapshot(loader_engine)
    path, client = loader_engine.load_checkpoint(d)
    assert path is None and client == {}
    _assert_matches(loader_engine, snap_before)


# ---- crash sweep: kill at EVERY filesystem publish during save ------------
def test_save_crash_sweep_never_publishes_torn_checkpoint(
    tmp_path, saved_pair, loader_engine
):
    """Acceptance: a simulated crash at any point during save_checkpoint
    never leaves `latest` pointing at an incomplete checkpoint, and the
    next load resumes a valid tag with engine state bitwise-identical to
    that tag's save. Every checkpoint file (and the manifest and the
    `latest` pointer) publishes through atomic_io's os.replace — crashing
    at the k-th replace, for every k, covers every commit-order prefix."""
    base, snap_a, snap_b = saved_pair
    engine = _make_engine(seed=3)
    _run_steps(engine, n=1, seed=5)

    class SimulatedKill(BaseException):
        """Not an Exception: nothing on the save path may swallow it."""

    real_replace = atomic_io.os.replace
    # count the publish ops of one full save (model + dp shards +
    # manifest + latest) so the sweep tracks layout changes automatically
    probe_calls = {"n": 0}

    def counting_replace(src, dst):
        probe_calls["n"] += 1
        return real_replace(src, dst)

    atomic_io.os.replace = counting_replace
    try:
        engine.save_checkpoint(str(tmp_path / "probe"), tag="probe")
    finally:
        atomic_io.os.replace = real_replace
    n_ops = probe_calls["n"]
    assert n_ops == 1 + engine.dp_world_size + 1 + 1
    for k in range(n_ops):
        workdir = str(tmp_path / f"crash{k}")
        shutil.copytree(base, workdir)
        calls = {"n": 0}

        def crashing_replace(src, dst, _k=k, _calls=calls):
            if _calls["n"] == _k:
                raise SimulatedKill(f"killed at publish op {_k}")
            _calls["n"] += 1
            return real_replace(src, dst)

        atomic_io.os.replace = crashing_replace
        try:
            with pytest.raises(SimulatedKill):
                engine.save_checkpoint(workdir, tag="tagC")
        finally:
            atomic_io.os.replace = real_replace
        # latest must still name a COMPLETE checkpoint...
        latest = open(os.path.join(workdir, "latest")).read().strip()
        status, reason = manifest.verify_checkpoint(
            os.path.join(workdir, latest)
        )
        assert status == manifest.VALID, (k, latest, reason)
        assert latest == "tagB", (k, latest)
        # ...and the next load resumes it bitwise-identically
        path, _ = loader_engine.load_checkpoint(workdir)
        assert path is not None and latest in path, (k, path)
        _assert_matches(loader_engine, snap_b)
        shutil.rmtree(workdir)
    # the un-crashed save publishes tagC and becomes the resume point
    workdir = str(tmp_path / "clean")
    shutil.copytree(base, workdir)
    engine.save_checkpoint(workdir, tag="tagC")
    snap_c = _snapshot(engine)
    assert open(os.path.join(workdir, "latest")).read().strip() == "tagC"
    path, _ = loader_engine.load_checkpoint(workdir)
    assert path is not None and "tagC" in path
    _assert_matches(loader_engine, snap_c)


# ---- retry integration ----------------------------------------------------
def test_save_retries_transient_write_failures(tmp_path, saved_pair):
    engine = _make_engine(
        cfg_extra={
            "resilience": {
                "retry": {"max_attempts": 3, "backoff_base": 0.001}
            }
        },
        seed=2,
    )
    _run_steps(engine, n=1, seed=2)
    real_replace = atomic_io.os.replace
    fails = {"n": 2}  # first two publishes flake, then the mount recovers

    def flaky_replace(src, dst):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("transient fuse error")
        return real_replace(src, dst)

    atomic_io.os.replace = flaky_replace
    try:
        assert engine.save_checkpoint(str(tmp_path), tag="t") is True
    finally:
        atomic_io.os.replace = real_replace
    assert manifest.verify_checkpoint(str(tmp_path / "t"))[0] == manifest.VALID
    snap = engine.resilience.registry.snapshot()
    assert snap["resilience/io_retries"] == 2
    assert snap["resilience/save_time_ms/count"] == 1


# ---- retention integration ------------------------------------------------
def test_keep_last_n_prunes_after_save(tmp_path):
    engine = _make_engine(
        cfg_extra={"resilience": {"keep_last_n": 2}}, seed=4
    )
    _run_steps(engine, n=1, seed=3)
    for i in range(4):
        engine.save_checkpoint(str(tmp_path), tag=f"s{i}")
    dirs = sorted(p.name for p in tmp_path.iterdir() if p.is_dir())
    assert dirs == ["s2", "s3"]
    assert open(tmp_path / "latest").read() == "s3"
    snap = engine.resilience.registry.snapshot()
    assert snap["resilience/checkpoints_pruned"] == 2


# ---- preemption drain integration -----------------------------------------
def test_preemption_drain_saves_at_step_boundary(tmp_path):
    engine = _make_engine(
        cfg_extra={
            "resilience": {
                "preemption": {
                    "enabled": True,
                    "save_dir": str(tmp_path),
                    "exit_after_save": False,  # keep the test process alive
                }
            }
        },
        seed=5,
    )
    try:
        assert engine.resilience.preemption is not None
        _run_steps(engine, n=1, seed=4)
        assert not list(tmp_path.iterdir())  # unarmed: no drain save
        # a SIGTERM lands mid-window: the handler only arms a flag...
        engine.resilience.preemption._on_signal(signal.SIGTERM, None)
        assert engine.resilience.preemption_armed
        # ...and the next step boundary commits the final checkpoint
        _run_steps(engine, n=1, seed=6)
        tag = f"preempt_global_step{engine.global_steps}"
        status, reason = manifest.verify_checkpoint(str(tmp_path / tag))
        assert status == manifest.VALID, reason
        assert open(tmp_path / "latest").read() == tag
        assert not engine.resilience.preemption_armed  # disarmed after save
        snap = engine.resilience.registry.snapshot()
        assert snap["resilience/preemption_saves"] == 1
        # snapshot state matches the engine bitwise (resume-ready)
        loader = _make_engine(seed=6)
        loader.load_checkpoint(str(tmp_path))
        _assert_matches(loader, _snapshot(engine))
    finally:
        if engine.resilience.preemption is not None:
            engine.resilience.preemption.uninstall()


def test_preemption_exit_after_save_resignals(tmp_path, monkeypatch):
    engine = _make_engine(
        cfg_extra={
            "resilience": {
                "preemption": {"enabled": True, "save_dir": str(tmp_path)}
            }
        },
        seed=8,
    )
    kills = []
    monkeypatch.setattr(
        "deepspeed_tpu.resilience.preemption.os.kill",
        lambda pid, sig: kills.append(sig),
    )
    try:
        _run_steps(engine, n=1, seed=7)
        engine.resilience.preemption.arm(signal.SIGTERM)
        _run_steps(engine, n=1, seed=8)
        assert kills == [signal.SIGTERM]  # original disposition re-raised
        tag = f"preempt_global_step{engine.global_steps}"
        assert manifest.verify_checkpoint(str(tmp_path / tag))[0] == manifest.VALID
    finally:
        engine.resilience.preemption.uninstall()


def test_preemption_without_save_target_warns_not_crashes():
    engine = _make_engine(
        cfg_extra={
            "resilience": {
                "preemption": {"enabled": True, "exit_after_save": False}
            }
        },
        seed=9,
    )
    try:
        engine.resilience.preemption.arm()
        _run_steps(engine, n=1, seed=9)  # no save dir known: warns, trains on
        assert engine.global_steps == 1
    finally:
        engine.resilience.preemption.uninstall()


# ---- disabled resilience keeps the legacy write path -----------------------
def test_resilience_disabled_writes_bare_files(tmp_path):
    engine = _make_engine(
        cfg_extra={"resilience": {"enabled": False}}, seed=10
    )
    _run_steps(engine, n=1, seed=10)
    engine.save_checkpoint(str(tmp_path), tag="t")
    files = sorted(p.name for p in (tmp_path / "t").iterdir())
    assert manifest.MANIFEST_FILE not in files  # legacy layout
    assert any("model_states" in f for f in files)
    # and the legacy checkpoint still loads (as LEGACY, parse-validated)
    loader = _make_engine(seed=11)
    path, _ = loader.load_checkpoint(str(tmp_path))
    assert path is not None
    _assert_matches(loader, _snapshot(engine))


# ---- telemetry integration -------------------------------------------------
def test_resilience_shares_telemetry_registry(tmp_path):
    engine = _make_engine(
        cfg_extra={
            "telemetry": {
                "enabled": True,
                "output_path": str(tmp_path),
                "job_name": "job",
                "watchdog": {"enabled": False},
            }
        },
        seed=12,
    )
    try:
        assert engine.resilience.registry is engine.telemetry.registry
        _run_steps(engine, n=1, seed=12)
        engine.save_checkpoint(str(tmp_path / "ck"))
        engine.flush_monitor()
        lines = [
            json.loads(l)
            for l in open(
                tmp_path / "job" / "metrics.jsonl"
            ).read().splitlines()
        ]
        tags = {l["tag"] for l in lines}
        assert "resilience/io_retries" in tags
        assert "resilience/save_time_ms" in tags
    finally:
        engine.telemetry.close()


# ---------------------------------------------------------------------------
# fault-injection registry (resilience/faults.py)
# ---------------------------------------------------------------------------
def test_fault_injector_unknown_site_rejected():
    from deepspeed_tpu.resilience.faults import FaultSpec

    with pytest.raises(ValueError):
        FaultSpec("not.a.site")


def test_fault_injector_times_after_semantics():
    from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec

    inj = FaultInjector([FaultSpec("grads.nan", times=2, after=3)])
    fired = [inj.fire("grads.nan") is not None for _ in range(8)]
    # traversals 1-3 skipped (after), 4-5 fire (times=2), 6+ exhausted
    assert fired == [False, False, False, True, True, False, False, False]
    assert inj.injected["grads.nan"] == 2


def test_fault_injector_probability_is_seed_deterministic():
    from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec

    def pattern(seed):
        inj = FaultInjector(
            [FaultSpec("decode.step", times=0, probability=0.5, seed=seed)],
            seed=seed,
        )
        return [inj.fire("decode.step") is not None for _ in range(64)]

    a, b = pattern(7), pattern(7)
    assert a == b  # same seed => identical firing traversals
    assert any(a) and not all(a)  # probability actually thins the firings
    assert pattern(8) != a  # a different seed moves them


def test_fault_injector_raises_site_canonical_exception():
    from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec

    inj = FaultInjector([
        FaultSpec("checkpoint.write"), FaultSpec("staging.worker"),
    ])
    with pytest.raises(OSError):
        inj.maybe_raise("checkpoint.write")
    with pytest.raises(RuntimeError):
        inj.maybe_raise("staging.worker")
    # exhausted: subsequent traversals pass through clean
    inj.maybe_raise("checkpoint.write")


def test_null_injector_is_inert():
    from deepspeed_tpu.resilience.faults import NULL_INJECTOR

    assert NULL_INJECTOR.enabled is False
    assert NULL_INJECTOR.fire("grads.nan") is None
    NULL_INJECTOR.maybe_raise("checkpoint.write")  # no-op


# ---------------------------------------------------------------------------
# suppressed-error audit (no silent swallows)
# ---------------------------------------------------------------------------
def test_count_suppressed_increments_diagnostics_registry():
    from deepspeed_tpu.telemetry.registry import (
        count_suppressed,
        diagnostics_registry,
    )

    before = diagnostics_registry().counter(
        "internal/suppressed_errors"
    ).value
    count_suppressed("test.site", RuntimeError("boom"))
    snap = diagnostics_registry().snapshot()
    assert snap["internal/suppressed_errors"] == before + 1
    assert snap["internal/suppressed_errors/test.site"] >= 1


def test_compile_cache_disarm_failure_is_counted_not_silent(monkeypatch):
    import jax as _jax

    from deepspeed_tpu.runtime import compile_cache
    from deepspeed_tpu.telemetry.registry import diagnostics_registry

    compile_cache._armed = ("/tmp/x", 0.0)
    monkeypatch.setattr(
        _jax.config, "update",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("nope")),
    )
    before = diagnostics_registry().counter(
        "internal/suppressed_errors"
    ).value
    compile_cache.disarm_compile_cache()  # must not raise
    assert compile_cache._armed is None
    assert diagnostics_registry().counter(
        "internal/suppressed_errors"
    ).value > before


# ---------------------------------------------------------------------------
# self-healing run supervision (resilience/supervisor.py)
# ---------------------------------------------------------------------------
from deepspeed_tpu.resilience import (  # noqa: E402
    ReplayableDataSource,
    SupervisorEscalation,
)


def _chaos_factory(micro=8, dim=INPUT_DIM, base_seed=20_000):
    """Deterministic micro-batch stream: batch i is a pure function of
    (base_seed, i), so any start offset replays bitwise."""
    def factory(start):
        def gen(i):
            while True:
                r = np.random.default_rng(base_seed + i)
                x = r.normal(size=(micro, dim)).astype(np.float32)
                y = r.integers(0, 10, micro).astype(np.int32)
                yield (x, y)
                i += 1

        return gen(start)

    return factory


def _supervised_engine(faults, seed=0, max_rollbacks=2, staging=False,
                       nonfinite_window=1):
    extra = {
        "resilience": {
            "supervisor": {
                "enabled": True,
                "nonfinite_window": nonfinite_window,
                "max_rollbacks": max_rollbacks,
            },
            "fault_injection": {"enabled": bool(faults), "faults": faults}
            if faults else {},
        },
    }
    if staging:
        extra["data_pipeline"] = {"enabled": True, "staging_buffers": 2}
    return _make_engine(cfg_extra=extra, seed=seed)


def test_replayable_source_rewinds_bitwise():
    src = ReplayableDataSource(_chaos_factory())
    first = [next(src) for _ in range(4)]
    assert src.position == 4
    src.rewind(1)
    replay = [next(src) for _ in range(3)]
    for (xa, ya), (xb, yb) in zip(first[1:], replay):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


@pytest.mark.parametrize("site,staging", [
    ("grads.nan", False),
    ("grads.nan", True),
    ("staging.worker", True),
    ("staging.device_put", True),
])
def test_injected_fault_self_heals_bitwise(tmp_path, site, staging):
    """Chaos matrix core: an injected fault after the commit point either
    poisons a window (grads.nan) or kills the input pipeline
    (staging.*); the supervisor rolls back to the checkpoint, rewinds
    the data/RNG chain, and the run completes BITWISE-identical to an
    uninjected replay from the same checkpoint."""
    factory = _chaos_factory()
    engine = _supervised_engine(
        [{"site": site, "after": 3, "times": 1}], seed=3, staging=staging,
    )
    src = ReplayableDataSource(factory)
    losses = [float(engine.train_batch(src)) for _ in range(2)]
    engine.save_checkpoint(str(tmp_path))
    losses += [float(engine.train_batch(src)) for _ in range(4)]
    engine.close_data_pipeline()
    assert all(np.isfinite(losses)), losses
    snap = engine.resilience.registry.snapshot()
    assert snap["resilience/rollbacks"] == 1
    assert snap["resilience/anomalies"] == 1
    assert snap["resilience/faults_injected"] == 1

    # uninjected replay from the same checkpoint: bitwise-identical
    replay = _make_engine(seed=9)
    path, _ = replay.load_checkpoint(str(tmp_path))
    assert path is not None
    src2 = ReplayableDataSource(factory, start=replay.micro_steps)
    n_replay = engine.global_steps - replay.global_steps
    assert n_replay > 0
    for _ in range(n_replay):
        float(replay.train_batch(src2))
    for a, b in zip(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, engine.params)
        ),
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, replay.params)
        ),
    ):
        np.testing.assert_array_equal(a, b)
    assert engine.global_steps == replay.global_steps
    assert engine.micro_steps == replay.micro_steps


def test_persistent_fault_escalates_with_typed_error(tmp_path):
    """times=0 (unlimited) grads.nan: every replayed window re-poisons,
    so the retry budget drains and the supervisor escalates with the
    typed terminal error instead of looping forever."""
    engine = _supervised_engine(
        [{"site": "grads.nan", "after": 2, "times": 0}],
        seed=4, max_rollbacks=1,
    )
    src = ReplayableDataSource(_chaos_factory())
    float(engine.train_batch(src))
    engine.save_checkpoint(str(tmp_path))
    float(engine.train_batch(src))  # traversal 2: still clean
    with pytest.raises(SupervisorEscalation) as exc_info:
        for _ in range(4):
            float(engine.train_batch(src))
    assert exc_info.value.rollbacks == 1
    assert "budget" in str(exc_info.value)


def test_anomaly_without_checkpoint_escalates(tmp_path):
    """No committed checkpoint => nothing to roll back to: the first
    anomaly escalates immediately (typed), never hangs or corrupts."""
    engine = _supervised_engine(
        [{"site": "grads.nan", "after": 0, "times": 1}], seed=5,
    )
    src = ReplayableDataSource(_chaos_factory())
    with pytest.raises(SupervisorEscalation):
        float(engine.train_batch(src))


def test_stall_escalation_rolls_back_at_next_boundary(tmp_path):
    engine = _supervised_engine([], seed=6)
    src = ReplayableDataSource(_chaos_factory())
    float(engine.train_batch(src))
    engine.save_checkpoint(str(tmp_path))
    engine.supervisor.notify_stall(waited=123.0, last_step=1)
    # boundary after the stall: rollback to step 1, then the retried
    # window completes inside the same call -> step 2
    float(engine.train_batch(src))
    assert engine.supervisor.rollbacks == 1
    assert engine.global_steps == 2
    float(engine.train_batch(src))  # and the run keeps going
    assert engine.global_steps == 3


def test_watchdog_stall_listener_fires():
    from deepspeed_tpu.telemetry.watchdog import StepHeartbeatWatchdog

    clock = {"t": 0.0}
    seen = []
    wd = StepHeartbeatWatchdog(timeout=10.0, clock=lambda: clock["t"])
    wd.add_stall_listener(lambda waited, step: seen.append((waited, step)))
    wd.beat(step=3)
    clock["t"] = 11.0
    assert wd.check() is True
    assert seen and seen[0][1] == 3


def test_step_stall_fault_sleeps_and_run_completes(tmp_path):
    engine = _supervised_engine(
        [{"site": "step.stall", "times": 1, "args": {"duration_ms": 30}}],
        seed=7,
    )
    src = ReplayableDataSource(_chaos_factory())
    import time as _time

    t0 = _time.monotonic()
    losses = [float(engine.train_batch(src)) for _ in range(2)]
    assert _time.monotonic() - t0 >= 0.03
    assert all(np.isfinite(losses))
    assert engine.resilience.faults.injected["step.stall"] == 1


def test_spike_detector_triggers_rollback(monkeypatch):
    """Unit-level: a finite loss far above the rolling mean is an anomaly
    once min_history is met (rollback mocked — the trigger is the
    contract under test)."""
    from deepspeed_tpu.resilience.supervisor import TrainingSupervisor

    sup = TrainingSupervisor(
        spike_factor=3.0, spike_window=8, min_history=4, nonfinite_window=10,
    )
    calls = []
    monkeypatch.setattr(
        sup, "rollback", lambda engine, reason: calls.append(reason)
    )

    class FakeEngine:
        _last_grad_norm = 0.5

    eng = FakeEngine()
    for _ in range(5):
        assert sup.on_window(eng, 1.0) is False
    assert sup.on_window(eng, 10.0) is True  # > 3x rolling mean of 1.0
    assert calls and "spike" in calls[0]


def test_consecutive_nonfinite_budget(monkeypatch):
    from deepspeed_tpu.resilience.supervisor import TrainingSupervisor

    sup = TrainingSupervisor(nonfinite_window=3)
    calls = []
    monkeypatch.setattr(
        sup, "rollback", lambda engine, reason: calls.append(reason)
    )

    class FakeEngine:
        _last_grad_norm = 0.5

    eng = FakeEngine()
    assert sup.on_window(eng, float("nan")) is False
    assert sup.on_window(eng, 1.0) is False  # recovery resets the count
    assert sup.on_window(eng, float("nan")) is False
    assert sup.on_window(eng, float("inf")) is False
    assert sup.on_window(eng, float("nan")) is True  # 3 consecutive
    # the -1.0 grad-norm sentinel (device-side skip) also counts as bad
    sup2 = TrainingSupervisor(nonfinite_window=1)
    monkeypatch.setattr(
        sup2, "rollback", lambda engine, reason: calls.append(reason)
    )

    class SkippedEngine:
        _last_grad_norm = -1.0

    assert sup2.on_window(SkippedEngine(), 1.0) is True


def test_checkpoint_read_fault_during_rollback_is_retried(tmp_path):
    """Chaos on the healer itself: a transient read flake during the
    rollback's verified load is absorbed by retry backoff — the rollback
    still lands."""
    engine = _supervised_engine(
        [
            {"site": "grads.nan", "after": 2, "times": 1},
            {"site": "checkpoint.read", "times": 1},
        ],
        seed=8,
    )
    src = ReplayableDataSource(_chaos_factory())
    float(engine.train_batch(src))
    engine.save_checkpoint(str(tmp_path))
    losses = [float(engine.train_batch(src)) for _ in range(3)]
    assert all(np.isfinite(losses))
    snap = engine.resilience.registry.snapshot()
    assert snap["resilience/rollbacks"] == 1
    assert snap["resilience/io_retries"] >= 1


def test_checkpoint_rng_key_roundtrip(tmp_path):
    """Checkpoints persist the RNG key chain: a fresh engine (different
    seed) that loads one adopts the saved chain exactly — the resume
    splits the keys the original run would have."""
    engine = _make_engine(seed=21)
    _run_steps(engine, n=1, seed=21)
    engine.save_checkpoint(str(tmp_path))
    other = _make_engine(seed=99)
    assert not np.array_equal(
        np.asarray(other._rng), np.asarray(engine._rng)
    )
    other.load_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(
        np.asarray(other._rng), np.asarray(engine._rng)
    )


def test_ragged_window_error_is_not_healed(tmp_path):
    """Dataset exhaustion mid-window is the caller's sizing bug: the
    supervisor must surface the ragged-window error, not roll back and
    re-train old windows until its budget drains."""
    engine = _supervised_engine([], seed=30)
    bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size

    class Finite:
        """2.5 windows of data with accum=2: ends mid-window."""
        def __init__(self):
            self.n = 0
        def __iter__(self):
            return self
        def __next__(self):
            if self.n >= 5:
                raise StopIteration
            self.n += 1
            r = np.random.default_rng(self.n)
            return (r.normal(size=(bs, INPUT_DIM)).astype(np.float32),
                    r.integers(0, 10, bs).astype(np.int32))
        def rewind(self, position):  # rewindable, so rollback WOULD engage
            self.n = position

    # force accum=2 semantics via the unstaged list-window path: pull 2
    # micro-batches per train_batch call by overriding accum
    engine.config.gradient_accumulation_steps = 2
    src = Finite()
    float(engine.train_batch(src))
    engine.save_checkpoint(str(tmp_path))
    float(engine.train_batch(src))
    with pytest.raises(RuntimeError, match="ran dry mid-window"):
        engine.train_batch(src)
    assert engine.supervisor.rollbacks == 0  # never tried to heal this


# ---------------------------------------------------------------------------
# serving-seam fault sites (resilience/faults.py additions, PR 10):
# line mangling for the rpc.* pipe sites + the dict-form builder
# ---------------------------------------------------------------------------
def test_serving_sites_registered_and_validated():
    from deepspeed_tpu.resilience.faults import (
        KNOWN_FAULT_SITES,
        RPC_FAULT_MODES,
        FaultSpec,
    )

    for site in ("rpc.send", "rpc.recv", "replica.hang", "replica.flap",
                 "router.place", "snapshot.stale"):
        assert site in KNOWN_FAULT_SITES
        FaultSpec(site)  # constructible
    assert RPC_FAULT_MODES == ("drop", "corrupt", "delay")
    # the config validator rejects a typo'd rpc mode (it must not
    # silently mean "drop")
    with pytest.raises(DeepSpeedConfigError, match="args.mode"):
        DeepSpeedConfig(None, param_dict={
            "train_batch_size": 8,
            "resilience": {"fault_injection": {
                "enabled": True,
                "faults": [{"site": "rpc.send",
                            "args": {"mode": "garble"}}],
            }},
        }, world_size=1)


def test_mangle_line_modes():
    import time as _time

    from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec

    line = '{"op": "submit", "id": 7}'
    # drop
    inj = FaultInjector(
        [FaultSpec("rpc.send", times=1, args={"mode": "drop"}, seed=0)],
        seed=0,
    )
    assert inj.mangle_line("rpc.send", line) is None
    assert inj.mangle_line("rpc.send", line) == line  # spec exhausted
    assert inj.injected["rpc.send"] == 1
    # corrupt: undecodable as JSON, original prefix preserved for logs
    inj = FaultInjector(
        [FaultSpec("rpc.send", times=1, args={"mode": "corrupt"}, seed=0)],
        seed=0,
    )
    corrupted = inj.mangle_line("rpc.send", line)
    assert corrupted is not None and corrupted != line
    with pytest.raises(ValueError):
        json.loads(corrupted)
    # delay: returns the line intact, late
    inj = FaultInjector(
        [FaultSpec("rpc.recv", times=1,
                   args={"mode": "delay", "delay_ms": 50}, seed=0)],
        seed=0,
    )
    t0 = _time.monotonic()
    assert inj.mangle_line("rpc.recv", line) == line
    assert _time.monotonic() - t0 >= 0.045
    # unknown mode raises loudly at fire time (the config validator
    # catches it earlier on the config path)
    inj = FaultInjector(
        [FaultSpec("rpc.send", times=1, args={"mode": "zap"}, seed=0)],
        seed=0,
    )
    with pytest.raises(ValueError, match="unknown rpc fault mode"):
        inj.mangle_line("rpc.send", line)


def test_mangle_line_probabilistic_determinism():
    """Same (seed, site) => the same traversals are mangled — a chaos
    failure on the pipe reproduces byte-for-byte."""
    from deepspeed_tpu.resilience.faults import FaultInjector, FaultSpec

    def pattern(seed):
        inj = FaultInjector(
            [FaultSpec("rpc.recv", times=0, probability=0.4,
                       args={"mode": "drop"}, seed=seed)],
            seed=seed,
        )
        return [
            inj.mangle_line("rpc.recv", f"line-{i}") is None
            for i in range(40)
        ]

    first = pattern(seed=11)
    assert first == pattern(seed=11)
    assert any(first) and not all(first)  # 0.4: some dropped, some not
    assert first != pattern(seed=12)  # a different seed moves the draws


def test_build_fault_injector_from_dict():
    from deepspeed_tpu.resilience.faults import (
        NULL_INJECTOR,
        build_fault_injector_from_dict,
    )

    assert build_fault_injector_from_dict(None) is NULL_INJECTOR
    assert build_fault_injector_from_dict({"enabled": False}) is NULL_INJECTOR
    assert build_fault_injector_from_dict(
        {"enabled": True, "faults": []}
    ) is NULL_INJECTOR
    inj = build_fault_injector_from_dict({
        "enabled": True, "seed": 3,
        "faults": [{"site": "replica.hang", "times": 2,
                    "args": {"duration_ms": 5}}],
    })
    assert inj.enabled
    assert inj.maybe_stall("replica.hang") is True
    assert inj.maybe_stall("replica.hang") is True
    assert inj.maybe_stall("replica.hang") is False  # times exhausted
    assert inj.injected["replica.hang"] == 2
