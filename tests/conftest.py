"""Test harness configuration.

Forces JAX onto the host CPU platform with 8 virtual devices BEFORE jax is
imported anywhere, so every test exercises real multi-device sharding and
collectives without TPU hardware (the analog of the reference's
@distributed_test process spawner, tests/unit/common.py:14-100 — but using
XLA's simulated multi-device instead of forked NCCL processes).
"""

import os

# Force CPU even when the outer environment points at a TPU platform —
# unit tests must exercise the virtual 8-device mesh deterministically.
# NOTE: jax may already be imported by a sitecustomize hook, so setting the
# env var alone is not enough; jax.config.update works as long as no backend
# has been initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_config_file(tmp_path):
    """Write a config dict to a temp JSON file, return its path."""
    import json

    def _write(config_dict, name="ds_config.json"):
        path = tmp_path / name
        path.write_text(json.dumps(config_dict))
        return str(path)

    return _write
