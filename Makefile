# Test tiers (the reference splits pytest unit tests from unittest
# model-scale suites; here the split is a pytest marker — SURVEY.md §4).
#
#   make test-fast   fast core (< ~2 min): config, launcher, schedules,
#                    loss scaling, CSR, ZeRO specs, skip accounting, ...
#   make test        everything, including compile-heavy model-scale suites
#                    (~15-20 min on 8 virtual CPU devices)

PYTEST ?= python -m pytest

test-fast:
	$(PYTEST) tests/ -q -m "not slow"

test:
	$(PYTEST) tests/ -q

.PHONY: test test-fast
