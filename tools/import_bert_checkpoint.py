"""Import a pretrained torch/HF BERT checkpoint into the repo's layout.

The reference's SQuAD quality gate starts from a pretrained BERT
(reference: tests/model/BingBertSquad/test_e2e_squad.py:40-58 — EM 83.98 /
F1 90.71 is only reachable from pretrained weights). This tool produces
the `$BERT_CKPT_MSGPACK` artifact that tests/model/test_squad_real_data.py
consumes, from any of:

  - a HuggingFace model directory (``pytorch_model.bin`` inside), or
  - a bare ``state_dict`` file saved by torch (``.bin``/``.pt``), with or
    without a wrapping ``{"model": ...}``/``{"module": ...}`` key.

Layout translation (torch Linear stores ``[out, in]``; our block applies
``x @ W`` with ``[in, out]`` — every dense weight transposes):

  HF ``bert.encoder.layer.{i}.attention.self.{query,key,value}``
    -> ``attn_qkvw`` [layers, H, 3H] (transposed, concatenated) / ``attn_qkvb``
  HF ``attention.output.dense``        -> ``attn_ow``/``attn_ob``
  HF ``attention.output.LayerNorm``    -> ``attn_nw``/``attn_nb``
  HF ``intermediate.dense``            -> ``inter_w``/``inter_b``
  HF ``output.dense``                  -> ``output_w``/``output_b``
  HF ``output.LayerNorm``              -> ``norm_w``/``norm_b``

The per-layer tensors stack along a leading ``layers`` axis (the
``nn.scan`` layout of models/bert.py BertEncoder). The vocabulary pads up
to a multiple of 128 (MXU tiling, models/bert.py:105): embedding rows pad
with zeros and the MLM bias pads with -1e30, so padded tokens contribute
exp(-1e30)=0 to every softmax — logits over REAL tokens are bit-identical
to the unpadded model.

Usage:
  python tools/import_bert_checkpoint.py CKPT_OR_DIR -o bert_large.msgpack \
      --head qa            # qa | pretraining | none
"""

import argparse
import os
import re
import sys

import numpy as np

VOCAB_ALIGN = 128
MLM_PAD_BIAS = -1e30


def _round_up(x, m):
    return (x + m - 1) // m * m


def load_torch_state_dict(path):
    """Load a state_dict from a file or HF model directory; returns
    {name: np.ndarray (f32)}."""
    import torch

    if os.path.isdir(path):
        for fname in ("pytorch_model.bin", "model.pt", "model.bin"):
            cand = os.path.join(path, fname)
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise FileNotFoundError(f"no pytorch_model.bin under {path}")
    try:
        sd = torch.load(path, map_location="cpu", weights_only=True)
    except TypeError:  # older torch without weights_only
        sd = torch.load(path, map_location="cpu")
    for wrapper in ("model", "module", "state_dict"):
        if isinstance(sd, dict) and wrapper in sd and isinstance(
            sd[wrapper], dict
        ):
            sd = sd[wrapper]
    return {
        k: v.detach().to(torch.float32).numpy()
        for k, v in sd.items()
        if hasattr(v, "detach")
    }


def _normalize_keys(sd):
    """Strip common prefixes, fold pre-HF naming (gamma/beta) into
    weight/bias, and coerce values (torch tensors or arrays) to f32
    numpy so one mapping serves both checkpoint generations."""
    out = {}
    for k, v in sd.items():
        # DataParallel/DDP saves prepend "module." (possibly nested);
        # strip all of them, THEN one optional "bert." scope
        k = re.sub(r"^(module\.)+", "", k)
        k = re.sub(r"^bert\.", "", k, count=1)
        k = k.replace(".gamma", ".weight").replace(".beta", ".bias")
        if hasattr(v, "detach"):  # torch tensor
            v = v.detach().cpu().to_dense() if v.is_sparse else v.detach().cpu()
            v = v.float().numpy()
        out[k] = np.asarray(v)
    return out


def _get(sd, key):
    if key not in sd:
        raise KeyError(
            f"checkpoint is missing {key!r}; keys look like: "
            f"{sorted(sd)[:8]} ..."
        )
    return sd[key]


def convert_state_dict(sd, head="qa", dtype=np.float32):
    """torch/HF BERT ``state_dict`` -> this repo's flax param tree
    (models/bert.py BertForQuestionAnswering / BertForPreTraining).

    Infers H / layers / intermediate / vocab from tensor shapes; returns
    (params, inferred_config_dict).
    """
    sd = _normalize_keys(sd)
    word = _get(sd, "embeddings.word_embeddings.weight")
    vocab, H = word.shape
    layer_ids = sorted({
        int(m.group(1))
        for k in sd
        if (m := re.match(r"encoder\.layer\.(\d+)\.", k))
    })
    if not layer_ids or layer_ids != list(range(len(layer_ids))):
        raise ValueError(f"unexpected encoder layer numbering: {layer_ids}")
    L = len(layer_ids)
    inter = _get(sd, "encoder.layer.0.intermediate.dense.weight").shape[0]

    def stack(fmt, transpose=False):
        ts = [_get(sd, fmt.format(i)) for i in range(L)]
        if transpose:
            ts = [t.T for t in ts]
        return np.stack(ts).astype(dtype)

    qkvw = np.stack([
        np.concatenate(
            [
                _get(sd, f"encoder.layer.{i}.attention.self.{part}.weight").T
                for part in ("query", "key", "value")
            ],
            axis=1,
        )
        for i in range(L)
    ]).astype(dtype)  # [L, H, 3H]
    qkvb = np.stack([
        np.concatenate(
            [
                _get(sd, f"encoder.layer.{i}.attention.self.{part}.bias")
                for part in ("query", "key", "value")
            ]
        )
        for i in range(L)
    ]).astype(dtype)

    layer = {
        "attn_qkvw": qkvw,
        "attn_qkvb": qkvb,
        "attn_ow": stack(
            "encoder.layer.{}.attention.output.dense.weight", transpose=True
        ),
        "attn_ob": stack("encoder.layer.{}.attention.output.dense.bias"),
        "attn_nw": stack(
            "encoder.layer.{}.attention.output.LayerNorm.weight"
        ).astype(np.float32),
        "attn_nb": stack(
            "encoder.layer.{}.attention.output.LayerNorm.bias"
        ).astype(np.float32),
        "inter_w": stack(
            "encoder.layer.{}.intermediate.dense.weight", transpose=True
        ),
        "inter_b": stack("encoder.layer.{}.intermediate.dense.bias"),
        "output_w": stack(
            "encoder.layer.{}.output.dense.weight", transpose=True
        ),
        "output_b": stack("encoder.layer.{}.output.dense.bias"),
        "norm_w": stack(
            "encoder.layer.{}.output.LayerNorm.weight"
        ).astype(np.float32),
        "norm_b": stack(
            "encoder.layer.{}.output.LayerNorm.bias"
        ).astype(np.float32),
    }

    vocab_padded = _round_up(vocab, VOCAB_ALIGN)
    word_padded = np.zeros((vocab_padded, H), dtype)
    word_padded[:vocab] = word.astype(dtype)

    bert = {
        "embeddings": {
            "word_embeddings": word_padded,
            "position_embeddings": _get(
                sd, "embeddings.position_embeddings.weight"
            ).astype(dtype),
            "token_type_embeddings": _get(
                sd, "embeddings.token_type_embeddings.weight"
            ).astype(dtype),
            "LayerNorm": {
                "scale": _get(sd, "embeddings.LayerNorm.weight").astype(
                    np.float32
                ),
                "bias": _get(sd, "embeddings.LayerNorm.bias").astype(
                    np.float32
                ),
            },
        },
        "encoder": {"layer": layer},
        # HF QA checkpoints ship without a pooler (add_pooling_layer=False);
        # our BertModel always declares one (the NSP head needs it) — zeros
        # keep the tree complete and the QA path never reads it
        "pooler": {
            "kernel": (
                sd["pooler.dense.weight"].T.astype(dtype)
                if "pooler.dense.weight" in sd
                else np.zeros((H, H), dtype)
            ),
            "bias": (
                sd["pooler.dense.bias"].astype(dtype)
                if "pooler.dense.bias" in sd
                else np.zeros((H,), dtype)
            ),
        },
    }

    params = {"bert": bert}
    if head == "qa":
        if "qa_outputs.weight" in sd:
            params["qa_outputs"] = {
                "kernel": sd["qa_outputs.weight"].T.astype(dtype),
                "bias": sd["qa_outputs.bias"].astype(dtype),
            }
        # else: leave the head to the caller's fresh init (fine-tuning
        # from a pretraining-only checkpoint re-initializes the QA head)
    elif head == "pretraining":
        params["transform"] = {
            "kernel": _get(
                sd, "cls.predictions.transform.dense.weight"
            ).T.astype(dtype),
            "bias": _get(sd, "cls.predictions.transform.dense.bias").astype(
                dtype
            ),
        }
        params["transform_ln"] = {
            "scale": _get(
                sd, "cls.predictions.transform.LayerNorm.weight"
            ).astype(np.float32),
            "bias": _get(
                sd, "cls.predictions.transform.LayerNorm.bias"
            ).astype(np.float32),
        }
        mlm_bias = np.full((vocab_padded,), MLM_PAD_BIAS, np.float32)
        mlm_bias[:vocab] = _get(sd, "cls.predictions.bias").astype(np.float32)
        params["mlm_bias"] = mlm_bias
        params["nsp"] = {
            "kernel": _get(sd, "cls.seq_relationship.weight").T.astype(dtype),
            "bias": _get(sd, "cls.seq_relationship.bias").astype(dtype),
        }
    elif head != "none":
        raise ValueError(f"unknown head {head!r} (qa|pretraining|none)")

    cfg = {
        "vocab_size": int(vocab),
        "hidden_size": int(H),
        "num_hidden_layers": int(L),
        "num_attention_heads": int(H // 64),  # BERT convention: head dim 64
        "intermediate_size": int(inter),
        "max_position_embeddings": int(
            bert["embeddings"]["position_embeddings"].shape[0]
        ),
        "type_vocab_size": int(
            bert["embeddings"]["token_type_embeddings"].shape[0]
        ),
    }
    return params, cfg


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("checkpoint", help="torch state_dict file or HF model dir")
    ap.add_argument("-o", "--output", required=True, help="output .msgpack")
    ap.add_argument(
        "--head", default="qa", choices=("qa", "pretraining", "none")
    )
    ap.add_argument(
        "--dtype", default="float32", choices=("float32", "bfloat16"),
        help="storage dtype for dense weights (LayerNorms stay fp32)",
    )
    args = ap.parse_args(argv)

    from flax import serialization
    import jax.numpy as jnp

    dtype = np.float32 if args.dtype == "float32" else jnp.bfloat16
    sd = load_torch_state_dict(args.checkpoint)
    params, cfg = convert_state_dict(sd, head=args.head, dtype=dtype)
    with open(args.output, "wb") as f:
        f.write(serialization.to_bytes(params))
    n = sum(
        int(np.prod(np.shape(leaf)))
        for leaf in _tree_leaves(params)
    )
    print(
        f"wrote {args.output}: {n / 1e6:.1f}M params, config {cfg}",
        file=sys.stderr,
    )
    return cfg


def _tree_leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _tree_leaves(v)
    else:
        yield tree


if __name__ == "__main__":
    main()
